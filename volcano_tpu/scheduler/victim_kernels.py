"""JAX victim-selection kernels for preempt/reclaim (SURVEY.md section 2.3
item 6): per-node masked sort + prefix-sum cover test as one device program.

The host loop in the reference walks nodes in score order and, per node,
filters resident Running tasks through the tiered preemptable/reclaimable
callbacks, then evicts in reverse task order until the preemptor's request
is covered (preempt.go:176-243, reclaim.go:115-180). One ``victim_step``
call computes that whole decision for one preemptor over ALL nodes at once:

  1. candidate mask over the [V] running tasks (mode filter + plugin vetoes),
  2. per-node eviction-order prefix sums of candidate requests,
  3. node eligibility = request covered + predicate class + pod-count cap,
  4. best node by the nodeorder score (first-max tie-break, same as host),
  5. functional state update (evictions -> releasing, preemptor pipelined).

``reclaim_solve`` and ``preempt_solve`` go one level further: they run the
ENTIRE reclaim/preempt action loop (the reference's per-queue priority-
queue walk, statement checkpoint/rollback, two-phase preemption) as one
device program — a ``lax.while_loop`` whose body selects the next
(queue, job, task) by the same ordering keys the host loop uses and runs
the victim core, so a 2,000-preemptor storm costs ONE dispatch + ONE
host round trip instead of 2,000 (the round-trip-per-preemptor driver was
round 3's 356 s contended cycle; see fast_victims.py).

Two batching devices make the storm loop cheap:

  * all sort orders are hoisted out of the loop.  The per-preemptor
    cumsums previously sorted by ``(~candidate, node, key...)``; a masked
    segment-cumsum over the STATIC ``(node, key...)`` order produces
    bit-identical prefix sums at candidate rows, because the interspersed
    zeros of non-candidates do not change partial sums.  No per-step
    [V]-sized sort remains.
  * the best-node walk is two lexicographic argmin reductions (covered
    and valid) instead of a positional sort of all nodes.

Veto fidelity notes:
  * gang: per-candidate check against the call-time occupied count, exactly
    like gang.go:71-94 (the count does NOT decrement within one call).
  * drf: the hypothetical allocation decrements for every candidate in
    iteration order whether or not the candidate is admitted — drf.go:86-117
    subtracts before testing — so the cumulative sums here are plain
    per-(node, job) prefix sums, veto-independent.
  * proportion: same shape per (node, queue) against deserved. Divergence:
    the host skips (without subtracting) a candidate whose queue allocation
    is already strictly below its request (proportion.go reclaimableFn's
    ``allocated.less(resreq)`` guard); this kernel subtracts unconditionally.
    The guard only fires when a queue's bookkeeping went negative — not
    reachable through the session seams.
  * A host node attempt that passes validateVictims but fails the final
    coverage check strands its evictions in the statement and moves on
    (preempt.go:176-243). The kernels detect that case and report
    ``clean=False`` instead of modeling it; the storm solves abort with
    nothing recorded and the caller replays the cycle through the object
    path, keeping exact parity.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from volcano_tpu.scheduler.kernels import (
    NEG_INF,
    POS_INF,
    _lex_argmin,
    _score_nodes,
    dominant_share,
    less_equal,
)

SHARE_DELTA = 1e-6
# one round's per-job proposal window in preempt_rounds; a gang whose
# remaining min-need exceeds it cannot satisfy the all-or-nothing commit
# and must take the exact loop (fast_victims gates on this)
ROUNDS_P_CHUNK = 32


class VictimConsts(NamedTuple):
    """Cycle-constant device arrays for victim selection."""

    run_req: jnp.ndarray        # [V, R] resreq of running tasks
    run_node: jnp.ndarray       # [V] i32 node index
    run_job: jnp.ndarray        # [V] i32 job index
    run_prio: jnp.ndarray       # [V] i32 task priority
    run_rank: jnp.ndarray       # [V] i32 uid rank (for reverse-uid ties)
    run_evictable: jnp.ndarray  # [V] bool conformance veto precomputed
    job_queue: jnp.ndarray      # [J] i32
    job_min: jnp.ndarray        # [J] i32
    node_alloc: jnp.ndarray     # [N, R]
    node_max_tasks: jnp.ndarray  # [N] i32
    node_valid: jnp.ndarray     # [N] bool
    class_mask: jnp.ndarray     # [C, N] bool
    class_score: jnp.ndarray    # [C, N] f32
    queue_deserved: jnp.ndarray  # [Q, R]
    total: jnp.ndarray          # [R]
    eps: jnp.ndarray            # [R]
    w_least: jnp.ndarray        # f32
    w_balanced: jnp.ndarray     # f32


class VictimState(NamedTuple):
    """Mutating session state mirrored on device; functionally updated per
    step and checkpointable for Statement rollback."""

    run_live: jnp.ndarray      # [V] bool not yet evicted
    idle: jnp.ndarray          # [N, R]
    releasing: jnp.ndarray     # [N, R]
    used: jnp.ndarray          # [N, R]
    task_count: jnp.ndarray    # [N] i32
    job_alloc: jnp.ndarray     # [J, R] drf allocated
    job_occupied: jnp.ndarray  # [J] i32 ready_task_num
    queue_alloc: jnp.ndarray   # [Q, R] proportion allocated


def _seg_cumsum(values, new_seg):
    """Inclusive prefix sums within runs delimited by ``new_seg`` flags."""
    n = values.shape[0]
    cum = jnp.cumsum(values, axis=0)
    start = jax.lax.cummax(jnp.where(new_seg, jnp.arange(n), 0))
    return cum - (cum[start] - values[start])


def _tree_where(pred, a, b):
    """Elementwise select over matching pytrees with a scalar predicate."""
    return jax.tree_util.tree_map(lambda x, y: jnp.where(pred, x, y), a, b)


# --------------------------------------------------------------------------
# static orderings (consts-only, hoisted out of the storm loops)
# --------------------------------------------------------------------------

def _orders_drf(c: VictimConsts):
    """(node, job, pool-index) order + segment-start flags for the DRF
    hypothetical-transfer cumsum."""
    V = c.run_req.shape[0]
    vidx = jnp.arange(V, dtype=jnp.int32)
    o = jnp.lexsort((vidx, c.run_job, c.run_node))
    sn, sj = c.run_node[o], c.run_job[o]
    seg = jnp.concatenate(
        [jnp.array([True]), (sn[1:] != sn[:-1]) | (sj[1:] != sj[:-1])]
    )
    return o, seg


def _orders_prop(c: VictimConsts, Q: int):
    """(node, queue, pool-index) order + segment flags for proportion."""
    V = c.run_req.shape[0]
    vidx = jnp.arange(V, dtype=jnp.int32)
    rq = jnp.clip(c.job_queue[c.run_job], 0, Q - 1)
    o = jnp.lexsort((vidx, rq, c.run_node))
    sn, sq = c.run_node[o], rq[o]
    seg = jnp.concatenate(
        [jnp.array([True]), (sn[1:] != sn[:-1]) | (sq[1:] != sq[:-1])]
    )
    return o, seg


def _orders_evict(c: VictimConsts, order_by_priority: bool,
                  reclaim_mode: bool):
    """Per-node eviction order: preempt drains a reversed-TaskOrderFn queue
    = (priority asc, uid desc) (preempt.go victimsQueue); reclaim evicts in
    candidate list order = node-resident insertion order (reclaim.go:154)."""
    V = c.run_req.shape[0]
    vidx = jnp.arange(V, dtype=jnp.int32)
    if reclaim_mode:
        o = jnp.lexsort((vidx, c.run_node))
    else:
        prio_key = (
            c.run_prio if order_by_priority else jnp.zeros((V,), jnp.int32)
        )
        o = jnp.lexsort((-c.run_rank, prio_key, c.run_node))
    sn = c.run_node[o]
    seg = jnp.concatenate([jnp.array([True]), sn[1:] != sn[:-1]])
    return o, seg


# --------------------------------------------------------------------------
# one preemptor's victim solve (the shared core)
# --------------------------------------------------------------------------

def _victim_core(
    c: VictimConsts,
    s: VictimState,
    t_req,            # [R] preemptor resreq
    t_cls,            # i32 predicate class
    jt,               # i32 preemptor job index
    qt,               # i32 preemptor queue index
    base,             # [V] bool preemptee list (mode filter, precomputed)
    o_drf=None, seg_drf=None,
    o_prop=None, seg_prop=None,
    o_ev=None, seg_ev=None,
    *,
    use_gang: bool,
    use_drf: bool,
    use_prop: bool,
    use_conformance: bool,
    reclaim_mode: bool,
):
    """Returns (new_state, assigned, node_index, victim_mask[V], clean).
    ``clean=False`` means the host walk would strand evictions on nodes
    that cannot cover the request; the returned state must be DISCARDED.
    The ``o_*``/``seg_*`` orders come from the ``_orders_*`` helpers and
    depend only on consts, so storm callers hoist them out of their loops.
    """
    V = c.run_req.shape[0]
    N = s.idle.shape[0]
    J = c.job_queue.shape[0]
    Q = s.queue_alloc.shape[0]

    rq_raw = c.job_queue[c.run_job]
    has_q = rq_raw >= 0
    run_q = jnp.clip(rq_raw, 0, Q - 1)

    # ``base`` is the preemptee list every plugin sees (the action's task
    # filter); each veto intersects into ``cand``, but the drf/proportion
    # hypothetical subtractions run over ALL of base — the host plugins
    # subtract every preemptee whether or not another plugin vetoes it
    cand = base
    if use_conformance:
        cand = cand & c.run_evictable
    if use_gang:
        occ = s.job_occupied[c.run_job]
        vmin = c.job_min[c.run_job]
        cand = cand & ((vmin <= occ - 1) | (vmin == 1))

    if use_drf:
        ls = dominant_share(s.job_alloc[jt] + t_req, c.total)
        sreq = jnp.where(base[o_drf, None], c.run_req[o_drf], 0.0)
        relcum = _seg_cumsum(sreq, seg_drf)
        rs = dominant_share(s.job_alloc[c.run_job[o_drf]] - relcum, c.total)
        admit_s = (ls < rs) | (jnp.abs(ls - rs) <= SHARE_DELTA)
        # scatter is only meaningful at base rows; cand is already a subset
        # of base, so garbage at non-base rows cannot admit anything
        cand = cand & jnp.zeros((V,), bool).at[o_drf].set(admit_s)

    if use_prop:
        # queueless rows don't join the hypothetical subtraction either
        # (the host's attr-None continue skips before the sub)
        sreq = jnp.where(
            (base & has_q)[o_prop, None], c.run_req[o_prop], 0.0
        )
        relcum = _seg_cumsum(sreq, seg_prop)
        sq = run_q[o_prop]
        alloc_after = s.queue_alloc[sq] - relcum
        # queueless victims have no proportion attr: the host skips them
        # (reclaimableFn's attr-None continue), so they are never admitted
        admit_s = (
            less_equal(c.queue_deserved[sq], alloc_after, c.eps)
            & has_q[o_prop]
        )
        cand = cand & jnp.zeros((V,), bool).at[o_prop].set(admit_s)

    # per-node eviction-order prefix sums.  The host loop is DO-while
    # shaped — it evicts a node's first victim BEFORE the cover check
    # (preempt.py:151-156 / reclaim.py:106-110), which only matters for an
    # empty-request preemptor (its request is covered by zero victims, yet
    # the host still takes exactly one) — so the first admitted candidate
    # of each node is in the prefix unconditionally.
    cand_s = cand[o_ev]
    s2req = jnp.where(cand_s[:, None], c.run_req[o_ev], 0.0)
    sn2 = c.run_node[o_ev]
    cum2 = _seg_cumsum(s2req, seg_ev)
    cum_excl = cum2 - s2req
    cand_cnt = _seg_cumsum(cand_s.astype(jnp.int32), seg_ev)
    first_cand = cand_s & (cand_cnt == 1)
    in_prefix_s = cand_s & (
        first_cand | ~less_equal(t_req[None, :], cum_excl, c.eps)
    )

    node_tgt = jnp.where(cand, c.run_node, N)
    node_tot = jax.ops.segment_sum(
        jnp.where(cand[:, None], c.run_req, 0.0), node_tgt, num_segments=N + 1
    )[:N]
    any_adm = (
        jax.ops.segment_sum(
            cand.astype(jnp.int32), node_tgt, num_segments=N + 1
        )[:N]
        > 0
    )
    pred_ok = (
        c.node_valid & c.class_mask[t_cls] & (s.task_count + 1 <= c.node_max_tasks)
    )
    # validateVictims (preempt.go:245): skip only when the victim total is
    # strictly below the request in EVERY dim
    validate = ~jnp.all(node_tot < t_req[None, :], axis=-1)
    valid_node = pred_ok & any_adm & validate
    covered = less_equal(t_req[None, :], node_tot, c.eps) & valid_node

    score = _score_nodes(
        t_req, s.used, c.node_alloc, c.class_score[t_cls], c.w_least, c.w_balanced
    )
    # walk order: preempt visits nodes best-score-first (stable on ties,
    # preempt.go sortNodes); reclaim visits in snapshot order (reclaim.go
    # iterates ssn.Nodes directly).  The first covered / first valid nodes
    # of that walk are lexicographic argmins over (walk_key, index) — no
    # positional sort needed.
    nidx = jnp.arange(N, dtype=jnp.int32)
    if reclaim_mode:
        walk_key = nidx.astype(jnp.float32)
    else:
        walk_key = -score
    kmin_cov = jnp.min(jnp.where(covered, walk_key, POS_INF))
    nstar = jnp.argmax(covered & (walk_key == kmin_cov)).astype(jnp.int32)
    kmin_val = jnp.min(jnp.where(valid_node, walk_key, POS_INF))
    nstar_val = jnp.argmax(valid_node & (walk_key == kmin_val)).astype(jnp.int32)
    assigned = jnp.any(covered)

    # clean = the host walk would evict on no node before the chosen one
    # (otherwise it strands partial evictions on earlier valid nodes —
    # preempt.go keeps them in the statement — and the caller must take the
    # object fallback to reproduce that).  Same node <=> equal keys AND
    # equal first index among key ties.
    clean = jnp.where(
        assigned,
        (kmin_val == kmin_cov) & (nstar_val == nstar),
        ~jnp.any(valid_node),
    )

    victim_s = in_prefix_s & (sn2 == nstar) & assigned
    vmask = jnp.zeros((V,), bool).at[o_ev].set(victim_s)

    # -- state update (evict victims + pipeline preemptor) -------------------
    vreq = jnp.where(vmask[:, None], c.run_req, 0.0)
    vsum = vreq.sum(axis=0)
    t_add = jnp.where(assigned, t_req, jnp.zeros_like(t_req))
    new_state = VictimState(
        run_live=s.run_live & ~vmask,
        idle=s.idle,  # evict keeps idle (update_task Running->Releasing nets zero)
        releasing=s.releasing.at[nstar].add(vsum - t_add),
        used=s.used.at[nstar].add(t_add),
        task_count=s.task_count.at[nstar].add(jnp.where(assigned, 1, 0)),
        job_alloc=(
            s.job_alloc
            - jax.ops.segment_sum(vreq, c.run_job, num_segments=J)
        ).at[jt].add(t_add),
        job_occupied=s.job_occupied
        - jax.ops.segment_sum(vmask.astype(jnp.int32), c.run_job, num_segments=J),
        queue_alloc=(
            s.queue_alloc
            - jax.ops.segment_sum(
                vreq, jnp.where(has_q, run_q, Q), num_segments=Q + 1
            )[:Q]
        # qt = -1 (queue missing) must not credit queue 0 — the native twin
        # skips the update for qt < 0 and the two must agree
        ).at[jnp.clip(qt, 0, Q - 1)].add(
            jnp.where(qt >= 0, t_add, jnp.zeros_like(t_add))
        ),
    )
    return new_state, assigned, nstar, vmask, clean


@functools.partial(
    jax.jit,
    static_argnames=(
        "mode", "use_gang", "use_drf", "use_prop", "use_conformance",
        "order_by_priority",
    ),
)
def victim_step(
    c: VictimConsts,
    s: VictimState,
    t_req,            # [R] preemptor resreq
    t_cls,            # i32 predicate class
    jt,               # i32 preemptor job index
    qt,               # i32 preemptor queue index
    mode: str = "queue",          # "queue" | "job" | "reclaim"
    use_gang: bool = True,
    use_drf: bool = False,
    use_prop: bool = False,
    use_conformance: bool = False,
    order_by_priority: bool = True,
):
    """One preemptor's victim solve over all nodes (standalone entry used
    by the object tensor path, the sharded variant, and the native-twin
    parity tests; the fast cycle's storms use reclaim_solve/preempt_solve).

    Returns (new_state, assigned, node_index, victim_mask[V], clean).
    ``clean=False`` means the host walk would strand evictions on nodes
    that cannot cover the request; the returned state must be DISCARDED
    and the caller has to replay this preemptor through the host path.
    """
    Q = s.queue_alloc.shape[0]
    # raw queue rows keep the -1 "queue missing" sentinel so residents of a
    # deleted queue never match a real queue (host compares queue strings)
    rq_raw = c.job_queue[c.run_job]
    if mode == "queue":
        base = s.run_live & (rq_raw == qt) & (c.run_job != jt)
    elif mode == "job":
        base = s.run_live & (c.run_job == jt)
    else:  # reclaim: residents of other queues (including queueless jobs)
        base = s.run_live & (rq_raw != qt)

    o_drf = seg_drf = o_prop = seg_prop = None
    if use_drf:
        o_drf, seg_drf = _orders_drf(c)
    if use_prop:
        o_prop, seg_prop = _orders_prop(c, Q)
    o_ev, seg_ev = _orders_evict(c, order_by_priority, mode == "reclaim")
    return _victim_core(
        c, s, t_req, t_cls, jt, qt, base,
        o_drf, seg_drf, o_prop, seg_prop, o_ev, seg_ev,
        use_gang=use_gang, use_drf=use_drf, use_prop=use_prop,
        use_conformance=use_conformance, reclaim_mode=(mode == "reclaim"),
    )


# --------------------------------------------------------------------------
# storm solves: the full reclaim/preempt action loops as device programs
# --------------------------------------------------------------------------

def _job_order_keys(c, s, job_prio, job_key_order, jidx):
    """Session job_order_fn as lexicographic keys — identical contributors
    to kernels.allocate_solve's job selection (priority desc, gang
    not-ready-first, DRF share asc, creation/index order)."""
    keys = []
    for name in job_key_order:
        if name == "priority":
            keys.append(-job_prio.astype(jnp.float32))
        elif name == "gang":
            keys.append((s.job_occupied >= c.job_min).astype(jnp.float32))
        elif name == "drf":
            keys.append(dominant_share(s.job_alloc, c.total[None, :]))
    keys.append(jidx.astype(jnp.float32))
    return keys


class _StormRecords(NamedTuple):
    """Decision log of a storm solve, reconstructed host-side into the
    ordered eviction/pipeline lists after ONE device_get."""

    evict_att: jnp.ndarray  # [V] i32: ok-attempt seq that evicted row, -1
    pipe_node: jnp.ndarray  # [T] i32: node the task pipelined onto, -1
    pipe_att: jnp.ndarray   # [T] i32: ok-attempt seq of the pipeline, -1
    att: jnp.ndarray        # i32 count of ok attempts


class _ReclaimCarry(NamedTuple):
    s: VictimState
    qlive: jnp.ndarray      # [Q] bool queue still in the priority queue
    javail: jnp.ndarray     # [J] bool job not yet visited
    pipe: jnp.ndarray       # [J] i32 pipelined count (JobPipelined input)
    rec: _StormRecords
    abort: jnp.ndarray      # bool: kernel-inexpressible case hit
    iters: jnp.ndarray      # i32 runaway guard


@functools.partial(
    jax.jit,
    static_argnames=(
        "use_gang", "use_prop", "use_conformance", "order_by_priority",
        "has_proportion", "job_key_order",
    ),
)
def reclaim_solve(
    c: VictimConsts,
    s0: VictimState,
    task_req,        # [T, R]
    task_class,      # [T] i32
    job_first,       # [J] i32 first pending task row per job (job_start)
    job_prio,        # [J] i32
    job_cand0,       # [J] bool schedulable jobs with pending work
    queue_live0,     # [Q] bool queues of schedulable jobs
    pipe0,           # [J] i32
    *,
    use_gang: bool,
    use_prop: bool,
    use_conformance: bool,
    order_by_priority: bool,
    has_proportion: bool,
    job_key_order=("priority", "gang", "drf"),
):
    """The whole reclaim action on device (reclaim.go:42-201 /
    fast_victims.reclaim_pass): pop the queue with the lowest proportion
    share, pop its best job ONCE, attempt its head task cross-queue, and
    re-arm the queue only on success.  Returns
    (final_state, pipe, records, abort) — on abort the caller discards
    everything and replays through the object machinery.
    """
    T = task_req.shape[0]
    J = c.job_queue.shape[0]
    Q = s0.queue_alloc.shape[0]
    jidx = jnp.arange(J, dtype=jnp.int32)

    o_prop = seg_prop = None
    if use_prop:
        o_prop, seg_prop = _orders_prop(c, Q)
    o_ev, seg_ev = _orders_evict(c, order_by_priority, True)

    cap = jnp.int32(2 * (J + Q) + 64)

    def cond(cy: _ReclaimCarry):
        return ~cy.abort & jnp.any(cy.qlive) & (cy.iters < cap)

    def body(cy: _ReclaimCarry):
        if has_proportion:
            q_share = dominant_share(cy.s.queue_alloc, c.queue_deserved)
        else:
            q_share = jnp.zeros((Q,), jnp.float32)
        qkey = jnp.where(cy.qlive, q_share, POS_INF)
        qmin = jnp.min(qkey)
        qstar = jnp.argmax(cy.qlive & (q_share == qmin)).astype(jnp.int32)
        if has_proportion:
            overused = less_equal(
                c.queue_deserved[qstar], cy.s.queue_alloc[qstar], c.eps
            )
        else:
            overused = jnp.array(False)
        jcand = cy.javail & (c.job_queue == qstar)
        take = jnp.any(jcand) & ~overused

        def drop(cy):
            return cy._replace(qlive=cy.qlive.at[qstar].set(False))

        def attempt(cy):
            keys = _job_order_keys(c, cy.s, job_prio, job_key_order, jidx)
            j, _ = _lex_argmin(jcand, keys, jidx)
            j = j.astype(jnp.int32)
            t = jnp.clip(job_first[j], 0, T - 1)
            qt = c.job_queue[j]
            base = cy.s.run_live & (c.job_queue[c.run_job] != qt)
            new_s, assigned, nstar, vmask, clean = _victim_core(
                c, cy.s, task_req[t], task_class[t], j, qt, base,
                None, None, o_prop, seg_prop, o_ev, seg_ev,
                use_gang=use_gang, use_drf=False, use_prop=use_prop,
                use_conformance=use_conformance, reclaim_mode=True,
            )
            ok = assigned & clean
            rec = cy.rec
            return cy._replace(
                s=_tree_where(ok, new_s, cy.s),
                javail=cy.javail.at[j].set(False),
                # the queue survives only a successful visit
                # (host: ``if ok: qpq.push(q)``)
                qlive=cy.qlive.at[qstar].set(ok),
                pipe=cy.pipe.at[j].add(jnp.where(ok, 1, 0)),
                rec=rec._replace(
                    evict_att=jnp.where(ok & vmask, rec.att, rec.evict_att),
                    pipe_node=rec.pipe_node.at[t].set(
                        jnp.where(ok, nstar, rec.pipe_node[t])
                    ),
                    pipe_att=rec.pipe_att.at[t].set(
                        jnp.where(ok, rec.att, rec.pipe_att[t])
                    ),
                    att=rec.att + jnp.where(ok, 1, 0),
                ),
                abort=cy.abort | ~clean,
            )

        cy = jax.lax.cond(take, attempt, drop, cy)
        return cy._replace(iters=cy.iters + 1)

    V = c.run_req.shape[0]
    init = _ReclaimCarry(
        s=s0,
        qlive=queue_live0,
        javail=job_cand0,
        pipe=pipe0,
        rec=_StormRecords(
            evict_att=jnp.full((V,), -1, jnp.int32),
            pipe_node=jnp.full((T,), -1, jnp.int32),
            pipe_att=jnp.full((T,), -1, jnp.int32),
            att=jnp.int32(0),
        ),
        abort=jnp.array(False),
        iters=jnp.int32(0),
    )
    out = jax.lax.while_loop(cond, body, init)
    abort = out.abort | (out.iters >= cap)
    return out.s, out.pipe, out.rec, abort


class _PreemptCarry(NamedTuple):
    s: VictimState
    pipe: jnp.ndarray       # [J] i32
    rec: _StormRecords
    # statement checkpoint (taken at phase-1 job pop; restore = Discard)
    ck_s: VictimState
    ck_pipe: jnp.ndarray
    ck_rec: _StormRecords
    job_avail: jnp.ndarray  # [J] bool phase-1 heap membership
    cursor: jnp.ndarray     # [J] i32 per-job task-deque position
    qpos: jnp.ndarray       # i32 index into queues_order
    phase: jnp.ndarray      # i32: 0 select, 1 drain job, 2 within-job
    cur_job: jnp.ndarray    # i32
    assigned: jnp.ndarray   # bool: current pop placed something
    j2pos: jnp.ndarray      # i32 index into under_request
    last_v: jnp.ndarray     # i32 victim count of last phase-1 ok attempt
    any_p1: jnp.ndarray     # bool: any phase-1 ok attempt happened
    # ok attempts for the metrics counter: unlike rec.att it is NOT part of
    # the statement checkpoint — the host registers each ok attempt as it
    # happens and never un-registers on Discard (tensor_actions parity)
    att_total: jnp.ndarray  # i32
    abort: jnp.ndarray
    iters: jnp.ndarray


@functools.partial(
    jax.jit,
    static_argnames=(
        "use_gang", "use_drf", "use_conformance", "order_by_priority",
        "job_key_order", "gang_pipelined",
    ),
)
def preempt_solve(
    c: VictimConsts,
    s0: VictimState,
    task_req,        # [T, R]
    task_class,      # [T] i32
    task_attempt,    # [T] bool: valid pending rows the solve left unplaced
    job_start,       # [J] i32
    job_ntasks,      # [J] i32
    job_prio,        # [J] i32
    job_avail0,      # [J] bool: under-request preemptor jobs
    under_request,   # [J] i32 preemptor job ids in index order, padded
    nu,              # i32 count of under_request entries
    queues_order,    # [Q] i32 queue ids in first-appearance order, padded
    nq,              # i32 count of queues_order entries
    pipe0,           # [J] i32
    *,
    use_gang: bool,
    use_drf: bool,
    use_conformance: bool,
    order_by_priority: bool,
    job_key_order=("priority", "gang", "drf"),
    gang_pipelined: bool = True,
):
    """The whole preempt action on device (preempt.go:45-273 /
    fast_victims.preempt_pass): per queue, phase-1 same-queue cross-job
    preemption under statement checkpoint/rollback semantics, then phase-2
    within-job preemption over every under-request job.  Returns
    (final_state, pipe, records, last_p1_victims, any_p1, abort).
    """
    T = task_req.shape[0]
    J = c.job_queue.shape[0]
    Q = queues_order.shape[0]
    jidx = jnp.arange(J, dtype=jnp.int32)

    o_drf = seg_drf = None
    if use_drf:
        o_drf, seg_drf = _orders_drf(c)
    o_ev, seg_ev = _orders_evict(c, order_by_priority, False)

    # every iteration consumes a task row, retires/re-arms a job, or
    # advances a (phase, queue, under-request) pointer
    cap = 4 * T + 4 * jnp.int32(J) + nq * (nu + 4) + 64

    def _pipelined(cy, j):
        if gang_pipelined:
            return cy.s.job_occupied[j] + cy.pipe[j] >= c.job_min[j]
        return jnp.array(True)

    def _finish_job(cy):
        """Host epilogue of one phase-1 pop: Discard when the gang never
        reached JobPipelined, re-push (keep available) only when it both
        pipelined and placed something this pop."""
        j = cy.cur_job
        pip = _pipelined(cy, j)
        restore = ~pip
        return cy._replace(
            s=_tree_where(restore, cy.ck_s, cy.s),
            pipe=jnp.where(restore, cy.ck_pipe, cy.pipe),
            rec=_tree_where(restore, cy.ck_rec, cy.rec),
            job_avail=cy.job_avail.at[j].set(pip & cy.assigned),
            phase=jnp.int32(0),
        )

    def sel(cy):
        """Phase 0: pop the best preemptor job of the current queue, take
        the statement checkpoint; empty heap -> phase 2."""
        q = queues_order[jnp.clip(cy.qpos, 0, Q - 1)]
        cand = cy.job_avail & (c.job_queue == q)
        has = jnp.any(cand)
        keys = _job_order_keys(c, cy.s, job_prio, job_key_order, jidx)
        j, _ = _lex_argmin(cand, keys, jidx)
        j = j.astype(jnp.int32)
        cy2 = cy._replace(
            cur_job=jnp.where(has, j, cy.cur_job),
            assigned=jnp.where(has, False, cy.assigned),
            job_avail=cy.job_avail.at[j].set(
                jnp.where(has, False, cy.job_avail[j])
            ),
            ck_s=_tree_where(has, cy.s, cy.ck_s),
            ck_pipe=jnp.where(has, cy.pipe, cy.ck_pipe),
            ck_rec=_tree_where(has, cy.rec, cy.ck_rec),
            phase=jnp.where(has, jnp.int32(1), jnp.int32(2)),
            j2pos=jnp.where(has, cy.j2pos, jnp.int32(0)),
        )
        return cy2, jnp.array(False), jnp.int32(0), j, jnp.array(True)

    def drain(cy):
        """Phase 1: consume the current job's next pending row."""
        j = cy.cur_job
        exhausted = cy.cursor[j] >= job_ntasks[j]
        t = jnp.clip(job_start[j] + cy.cursor[j], 0, T - 1)
        do_att = ~exhausted & task_attempt[t]
        cy2 = cy._replace(
            cursor=cy.cursor.at[j].add(jnp.where(exhausted, 0, 1))
        )
        cy3 = jax.lax.cond(exhausted, _finish_job, lambda x: x, cy2)
        return cy3, do_att, t, j, jnp.array(True)

    def p2(cy):
        """Phase 2: within-job preemption over the under-request list."""
        done = cy.j2pos >= nu
        j = under_request[jnp.clip(cy.j2pos, 0, J - 1)]
        exhausted = cy.cursor[j] >= job_ntasks[j]
        t = jnp.clip(job_start[j] + cy.cursor[j], 0, T - 1)
        do_att = ~done & ~exhausted & task_attempt[t]
        cy2 = cy._replace(
            qpos=jnp.where(done, cy.qpos + 1, cy.qpos),
            phase=jnp.where(done, jnp.int32(0), jnp.int32(2)),
            j2pos=jnp.where(~done & exhausted, cy.j2pos + 1, cy.j2pos),
            cursor=cy.cursor.at[j].add(jnp.where(~done & ~exhausted, 1, 0)),
        )
        return cy2, do_att, t, j, jnp.array(False)

    def attempt(args):
        cy, t, jt, queue_mode = args
        qt = c.job_queue[jt]
        rq_raw = c.job_queue[c.run_job]
        base = jnp.where(
            queue_mode,
            cy.s.run_live & (rq_raw == qt) & (c.run_job != jt),
            cy.s.run_live & (c.run_job == jt),
        )
        new_s, assigned_t, nstar, vmask, clean = _victim_core(
            c, cy.s, task_req[t], task_class[t], jt, qt, base,
            o_drf, seg_drf, None, None, o_ev, seg_ev,
            use_gang=use_gang, use_drf=use_drf, use_prop=False,
            use_conformance=use_conformance, reclaim_mode=False,
        )
        ok = assigned_t & clean
        nv = jnp.sum(vmask).astype(jnp.int32)
        rec = cy.rec
        cy2 = cy._replace(
            abort=cy.abort | ~clean,
            s=_tree_where(ok, new_s, cy.s),
            pipe=cy.pipe.at[jt].add(jnp.where(ok, 1, 0)),
            rec=rec._replace(
                evict_att=jnp.where(ok & vmask, rec.att, rec.evict_att),
                pipe_node=rec.pipe_node.at[t].set(
                    jnp.where(ok, nstar, rec.pipe_node[t])
                ),
                pipe_att=rec.pipe_att.at[t].set(
                    jnp.where(ok, rec.att, rec.pipe_att[t])
                ),
                att=rec.att + jnp.where(ok, 1, 0),
            ),
            assigned=cy.assigned | (ok & queue_mode),
            last_v=jnp.where(ok & queue_mode, nv, cy.last_v),
            any_p1=cy.any_p1 | (ok & queue_mode),
            att_total=cy.att_total + jnp.where(ok, 1, 0),
            # phase 2 stops a job's drain at the first failed attempt
            j2pos=jnp.where(
                ~queue_mode & clean & ~assigned_t, cy.j2pos + 1, cy.j2pos
            ),
        )
        # phase 1 checks JobPipelined after EVERY attempt, ok or not
        return jax.lax.cond(
            queue_mode & ~cy2.abort & _pipelined(cy2, jt),
            _finish_job, lambda x: x, cy2,
        )

    def body(cy):
        cy, do_att, t, jt, qm = jax.lax.switch(
            cy.phase, [sel, drain, p2], cy
        )
        cy = jax.lax.cond(
            do_att & ~cy.abort, attempt, lambda a: a[0], (cy, t, jt, qm)
        )
        return cy._replace(iters=cy.iters + 1)

    def cond(cy):
        return ~cy.abort & (cy.qpos < nq) & (cy.iters < cap)

    V = c.run_req.shape[0]
    rec0 = _StormRecords(
        evict_att=jnp.full((V,), -1, jnp.int32),
        pipe_node=jnp.full((T,), -1, jnp.int32),
        pipe_att=jnp.full((T,), -1, jnp.int32),
        att=jnp.int32(0),
    )
    init = _PreemptCarry(
        s=s0, pipe=pipe0, rec=rec0,
        ck_s=s0, ck_pipe=pipe0, ck_rec=rec0,
        job_avail=job_avail0,
        cursor=jnp.zeros((J,), jnp.int32),
        qpos=jnp.int32(0), phase=jnp.int32(0), cur_job=jnp.int32(0),
        assigned=jnp.array(False), j2pos=jnp.int32(0),
        last_v=jnp.int32(0), any_p1=jnp.array(False),
        att_total=jnp.int32(0),
        abort=jnp.array(False), iters=jnp.int32(0),
    )
    out = jax.lax.while_loop(cond, body, init)
    abort = out.abort | (out.qpos < nq)
    return (
        out.s, out.pipe, out.rec, out.att_total, out.last_v, out.any_p1,
        abort,
    )


# --------------------------------------------------------------------------
# batched-rounds preempt: throughput mode for large storms
# --------------------------------------------------------------------------

class _RoundsCarry(NamedTuple):
    s: VictimState          # run_live is maintained in ev layout (live_ev)
    live_ev: jnp.ndarray    # [V] bool, evict-order layout
    cursor: jnp.ndarray     # [J] i32 position into the job's packed rows
    pipe: jnp.ndarray       # [J] i32
    dropped: jnp.ndarray    # [J] bool
    rec: _StormRecords
    att_total: jnp.ndarray  # i32 committed tasks (metrics counter)
    last_v: jnp.ndarray     # i32 victims of the last progressing round
    any_commit: jnp.ndarray  # bool
    round_: jnp.ndarray     # i32
    progressed: jnp.ndarray  # bool


@functools.partial(
    jax.jit,
    static_argnames=(
        "use_gang", "use_drf", "use_conformance", "order_by_priority",
        "job_key_order", "gang_pipelined", "m_chunk", "p_chunk", "k_chunk",
    ),
)
def preempt_rounds(
    c: VictimConsts,
    s0: VictimState,
    task_req,        # [T, R]
    task_class,      # [T] i32
    rows_packed,     # [T] i32 attemptable task rows, contiguous per job
    job_pstart,      # [J] i32 offset into rows_packed
    job_pcount,      # [J] i32 attemptable row count per job
    job_prio,        # [J] i32
    job_avail0,      # [J] bool preemptor jobs
    pipe0,           # [J] i32
    *,
    use_gang: bool,
    use_drf: bool,
    use_conformance: bool,
    order_by_priority: bool,
    job_key_order=("priority", "gang", "drf"),
    gang_pipelined: bool = True,
    m_chunk: int = 128,
    p_chunk: int = ROUNDS_P_CHUNK,
    k_chunk: int = 8,
):
    """Throughput-mode preemption: rounds of parallel victim-capacity
    placement, the contention analogue of ``kernels.allocate_solve_batch``.

    The exact storm loop (``preempt_solve``) pays several O(V)
    gather/scatter passes PER PREEMPTOR — ~10 ms per attempt at a 131k
    victim pool on TPU, which is the whole budget for a 2,000-task storm.
    This kernel amortizes those passes over a round:

      1. per-round candidate analysis over the pool ONCE — conformance
         (static), gang budgets (a job's first ``occupied - min_available``
         rows in global evict order; the sequential path decrements the
         count attempt by attempt), and the DRF hypothetical-transfer test
         at the round's most-restrictive preemptor share per queue
         (conservative: admits no victim the weakest preemptor could not
         take);
      2. per-node evictable-capacity curves (evict-order prefix sums of
         admitted requests);
      3. a round of parallel proposals: the top-``m_chunk`` preemptor jobs
         (ranked by the session job-order keys) spread their next
         ``p_chunk`` tasks over their ``k_chunk`` best-scoring feasible
         nodes; conflicts resolve by (node, rank) prefix sums against the
         capacity curve, pod-count caps included;
      4. gang all-or-nothing: a job not yet JobPipelined must win at least
         ``min_available - occupied - pipelined`` tasks in one round or its
         wins are cancelled (the sequential path drains a popped gang to
         pipelined-or-Discard in one statement, so the unit matches);
      5. committed capacity materializes into victims at round end — the
         admitted evict-order prefix of each consumed node — and the next
         round's analysis sees the updated pool/occupancies/shares.

    A round with zero wins drops every selected job (state is unchanged,
    so they cannot win later); the caller retries leftovers through the
    exact loop.  Divergences vs the sequential path (documented, bench
    scale only): scores and shares freeze within a round, the DRF veto
    uses the per-queue worst-case share, victims attribute to rounds
    rather than single attempts, running rows of preemptor jobs are never
    victims (the host only excludes them for their own job), and queues
    interleave within a round instead of completing in discovery order.
    Capacity is never oversubscribed: every grant is prefix-checked
    against the admitted victim totals of its node, and evictions cover
    grants exactly as the per-attempt rule does (minimal evict-order
    prefix whose total covers the consumed capacity).

    Returns (final_state, pipe, records, att_total, last_v, any_commit,
    cursor, dropped).
    """
    V = c.run_req.shape[0]
    N = s0.idle.shape[0]
    T = task_req.shape[0]
    J = c.job_queue.shape[0]
    Q = s0.queue_alloc.shape[0]
    R = c.run_req.shape[1]
    M = min(m_chunk, J)
    P = p_chunk
    K = min(k_chunk, N)
    jidx = jnp.arange(J, dtype=jnp.int32)
    vidx = jnp.arange(V, dtype=jnp.int32)

    # ---- hoisted static layouts -----------------------------------------
    # eviction order grouped per (node, QUEUE): phase-1 preemption is
    # strictly same-queue (preempt_solve base: rq == qt), so capacity
    # curves and victim prefixes must never fund a preemptor with another
    # queue's residents.  Within a (node, queue) group the order is the
    # host rule (priority asc, rank desc); a queue's rows of one node are
    # a contiguous segment, so one cumsum yields per-(node, queue) curves.
    rq_pool_raw = c.job_queue[c.run_job]
    rq_pool = jnp.clip(rq_pool_raw, 0, Q - 1)
    prio_pool = (
        c.run_prio if order_by_priority else jnp.zeros((V,), jnp.int32)
    )
    o_ev = jnp.lexsort((-c.run_rank, prio_pool, rq_pool, c.run_node))
    inv_ev = jnp.zeros((V,), jnp.int32).at[o_ev].set(vidx)  # pool -> ev pos
    sn2 = c.run_node[o_ev]
    req_ev = c.run_req[o_ev]
    job_ev = c.run_job[o_ev]
    rq_ev_raw = c.job_queue[job_ev]
    has_q_ev = rq_ev_raw >= 0
    rq_ev = jnp.clip(rq_ev_raw, 0, Q - 1)
    flat_ev = sn2 * Q + rq_ev  # (node, queue) cell of each row
    seg_ev = jnp.concatenate(
        [jnp.array([True]), flat_ev[1:] != flat_ev[:-1]]
    )
    evictable_ev = c.run_evictable[o_ev]
    start_ev = jax.lax.cummax(jnp.where(seg_ev, jnp.arange(V), 0))
    # last row of each (node, queue) segment (for the curve totals)
    last_ev = jnp.concatenate([seg_ev[1:], jnp.array([True])])
    # within-job rank in global evict order, for gang eviction budgets
    o_jb = jnp.lexsort((inv_ev[vidx], c.run_job))  # pool rows by (job, ev)
    jb_seg = jnp.concatenate(
        [jnp.array([True]), c.run_job[o_jb][1:] != c.run_job[o_jb][:-1]]
    )
    jb_start = jax.lax.cummax(jnp.where(jb_seg, jnp.arange(V), 0))
    cnt_in_job_pool = jnp.zeros((V,), jnp.int32).at[o_jb].set(
        (jnp.arange(V) - jb_start).astype(jnp.int32)
    )
    cnt_in_job_ev = cnt_in_job_pool[o_ev]
    row_is_pre_ev = job_avail0[job_ev]

    if use_drf:
        o_drf, seg_drf = _orders_drf(c)
        # static perms between the ev and drf layouts
        ev_pos_drf = inv_ev[o_drf]            # drf pos -> ev pos
        inv_drf = jnp.zeros((V,), jnp.int32).at[o_drf].set(vidx)
        drf_pos_ev = inv_drf[o_ev]            # ev pos -> drf pos
        req_drf = c.run_req[o_drf]
        job_drf = c.run_job[o_drf]
        rq_drf_raw = c.job_queue[job_drf]
        has_q_drf = rq_drf_raw >= 0
        rq_drf = jnp.clip(rq_drf_raw, 0, Q - 1)
        start_drf = jax.lax.cummax(jnp.where(seg_drf, jnp.arange(V), 0))

    def _cumsum_seg(values, start):
        cum = jnp.cumsum(values, axis=0)
        return cum - (cum[start] - values[start])

    def body(cy: _RoundsCarry):
        s = cy.s
        active = (
            job_avail0 & ~cy.dropped & (cy.cursor < job_pcount)
        )
        act_q = (
            jax.ops.segment_sum(
                (active & (c.job_queue >= 0)).astype(jnp.int32),
                jnp.clip(c.job_queue, 0, Q - 1), num_segments=Q,
            )
            > 0
        )

        # ---- candidate analysis (once per round over the pool) ----------
        cand_ev = cy.live_ev & act_q[rq_ev] & has_q_ev & ~row_is_pre_ev
        if use_conformance:
            cand_ev = cand_ev & evictable_ev
        if use_gang:
            budget = jnp.where(
                c.job_min > 1,
                s.job_occupied - c.job_min,
                jnp.int32(2**31 - 1),
            )
            cand_ev = cand_ev & (cnt_in_job_ev < budget[job_ev])

        head_t = rows_packed[
            jnp.clip(job_pstart + cy.cursor, 0, T - 1)
        ]                                              # [J]
        head_req_all = task_req[jnp.clip(head_t, 0, T - 1)]  # [J, R]

        if use_drf:
            # worst-case (largest) preemptor share per queue this round —
            # conservative: admits only victims every active preemptor of
            # the queue could take
            ls_j = dominant_share(s.job_alloc + head_req_all, c.total)
            ls_q = jax.ops.segment_max(
                jnp.where(active, ls_j, -jnp.inf),
                jnp.clip(c.job_queue, 0, Q - 1), num_segments=Q,
            )
            live_drf = cy.live_ev[ev_pos_drf]  # live in drf order
            base_drf = live_drf & act_q[rq_drf] & has_q_drf
            sreq = jnp.where(base_drf[:, None], req_drf, 0.0)
            relcum = _cumsum_seg(sreq, start_drf)
            rs = dominant_share(s.job_alloc[job_drf] - relcum, c.total)
            admit_drf = (ls_q[rq_drf] < rs + SHARE_DELTA) & has_q_drf
            cand_ev = cand_ev & admit_drf[drf_pos_ev]

        # ---- per-(node, queue) evictable-capacity curves ----------------
        vr = jnp.where(cand_ev[:, None], req_ev, 0.0)
        cum = _cumsum_seg(vr, start_ev)
        cap_flat = (
            jnp.zeros((N * Q + 1, R), jnp.float32)
            .at[jnp.where(last_ev, flat_ev, N * Q)].set(cum)
        )[: N * Q]

        # ---- job ranking + proposals (allocate_solve_batch pattern) -----
        keys = [jidx.astype(jnp.float32)]
        for name in reversed(job_key_order):
            if name == "priority":
                keys.append(-job_prio.astype(jnp.float32))
            elif name == "gang":
                keys.append((s.job_occupied >= c.job_min).astype(jnp.float32))
            elif name == "drf":
                keys.append(dominant_share(s.job_alloc, c.total[None, :]))
        keys.append(~active)
        order = jnp.lexsort(tuple(keys))
        sel = order[:M]
        sel_active = active[sel]

        head_req = head_req_all[sel]                   # [M, R]
        head_cls = task_class[jnp.clip(head_t[sel], 0, T - 1)]
        # each job sees only its OWN queue's capacity column
        q_sel = jnp.clip(c.job_queue[sel], 0, Q - 1)   # [M]
        cap_mnr = cap_flat.reshape(N, Q, R)[:, q_sel, :].transpose(1, 0, 2)
        covered = jnp.all(
            head_req[:, None, :] < cap_mnr + c.eps, axis=-1
        )
        pred = (
            c.class_mask[head_cls]
            & (s.task_count < c.node_max_tasks)[None, :]
            & c.node_valid[None, :]
        )
        feasible = covered & pred & sel_active[:, None]
        job_ok = jnp.any(feasible, axis=1)

        score = _score_nodes(
            head_req, s.used, c.node_alloc, c.class_score[head_cls],
            c.w_least, c.w_balanced,
        )
        jh = (sel.astype(jnp.uint32) * jnp.uint32(2654435761))[:, None]
        nh = (jnp.arange(N, dtype=jnp.uint32) * jnp.uint32(40503))[None, :]
        h = (jh ^ nh) * jnp.uint32(2246822519)
        h = h ^ (h >> 15)
        jitter = (h & jnp.uint32(0xFFFF)).astype(jnp.float32) * (1e-4 / 65535.0)
        masked = jnp.where(feasible, score + jitter, NEG_INF)
        _, topk_nodes = jax.lax.top_k(masked, K)
        topk_nodes = topk_nodes.astype(jnp.int32)
        rot = (
            jnp.arange(K, dtype=jnp.int32)[None, :]
            + (jnp.arange(M, dtype=jnp.int32) % K)[:, None]
        ) % K
        topk_nodes = jnp.take_along_axis(topk_nodes, rot, axis=1)
        topk_ok = jnp.take_along_axis(feasible, topk_nodes, axis=1)
        cap_k = cap_mnr[jnp.arange(M)[:, None], topk_nodes]  # [M, K, R]
        req_safe = jnp.maximum(head_req, 1e-30)[:, None, :]
        cnt = jnp.floor((cap_k + c.eps) / req_safe)
        cnt = jnp.where(head_req[:, None, :] > 0, cnt, jnp.inf).min(axis=-1)
        cnt = jnp.where(topk_ok, jnp.maximum(cnt, 0.0), 0.0)
        cum_cnt = jnp.cumsum(cnt, axis=1)
        offs = jnp.arange(P, dtype=jnp.int32)
        slot = jnp.sum(offs[None, :, None] >= cum_cnt[:, None, :], axis=-1)
        in_range = slot < K
        slot_c = jnp.clip(slot, 0, K - 1)
        prop_node_mp = jnp.take_along_axis(topk_nodes, slot_c, axis=1)

        F = M * P
        pofs = job_pstart[sel][:, None] + cy.cursor[sel][:, None] + offs[None, :]
        prop_valid = (
            sel_active[:, None]
            & job_ok[:, None]
            & (cy.cursor[sel][:, None] + offs[None, :] < job_pcount[sel][:, None])
            & in_range
        )
        t_prop = rows_packed[jnp.clip(pofs, 0, T - 1)]
        fr = lambda x: x.reshape((F,) + x.shape[2:])
        p_valid = fr(prop_valid)
        p_t = fr(jnp.clip(t_prop, 0, T - 1))
        p_req = task_req[p_t]
        p_node = fr(prop_node_mp)
        p_job = fr(jnp.broadcast_to(sel[:, None], (M, P)))
        rank = jnp.arange(F, dtype=jnp.int32)

        # conflict resolution against the proposer's own (node, queue)
        # capacity cell.  The pod-count cap is checked per segment, so two
        # queues storming the same node can jointly overshoot max_tasks by
        # up to (queues - 1) in one round — the same class of per-round
        # slack allocate_solve_batch documents; corrected next cycle.
        p_q = jnp.clip(c.job_queue[p_job], 0, Q - 1)
        key_flat = jnp.where(p_valid, p_node * Q + p_q, N * Q)
        order2 = jnp.lexsort((rank, key_flat))
        skf = key_flat[order2]
        snp = jnp.where(skf < N * Q, skf // Q, N)
        sreqp = jnp.where(p_valid[order2, None], p_req[order2], 0.0)
        seg_start = jnp.concatenate([jnp.array([True]), skf[1:] != skf[:-1]])
        cump = jnp.cumsum(sreqp, axis=0)
        start_pos = jax.lax.cummax(jnp.where(seg_start, jnp.arange(F), 0))
        relcump = cump - (cump[start_pos] - sreqp[start_pos])
        cap_rows = jnp.concatenate(
            [cap_flat, jnp.zeros((1, R), jnp.float32)], 0
        )[jnp.clip(skf, 0, N * Q)]
        tc_rows = jnp.concatenate(
            [s.task_count, jnp.zeros((1,), jnp.int32)], 0
        )[snp]
        max_rows = jnp.concatenate(
            [c.node_max_tasks, jnp.full((1,), 2**31 - 1, jnp.int32)], 0
        )[snp]
        pos_in_seg = jnp.arange(F) - start_pos
        accept_sorted = (
            jnp.all(relcump < cap_rows + c.eps, axis=-1)
            & (tc_rows + pos_in_seg < max_rows)
            & (snp < N)
        )
        win0 = jnp.zeros((F,), bool).at[order2].set(accept_sorted) & p_valid

        # no holes: a job's accepted offsets must be a prefix
        win_mp = win0.reshape(M, P)
        prefix_ok = jnp.cumsum((~win_mp).astype(jnp.int32), axis=1) == 0
        win_mp = win_mp & prefix_ok
        # gang all-or-nothing: win at least the remaining min-need in this
        # round, or nothing (the sequential statement drains a popped gang
        # to pipelined-or-Discard as one unit)
        if gang_pipelined:
            need = jnp.maximum(
                c.job_min[sel] - s.job_occupied[sel] - cy.pipe[sel], 0
            )
        else:
            need = jnp.zeros((M,), jnp.int32)
        wins_m = jnp.sum(win_mp.astype(jnp.int32), axis=1)
        commit_m = wins_m >= need
        win = (win_mp & commit_m[:, None]).reshape(F)

        any_win = jnp.any(win)

        # ---- commit: preemptor placements -------------------------------
        delta = jnp.where(win[:, None], p_req, 0.0)
        node_tgt = jnp.where(win, p_node, N)
        flat_tgt = jnp.where(win, p_node * Q + p_q, N * Q)
        consumed_flat = (
            jnp.zeros((N * Q + 1, R), jnp.float32).at[flat_tgt].add(delta)
        )[: N * Q]
        consumed = consumed_flat.reshape(N, Q, R).sum(axis=1)  # per node
        placed_cnt = (
            jnp.zeros((N + 1,), jnp.int32)
            .at[node_tgt].add(jnp.where(win, 1, 0))
        )[:N]
        job_tgt = jnp.where(win, p_job, J)
        ja2 = (
            jnp.concatenate([s.job_alloc, jnp.zeros((1, R), jnp.float32)], 0)
            .at[job_tgt].add(delta)
        )
        q_tgt = jnp.where(
            win, jnp.clip(c.job_queue[p_job], 0, Q - 1), Q
        )
        qa2 = (
            jnp.concatenate([s.queue_alloc, jnp.zeros((1, R), jnp.float32)], 0)
            .at[q_tgt].add(delta)
        )[:Q]
        pipe2 = (
            jnp.concatenate([cy.pipe, jnp.zeros((1,), jnp.int32)], 0)
            .at[job_tgt].add(jnp.where(win, 1, 0))
        )[:J]
        cursor2 = (
            jnp.concatenate([cy.cursor, jnp.zeros((1,), jnp.int32)], 0)
            .at[job_tgt].add(jnp.where(win, 1, 0))
        )[:J]
        t_tgt = jnp.where(win, p_t, T)
        att_seq = cy.rec.att + rank  # round-grouped attempt ids
        pn2 = (
            jnp.concatenate([cy.rec.pipe_node, jnp.zeros((1,), jnp.int32)], 0)
            .at[t_tgt].set(jnp.where(win, p_node, 0))
        )[:T]
        pa2 = (
            jnp.concatenate([cy.rec.pipe_att, jnp.zeros((1,), jnp.int32)], 0)
            .at[t_tgt].set(jnp.where(win, att_seq, 0))
        )[:T]

        # ---- materialize victims: minimal admitted evict-order prefix of
        # each (node, queue) cell covering that cell's consumed capacity
        # (the per-attempt cover rule, aggregated per cell — same-queue
        # funding only).  evict_att is kept in the ev layout inside the
        # loop and converted to pool order on return.
        cum_excl = cum - vr
        new_vict = cand_ev & ~less_equal(
            consumed_flat[flat_ev], cum_excl, c.eps
        )
        live2 = cy.live_ev & ~new_vict
        ea2 = jnp.where(new_vict, cy.rec.att + F, cy.rec.evict_att)
        vreq_new = jnp.where(new_vict[:, None], req_ev, 0.0)
        vict_node = jax.ops.segment_sum(
            vreq_new, sn2, num_segments=N, indices_are_sorted=True
        )
        vict_job = jax.ops.segment_sum(vreq_new, job_ev, num_segments=J)
        vict_job_cnt = jax.ops.segment_sum(
            new_vict.astype(jnp.int32), job_ev, num_segments=J
        )
        vict_q = jax.ops.segment_sum(
            vreq_new, jnp.where(has_q_ev, rq_ev, Q), num_segments=Q + 1
        )[:Q]
        n_vict = jnp.sum(new_vict.astype(jnp.int32))

        s2 = VictimState(
            run_live=s.run_live,  # reconciled from live_ev after the loop
            idle=s.idle,
            releasing=s.releasing + vict_node - consumed,
            used=s.used + consumed,
            task_count=s.task_count + placed_cnt,
            job_alloc=(ja2[:J] - vict_job),
            job_occupied=s.job_occupied - vict_job_cnt,
            queue_alloc=qa2 - vict_q,
        )

        # ---- stall: nothing won => every selected job is stuck at this
        # state; drop them all (no rollback needed — cancelled wins never
        # commit anything) and let the next window (or the exact tail) try
        drop_now = jnp.where(
            any_win, jnp.zeros((J,), bool),
            jnp.zeros((J,), bool).at[sel].set(sel_active),
        )

        return _RoundsCarry(
            s=s2,
            live_ev=live2,
            cursor=cursor2,
            pipe=pipe2,
            dropped=cy.dropped | drop_now,
            rec=cy.rec._replace(
                evict_att=ea2, pipe_node=pn2, pipe_att=pa2,
                att=cy.rec.att + F + 1,
            ),
            att_total=cy.att_total + jnp.sum(win.astype(jnp.int32)),
            last_v=jnp.where(any_win, n_vict, cy.last_v),
            any_commit=cy.any_commit | any_win,
            round_=cy.round_ + 1,
            progressed=any_win | jnp.any(drop_now),
        )

    def cond(cy: _RoundsCarry):
        active = job_avail0 & ~cy.dropped & (cy.cursor < job_pcount)
        return cy.progressed & jnp.any(active) & (cy.round_ < J + 8)

    V_ = V
    init = _RoundsCarry(
        s=s0,
        live_ev=s0.run_live[o_ev],
        cursor=jnp.zeros((J,), jnp.int32),
        pipe=pipe0,
        dropped=jnp.zeros((J,), bool),
        rec=_StormRecords(
            evict_att=jnp.full((V_,), -1, jnp.int32),
            pipe_node=jnp.full((T,), -1, jnp.int32),
            pipe_att=jnp.full((T,), -1, jnp.int32),
            att=jnp.int32(0),
        ),
        att_total=jnp.int32(0),
        last_v=jnp.int32(0),
        any_commit=jnp.array(False),
        round_=jnp.int32(0),
        progressed=jnp.array(True),
    )
    out = jax.lax.while_loop(cond, body, init)
    final_s = out.s._replace(
        run_live=jnp.zeros((V_,), bool).at[o_ev].set(out.live_ev)
    )
    final_rec = out.rec._replace(
        evict_att=jnp.full((V_,), -1, jnp.int32).at[o_ev].set(
            out.rec.evict_att
        )
    )
    return (
        final_s, out.pipe, final_rec, out.att_total, out.last_v,
        out.any_commit, out.cursor, out.dropped,
    )


# -- vtprof compile-sentinel registration (see kernels.py tail): the
# contention kernels are dispatched directly by fast_victims.py and the
# tensor-path victim driver, so their caches ARE the dispatch caches.
from volcano_tpu import vtprof as _vtprof  # noqa: E402

_vtprof.register_jit("victim_step", victim_step)
_vtprof.register_jit("reclaim_solve", reclaim_solve)
_vtprof.register_jit("preempt_solve", preempt_solve)
_vtprof.register_jit("preempt_rounds", preempt_rounds)
