"""Array-native preempt/reclaim for the fast cycle (VERDICT r2 next #2).

The object path's contention actions (tensor_actions.preempt/reclaim) keep
the reference's host loop structure — per-queue priority queues, Statement
commit/discard, one victim solve per preemptor — but run inside a full
object Session whose open/close costs O(cluster) Python.  This module runs
the SAME loop structure directly against the fast mirror's arrays:

  * the per-preemptor victim math is the SAME jitted ``victim_step`` device
    program (victim_kernels.py) the object tensor path uses, with the same
    static veto flags, so one compilation serves both paths;
  * Statement semantics are functional: the device ``VictimState`` tuple is
    immutable, so checkpoint = keeping the reference and discard = dropping
    the candidate state (SURVEY §7 step 6's "trivially pure in JAX" note);
    host-side order-key arrays are small and copied;
  * ordering parity uses the SAME ``PriorityQueue`` class over less-fns
    computed from array state, pushed in session iteration order, so the
    lazy-heap pop behavior under mutating DRF/proportion shares matches the
    object path exactly (pqueue.py's stale-heap contract);
  * anything the kernel cannot express — a host walk that would strand
    evictions on non-covering nodes (``clean=False``, see
    victim_kernels.py), a best-effort (empty-request) preemptor — aborts
    the fast pass with nothing published; the caller falls back to the
    object machinery, which recomputes the same decisions from the store.

Divergences from the object path, same documented class as the fast
allocate passes: eviction-order ties break by pod *arrival* rank rather
than uid string order.

Reference loops mirrored: preempt.go:45-273 (two-phase preemption,
statement per preemptor job), reclaim.go:42-201 (queue-ordered cross-queue
reclaim, one task per queue visit).
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from volcano_tpu.scheduler import metrics
from volcano_tpu.scheduler.pqueue import PriorityQueue


def _share(alloc: np.ndarray, denom: np.ndarray) -> float:
    """max over dims of l/r with 0/0 = 0 and x/0 = 1 (helpers.Share)."""
    zero = denom == 0
    ratio = np.where(zero, np.where(alloc == 0, 0.0, 1.0),
                     alloc / np.where(zero, 1.0, denom))
    return float(ratio.max()) if ratio.size else 0.0


def _less_equal(a: np.ndarray, b: np.ndarray, eps: np.ndarray) -> bool:
    """ε-tolerant a <= b over all dims (resource.py less_equal / the
    kernels.less_equal twin)."""
    return bool(((a < b) | (np.abs(a - b) < eps)).all())


class FastContention:
    """One cycle's contention driver over the fast snapshot.

    Owns the device VictimConsts/VictimState plus host order-key state
    (occupied/pipelined counts, drf job allocations, proportion queue
    allocations) and the committed eviction/pipeline records.  Build it
    after enqueue; run ``reclaim_pass`` before the allocate solve and
    ``preempt_pass`` after backfill (conf action order).
    """

    def __init__(self, fc, snap, aux, deserved: np.ndarray):
        import jax.numpy as jnp

        self.fc = fc
        self.snap = snap
        self.aux = aux
        self.jnp = jnp
        probe = fc.probe
        self.probe = probe
        n_jobs = aux["n_jobs"]
        self.n_jobs = n_jobs
        self.deserved = deserved  # [Q, R] numpy
        self.eps = snap.eps
        self.total = snap.total
        self.job_min = snap.job_min_available
        self.job_prio = snap.job_priority
        self.job_queue = snap.job_queue

        # host order-key state (the plugin attrs the object path tracks via
        # event handlers)
        self.occ = snap.job_ready_init.astype(np.int64).copy()
        self.pipe = np.zeros(self.occ.shape[0], np.int64)
        self.job_alloc = snap.job_alloc_init.astype(np.float64).copy()
        self.queue_alloc = snap.queue_alloc_init.astype(np.float64).copy()

        # committed decisions (published by the caller at cycle end)
        self.evictions: List[Tuple[int, str]] = []  # (pool idx, reason)
        self.pipelines: List[Tuple[int, int]] = []  # (task row, node idx)
        self.advanced = False  # advance_post_solve folded the solve in

        veto_p, veto_r = probe.victim_vetoes()
        self.kw_preempt = dict(
            use_gang="gang" in veto_p,
            use_drf="drf" in veto_p,
            use_prop=False,
            use_conformance="conformance" in veto_p,
            order_by_priority=probe.task_order_by_priority,
        )
        self.kw_reclaim = dict(
            use_gang="gang" in veto_r,
            use_drf=False,
            use_prop="proportion" in veto_r,
            use_conformance="conformance" in veto_r,
            order_by_priority=probe.task_order_by_priority,
        )
        self.gang_pipelined = any(
            opt.name == "gang" and opt.enabled_job_pipelined
            for tier in fc.conf.tiers for opt in tier.plugins
        )
        self.has_proportion = probe.enabled.get("proportion", False)

        from volcano_tpu.scheduler.victim_kernels import VictimConsts, VictimState

        self.consts = VictimConsts(
            run_req=jnp.asarray(snap.run_req),
            run_node=jnp.asarray(snap.run_node),
            run_job=jnp.asarray(snap.run_job),
            run_prio=jnp.asarray(snap.run_prio),
            run_rank=jnp.asarray(snap.run_rank),
            run_evictable=jnp.asarray(snap.run_evictable),
            job_queue=jnp.asarray(snap.job_queue),
            job_min=jnp.asarray(snap.job_min_available),
            node_alloc=jnp.asarray(snap.node_alloc),
            node_max_tasks=jnp.asarray(snap.node_max_tasks),
            node_valid=jnp.asarray(snap.node_valid),
            class_mask=jnp.asarray(snap.class_node_mask),
            class_score=jnp.asarray(snap.class_node_score),
            queue_deserved=jnp.asarray(deserved.astype(np.float32)),
            total=jnp.asarray(snap.total),
            eps=jnp.asarray(snap.eps),
            w_least=jnp.float32(probe.score_weights()[0]),
            w_balanced=jnp.float32(probe.score_weights()[1]),
        )
        self.run_live = snap.run_valid.copy()  # host mirror for bookkeeping
        # one upload for every preemptor's request row: attempt() slices on
        # device instead of paying a host->device transfer per call
        self.task_req_dev = jnp.asarray(snap.task_req)
        self.state = VictimState(
            run_live=jnp.asarray(snap.run_valid),
            idle=jnp.asarray(snap.node_idle),
            releasing=jnp.asarray(snap.node_releasing),
            used=jnp.asarray(snap.node_used),
            task_count=jnp.asarray(snap.node_task_count),
            job_alloc=jnp.asarray(snap.job_alloc_init),
            job_occupied=jnp.asarray(snap.job_ready_init),
            queue_alloc=jnp.asarray(snap.queue_alloc_init),
        )

    # -- consts rebuild after the task re-pack -------------------------------

    def refresh_for_preempt(self, snap) -> None:
        """The reclaim pass re-packed the task/class arrays (consumed
        preemptor rows); the preempt pass gathers t_cls against the NEW
        class indexing, so the consts' class planes must follow."""
        jnp = self.jnp
        self.consts = self.consts._replace(
            class_mask=jnp.asarray(snap.class_node_mask),
            class_score=jnp.asarray(snap.class_node_score),
        )
        self.task_req_dev = jnp.asarray(snap.task_req)

    def advance_post_solve(self, task_node, task_kind, ready,
                           be_rows, be_nodes) -> None:
        """Fold the allocate solve's and backfill's session effects into the
        victim state — the object path gets this from rebuilding the
        snapshot off the post-allocate session (tensor_actions preempt's
        _VictimDriver._load).  Allocations consume idle and count ready;
        pipelines consume releasing and count waiting; backfill placements
        count ready and a task slot."""
        jnp = self.jnp
        snap, aux = self.snap, self.aux
        idle = np.asarray(self.state.idle).copy()
        releasing = np.asarray(self.state.releasing).copy()
        used = np.asarray(self.state.used).copy()
        tc = np.asarray(self.state.task_count).copy()

        # end-state ready counts: the solve's own output (it already folds
        # the job_ready_init this state was built from, including any
        # reclaim evictions), plus backfill below
        self.occ = np.asarray(ready).astype(np.int64).copy()

        placed = np.nonzero(task_kind == 1)[0]
        piped = np.nonzero(task_kind == 2)[0]
        if placed.size:
            np.subtract.at(idle, task_node[placed], snap.task_req[placed])
            np.add.at(used, task_node[placed], snap.task_req[placed])
            np.add.at(tc, task_node[placed], 1)
            jj = snap.task_job[placed]
            np.add.at(self.job_alloc, jj, snap.task_req[placed])
            np.add.at(self.queue_alloc, snap.job_queue[jj],
                      snap.task_req[placed])
        if piped.size:
            np.subtract.at(releasing, task_node[piped], snap.task_req[piped])
            np.add.at(used, task_node[piped], snap.task_req[piped])
            np.add.at(tc, task_node[piped], 1)
            jj = snap.task_job[piped]
            np.add.at(self.job_alloc, jj, snap.task_req[piped])
            np.add.at(self.queue_alloc, snap.job_queue[jj],
                      snap.task_req[piped])
            np.add.at(self.pipe, jj, 1)
        if be_rows.size:
            np.add.at(tc, be_nodes, 1)
            np.add.at(self.occ, aux["pod_j"][be_rows], 1)
        idle = np.maximum(idle, 0.0)
        releasing = np.maximum(releasing, 0.0)
        self.state = self.state._replace(
            idle=jnp.asarray(idle.astype(np.float32)),
            releasing=jnp.asarray(releasing.astype(np.float32)),
            used=jnp.asarray(used.astype(np.float32)),
            task_count=jnp.asarray(tc.astype(np.int32)),
            job_alloc=jnp.asarray(self.job_alloc.astype(np.float32)),
            job_occupied=jnp.asarray(self.occ.astype(np.int32)),
            queue_alloc=jnp.asarray(self.queue_alloc.astype(np.float32)),
        )
        self.advanced = True

    # -- order fns (session.job_order_fn / queue_order_fn over arrays) -------

    def _job_ready(self, j: int) -> bool:
        return self.occ[j] >= self.job_min[j]

    def job_pipelined(self, j: int) -> bool:
        if not self.gang_pipelined:
            return True
        return self.occ[j] + self.pipe[j] >= self.job_min[j]

    def _job_share(self, j: int) -> float:
        return _share(self.job_alloc[j], self.total)

    def _job_less(self, l: int, r: int) -> bool:
        for key in self.probe.job_key_order:
            if key == "priority":
                lp, rp = self.job_prio[l], self.job_prio[r]
                if lp != rp:
                    return bool(lp > rp)
            elif key == "gang":
                lr, rr = self._job_ready(l), self._job_ready(r)
                if lr != rr:
                    return rr  # not-ready schedules first (gang.py:48-57)
            elif key == "drf":
                ls, rs = self._job_share(l), self._job_share(r)
                if ls != rs:
                    return ls < rs
        # creation order == job index (snapshot job order); uid never ties
        return l < r

    def _queue_share(self, q: int) -> float:
        return _share(self.queue_alloc[q], self.deserved[q])

    def _queue_less(self, l: int, r: int) -> bool:
        if self.has_proportion:
            ls, rs = self._queue_share(l), self._queue_share(r)
            if ls != rs:
                return ls < rs
        # queue index order == sorted-uid order (build_fast_snapshot)
        return l < r

    def overused(self, q: int) -> bool:
        if not self.has_proportion:
            return False
        return _less_equal(self.deserved[q], self.queue_alloc[q], self.eps)

    # -- one preemptor's device solve ----------------------------------------

    def attempt(self, t: int, mode: str):
        """Returns (ok, clean).  On ok the state advanced and the decision
        is recorded in the PENDING lists (committed by the caller)."""
        from volcano_tpu.scheduler.victim_kernels import victim_step

        import jax

        snap = self.snap
        jt = int(snap.task_job[t])
        qt = int(snap.job_queue[jt])
        kw = self.kw_reclaim if mode == "reclaim" else self.kw_preempt
        out_state, assigned, nstar, vmask, clean = victim_step(
            self.consts, self.state, self.task_req_dev[t],
            int(snap.task_class[t]), jt, qt, mode=mode, **kw,
        )
        # ONE device round trip for all control-flow outputs (per-output
        # np.asarray would pay a tunnel RTT each)
        assigned, nstar, vmask, clean = jax.device_get(
            (assigned, nstar, vmask, clean)
        )
        if not bool(clean):
            return False, False
        if not bool(assigned):
            return False, True
        self.state = out_state
        nstar = int(nstar)
        vidx = np.nonzero(vmask)[0]
        # eviction record order: preempt drains the reversed task-order
        # queue (prio asc, rank desc); reclaim evicts in pool (insertion)
        # order — tensor_actions._VictimDriver.attempt's exact rule
        if mode == "reclaim":
            vlist = sorted(int(i) for i in vidx)
        elif kw["order_by_priority"]:
            vlist = sorted(
                (int(i) for i in vidx),
                key=lambda i: (snap.run_prio[i], -snap.run_rank[i]),
            )
        else:
            vlist = sorted((int(i) for i in vidx),
                           key=lambda i: -snap.run_rank[i])

        # host order-key bookkeeping (the object path's event handlers)
        t_req = snap.task_req[t]
        if vidx.size:
            vjobs = snap.run_job[vidx]
            np.subtract.at(self.job_alloc, vjobs, snap.run_req[vidx])
            np.subtract.at(self.occ, vjobs, 1)
            vq = snap.job_queue[vjobs]
            ok_q = vq >= 0
            if ok_q.any():
                np.subtract.at(self.queue_alloc, vq[ok_q],
                               snap.run_req[vidx[ok_q]])
            self.run_live[vidx] = False
        self.job_alloc[jt] += t_req
        if qt >= 0:
            self.queue_alloc[qt] += t_req
        self.pipe[jt] += 1

        reason = "reclaim" if mode == "reclaim" else "preempt"
        self.evictions.extend((i, reason) for i in vlist)
        self.pipelines.append((t, nstar))
        return True, True

    # -- statement (functional checkpoint) -----------------------------------

    def checkpoint(self):
        return (
            self.state, self.occ.copy(), self.pipe.copy(),
            self.job_alloc.copy(), self.queue_alloc.copy(),
            self.run_live.copy(), len(self.evictions), len(self.pipelines),
        )

    def restore(self, ckpt) -> None:
        (self.state, self.occ, self.pipe, self.job_alloc, self.queue_alloc,
         self.run_live, ne, np_) = ckpt
        del self.evictions[ne:]
        del self.pipelines[np_:]

    # -- the passes ----------------------------------------------------------

    def _sched_jobs(self):
        """Job indices the contention loops visit, in session iteration
        order: schedulable PodGroup phase (enqueue's admissions included),
        queue always known (queue-less jobs were dropped at build)."""
        snap = self.snap
        return [
            j for j in range(self.n_jobs) if snap.job_schedulable[j]
        ]

    def _pending_rows(self, j: int, placed_mask: Optional[np.ndarray]):
        """This job's pending express rows in task order; ``placed_mask``
        (by task row) excludes rows the solve placed (preempt runs on the
        post-allocate pending set)."""
        snap = self.snap
        start, n = int(snap.job_start[j]), int(snap.job_ntasks[j])
        rows = range(start, start + n)
        if placed_mask is None:
            return deque(rows)
        return deque(r for r in rows if not placed_mask[r])

    def reclaim_pass(self) -> bool:
        """reclaim.go:42-201 / tensor_actions.reclaim: queue-ordered, one
        job + one task per queue visit, re-push the queue on success.
        Returns False when the object machinery must take the whole cycle
        (kernel-inexpressible case encountered); nothing was published."""
        aux = self.aux
        pend = aux["pend_nonbe_per_job"]
        queues_seen: List[int] = []
        jobs_by_q: Dict[int, PriorityQueue] = {}
        tasks_by_job: Dict[int, deque] = {}
        for j in self._sched_jobs():
            q = int(self.job_queue[j])
            if q not in jobs_by_q:
                queues_seen.append(q)
                jobs_by_q[q] = PriorityQueue(self._job_less)
            if pend[j] > 0:
                jobs_by_q[q].push(j)
                tasks_by_job[j] = self._pending_rows(j, None)

        qpq = PriorityQueue(self._queue_less)
        for q in queues_seen:
            qpq.push(q)
        while not qpq.empty():
            q = qpq.pop()
            if self.overused(q):
                continue
            jobs = jobs_by_q.get(q)
            if jobs is None or jobs.empty():
                continue
            j = jobs.pop()
            tasks = tasks_by_job.get(j)
            if tasks is None or not tasks:
                continue
            t = tasks.popleft()
            ok, clean = self.attempt(t, "reclaim")
            if not clean:
                return False
            if ok:
                qpq.push(q)
        return True

    def preempt_pass(self, placed_mask: np.ndarray) -> bool:
        """preempt.go:45-273 / tensor_actions.preempt: phase 1 same-queue
        cross-job preemption under statement semantics, phase 2 within-job.
        Returns False when the object sub-cycle must take over (nothing
        recorded by this pass survives — the caller discards)."""
        aux = self.aux
        pend = aux["pend_nonbe_per_job"]
        start_ckpt = self.checkpoint()
        queues_seen: List[int] = []
        preemptors: Dict[int, PriorityQueue] = {}
        tasks_by_job: Dict[int, deque] = {}
        under_request: List[int] = []
        for j in self._sched_jobs():
            q = int(self.job_queue[j])
            if q not in queues_seen:
                queues_seen.append(q)
            if pend[j] > 0:
                rows = self._pending_rows(j, placed_mask)
                if not rows:
                    continue  # everything placed: not a preemptor anymore
                if q not in preemptors:
                    preemptors[q] = PriorityQueue(self._job_less)
                preemptors[q].push(j)
                under_request.append(j)
                tasks_by_job[j] = rows

        for q in queues_seen:
            while True:
                jobs = preemptors.get(q)
                if jobs is None or jobs.empty():
                    break
                j = jobs.pop()
                ckpt = self.checkpoint()
                assigned = False
                while tasks_by_job[j]:
                    t = tasks_by_job[j].popleft()
                    before = len(self.evictions)
                    ok, clean = self.attempt(t, "queue")
                    if not clean:
                        self.restore(start_ckpt)
                        return False
                    if ok:
                        assigned = True
                        metrics.update_preemption_victims(
                            len(self.evictions) - before
                        )
                        metrics.register_preemption_attempt()
                    if self.job_pipelined(j):
                        break  # commit: records stay
                if not self.job_pipelined(j):
                    self.restore(ckpt)
                    continue
                if assigned:
                    jobs.push(j)

            # phase 2: within-job preemption over ALL under-request jobs —
            # INSIDE the queue loop, as the reference has it
            # (preempt.go:146-168 sits inside `for _, queue := range
            # queues`), so a later queue's phase 1 sees the task queues
            # phase 2 already drained
            for j in under_request:
                while True:
                    tasks = tasks_by_job.get(j)
                    if tasks is None or not tasks:
                        break
                    t = tasks.popleft()
                    ok, clean = self.attempt(t, "job")
                    if not clean:
                        self.restore(start_ckpt)
                        return False
                    if ok:
                        metrics.register_preemption_attempt()
                    else:
                        break
        return True

    # -- integration back into the fast snapshot -----------------------------

    def fold_into_snapshot(self, m) -> None:
        """After the reclaim pass: write the advanced node/job/queue state
        back into the snapshot arrays the allocate solve reads, and re-pack
        the task arrays without the pipelined preemptor rows (the kernels
        walk contiguous per-job row ranges)."""
        snap, aux = self.snap, self.aux
        snap.node_idle[:] = np.asarray(self.state.idle)
        snap.node_releasing[:] = np.asarray(self.state.releasing)
        snap.node_used[:] = np.asarray(self.state.used)
        snap.node_task_count[:] = np.asarray(self.state.task_count)
        snap.job_alloc_init[:] = self.job_alloc.astype(np.float32)
        snap.queue_alloc_init[:] = self.queue_alloc.astype(np.float32)
        # evictions dropped victims from ready counts; the solve's gang
        # admission must see it
        snap.job_ready_init[:] = self.occ.astype(np.int32)
        if not self.pipelines:
            return
        consumed = {t for t, _ in self.pipelines}
        pe_rows = aux["pe_rows"]
        keep = np.asarray(
            [i for i in range(pe_rows.size) if i not in consumed], np.int64
        )
        _rebuild_task_arrays(m, self.fc, snap, aux, pe_rows[keep])
        self.refresh_for_preempt(snap)


def _rebuild_task_arrays(m, fc, snap, aux, new_pe_rows) -> None:
    """Re-pack snap's task/class arrays over the surviving pending rows."""
    from volcano_tpu.scheduler.fastpath import _task_arrays

    n_jobs = aux["n_jobs"]
    R = snap.node_idle.shape[1]
    N = snap.node_idle.shape[0]
    ta = _task_arrays(
        m, new_pe_rows, aux["pod_j"], n_jobs, N, R, aux["node_rows"],
        aux["n_nodes"], fc.nodeaffinity_weight,
        snap.job_start, snap.job_ntasks,
    )
    snap.task_req = ta["task_req"]
    snap.task_job = ta["task_job"]
    snap.task_class = ta["task_class"]
    snap.task_valid = ta["task_valid"]
    snap.class_node_mask = ta["class_mask"]
    snap.class_node_score = ta["class_score"]
    snap.task_uids = ta["pod_keys"]
    aux["pe_rows"] = new_pe_rows
    aux["n_tasks"] = ta["n_tasks"]
