"""Array-native preempt/reclaim for the fast cycle (VERDICT r2 next #2,
device-resident storm driver per VERDICT r3 next #1).

The object path's contention actions (tensor_actions.preempt/reclaim) keep
the reference's host loop structure — per-queue priority queues, Statement
commit/discard, one victim solve per preemptor — but run inside a full
object Session whose open/close costs O(cluster) Python.  This module runs
the SAME loop structure directly against the fast mirror's arrays, and —
unlike round 3's driver, which paid one host<->device round trip per
preemptor (~2,000 round trips = the 356 s contended cycle) — the ENTIRE
pass now runs as one device program:

  * ``victim_kernels.reclaim_solve`` / ``preempt_solve`` execute the whole
    queue-ordered walk (job selection by the session order keys, statement
    checkpoint/rollback, two-phase preemption) inside a ``lax.while_loop``,
    so a storm costs ONE dispatch + ONE ``device_get`` regardless of size;
  * Statement semantics are functional: the device ``VictimState`` tuple is
    immutable, so checkpoint = carrying the reference and discard =
    selecting it back (SURVEY §7 step 6's "trivially pure in JAX" note);
  * the kernels record each decision as (victim -> ok-attempt seq,
    preemptor task -> node + seq) arrays; the host reconstructs the ordered
    eviction/pipeline lists from one fetch;
  * storms wider than ``CONTENTION_BATCH_THRESHOLD`` preemptor tasks run
    ``victim_kernels.preempt_rounds`` first — the contention analogue of
    the batched allocate solve: rounds of parallel placement against
    per-node evictable-capacity curves, ~3 orders of magnitude cheaper
    than per-attempt exact solves at bench scale (the exact loop's
    O(pool) passes per attempt cost ~10 ms each at a 131k pool).  The
    exact loop mops up whatever the rounds could not serve, and remains
    the parity oracle below the threshold (and always under
    ``solveMode: exact``);
  * anything the kernel cannot express — a host walk that would strand
    evictions on non-covering nodes (``clean=False``, see
    victim_kernels.py) — aborts the pass with nothing published; the
    caller falls back to the object machinery, which recomputes the same
    decisions from the store.  Best-effort (empty-request) preemptors ARE
    expressible: the core's DO-while prefix takes exactly one victim for
    them like the host loop, and fastpath re-packs their rows into the
    task arrays before the preempt pass.

Divergences from the object path, same documented class as the fast
allocate passes: eviction-order ties break by pod *arrival* rank rather
than uid string order, and job/queue selection takes the exact
lexicographic minimum of the session order keys each step (the object path
pops a lazy binary heap whose stale entries can reorder under mutating
DRF/proportion shares — kernels.allocate_solve's existing, parity-tested
divergence).  Shares compare in f32 on device vs f64 on host, inside the
same ε tolerances.

Reference loops mirrored: preempt.go:45-273 (two-phase preemption,
statement per preemptor job), reclaim.go:42-201 (queue-ordered cross-queue
reclaim, one task per queue visit).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from volcano_tpu import vtprof
from volcano_tpu.scheduler import metrics

# storms above this many preemptor tasks take the batched-rounds kernel
# first (solve_mode "auto"; "batch" always does, "exact" never) — the
# exact storm loop costs several O(pool) passes per preemptor, which at
# bench scale is ~10 ms per attempt
CONTENTION_BATCH_THRESHOLD = 64


def contention_static_args(conf, probe) -> dict:
    """The storm solves' static jit arguments, derived from the conf/probe.

    Shared by FastContention (which drives the kernels) and
    Scheduler.prewarm (which compiles them ahead of the first contended
    cycle) so the two can never warm different variants."""
    veto_p, veto_r = probe.victim_vetoes()
    return dict(
        kw_preempt=dict(
            use_gang="gang" in veto_p,
            use_drf="drf" in veto_p,
            use_conformance="conformance" in veto_p,
            order_by_priority=probe.task_order_by_priority,
        ),
        kw_reclaim=dict(
            use_gang="gang" in veto_r,
            use_prop="proportion" in veto_r,
            use_conformance="conformance" in veto_r,
            order_by_priority=probe.task_order_by_priority,
        ),
        gang_pipelined=any(
            opt.name == "gang" and opt.enabled_job_pipelined
            for tier in conf.tiers for opt in tier.plugins
        ),
        has_proportion=probe.enabled.get("proportion", False),
        job_key_order=tuple(probe.job_key_order),
    )


class FastContention:
    """One cycle's contention driver over the fast snapshot.

    Owns the device VictimConsts plus the host-resident VictimState and
    order-key mirrors (occupied/pipelined counts, drf job allocations,
    proportion queue allocations) and the committed eviction/pipeline
    records.  Build it after enqueue; run ``reclaim_pass`` before the
    allocate solve and ``preempt_pass`` after backfill (conf action order).
    """

    def __init__(self, fc, snap, aux, deserved: np.ndarray):
        import jax.numpy as jnp

        self.fc = fc
        self.snap = snap
        self.aux = aux
        self.jnp = jnp
        probe = fc.probe
        self.probe = probe
        n_jobs = aux["n_jobs"]
        self.n_jobs = n_jobs
        self.job_prio = snap.job_priority

        # host order-key state (the plugin attrs the object path tracks via
        # event handlers)
        self.occ = snap.job_ready_init.astype(np.int64).copy()
        self.pipe = np.zeros(self.occ.shape[0], np.int64)
        self.job_alloc = snap.job_alloc_init.astype(np.float64).copy()
        self.queue_alloc = snap.queue_alloc_init.astype(np.float64).copy()

        # committed decisions (published by the caller at cycle end)
        self.evictions: List[Tuple[int, str]] = []  # (pool idx, reason)
        self.pipelines: List[Tuple[int, int]] = []  # (task row, node idx)
        self.advanced = False  # advance_post_solve folded the solve in

        static = contention_static_args(fc.conf, probe)
        self.kw_preempt = static["kw_preempt"]
        self.kw_reclaim = static["kw_reclaim"]
        self.gang_pipelined = static["gang_pipelined"]
        self.has_proportion = static["has_proportion"]
        self.job_key_order = static["job_key_order"]

        from volcano_tpu.scheduler.victim_kernels import VictimConsts, VictimState

        # conf mesh: node planes shard only when every contention
        # dispatch is the round-vectorized kernel (solveMode: batch) —
        # the exact scalar loops would turn each step's node gathers
        # into cross-device collectives (tensor_backend.placement_fn)
        devn = probe.placement_fn(fc.conf.solve_mode == "batch")
        self._devn = devn
        self.consts = VictimConsts(
            run_req=jnp.asarray(snap.run_req),
            run_node=jnp.asarray(snap.run_node),
            run_job=jnp.asarray(snap.run_job),
            run_prio=jnp.asarray(snap.run_prio),
            run_rank=jnp.asarray(snap.run_rank),
            run_evictable=jnp.asarray(snap.run_evictable),
            job_queue=jnp.asarray(snap.job_queue),
            job_min=jnp.asarray(snap.job_min_available),
            node_alloc=devn(snap.node_alloc, "node_alloc"),
            node_max_tasks=devn(snap.node_max_tasks, "node_max_tasks"),
            node_valid=devn(snap.node_valid, "node_valid"),
            class_mask=devn(snap.class_node_mask, "class_mask"),
            class_score=devn(snap.class_node_score, "class_score"),
            queue_deserved=jnp.asarray(deserved.astype(np.float32)),
            total=jnp.asarray(snap.total),
            eps=jnp.asarray(snap.eps),
            w_least=jnp.float32(probe.score_weights()[0]),
            w_balanced=jnp.float32(probe.score_weights()[1]),
        )
        # one upload for every preemptor's request row: the storm solves
        # gather on device instead of paying a transfer per attempt
        self.task_req_dev = jnp.asarray(snap.task_req)
        self.task_class_dev = jnp.asarray(snap.task_class)
        # the mutable session state stays HOST-resident between kernel
        # calls (the kernels upload it; outputs come back in the one
        # batched fetch) — copies, because fold_into_snapshot mutates the
        # snap arrays these start from
        self.state = VictimState(
            run_live=snap.run_valid.copy(),
            idle=snap.node_idle.copy(),
            releasing=snap.node_releasing.copy(),
            used=snap.node_used.copy(),
            task_count=snap.node_task_count.copy(),
            job_alloc=snap.job_alloc_init.copy(),
            job_occupied=snap.job_ready_init.copy(),
            queue_alloc=snap.queue_alloc_init.copy(),
        )

    # -- consts rebuild after the task re-pack -------------------------------

    def refresh_for_preempt(self, snap) -> None:
        """The reclaim pass re-packed the task/class arrays (consumed
        preemptor rows); the preempt pass gathers t_cls against the NEW
        class indexing, so the consts' class planes must follow."""
        jnp = self.jnp
        devn = self._devn
        self.consts = self.consts._replace(
            class_mask=devn(snap.class_node_mask, "class_mask"),
            class_score=devn(snap.class_node_score, "class_score"),
        )
        self.task_req_dev = jnp.asarray(snap.task_req)
        self.task_class_dev = jnp.asarray(snap.task_class)

    def advance_post_solve(self, task_node, task_kind, ready,
                           be_rows, be_nodes) -> None:
        """Fold the allocate solve's and backfill's session effects into the
        victim state — the object path gets this from rebuilding the
        snapshot off the post-allocate session (tensor_actions preempt's
        _VictimDriver._load).  Allocations consume idle and count ready;
        pipelines consume releasing and count waiting; backfill placements
        count ready and a task slot."""
        snap, aux = self.snap, self.aux
        idle = np.asarray(self.state.idle).copy()
        releasing = np.asarray(self.state.releasing).copy()
        used = np.asarray(self.state.used).copy()
        tc = np.asarray(self.state.task_count).copy()

        # end-state ready counts: the solve's own output (it already folds
        # the job_ready_init this state was built from, including any
        # reclaim evictions), plus backfill below
        self.occ = np.asarray(ready).astype(np.int64).copy()

        placed = np.nonzero(task_kind == 1)[0]
        piped = np.nonzero(task_kind == 2)[0]
        if placed.size:
            np.subtract.at(idle, task_node[placed], snap.task_req[placed])
            np.add.at(used, task_node[placed], snap.task_req[placed])
            np.add.at(tc, task_node[placed], 1)
            jj = snap.task_job[placed]
            np.add.at(self.job_alloc, jj, snap.task_req[placed])
            np.add.at(self.queue_alloc, snap.job_queue[jj],
                      snap.task_req[placed])
        if piped.size:
            np.subtract.at(releasing, task_node[piped], snap.task_req[piped])
            np.add.at(used, task_node[piped], snap.task_req[piped])
            np.add.at(tc, task_node[piped], 1)
            jj = snap.task_job[piped]
            np.add.at(self.job_alloc, jj, snap.task_req[piped])
            np.add.at(self.queue_alloc, snap.job_queue[jj],
                      snap.task_req[piped])
            np.add.at(self.pipe, jj, 1)
        if be_rows.size:
            np.add.at(tc, be_nodes, 1)
            np.add.at(self.occ, aux["pod_j"][be_rows], 1)
        idle = np.maximum(idle, 0.0)
        releasing = np.maximum(releasing, 0.0)
        self.state = self.state._replace(
            idle=idle.astype(np.float32),
            releasing=releasing.astype(np.float32),
            used=used.astype(np.float32),
            task_count=tc.astype(np.int32),
            job_alloc=self.job_alloc.astype(np.float32),
            job_occupied=self.occ.astype(np.int32),
            queue_alloc=self.queue_alloc.astype(np.float32),
        )
        self.advanced = True

    # -- shared host plumbing around the storm kernels -----------------------

    def _schedulable(self) -> np.ndarray:
        J = self.snap.job_queue.shape[0]
        sched = np.zeros(J, bool)
        sched[: self.n_jobs] = self.snap.job_schedulable[: self.n_jobs]
        return sched

    def _pend_per_job(self, key: str = "pend_nonbe_per_job") -> np.ndarray:
        J = self.snap.job_queue.shape[0]
        pend = np.zeros(J, np.int64)
        src = np.asarray(self.aux[key])
        n = min(J, src.shape[0])
        pend[:n] = src[:n]
        return pend

    def _absorb(self, out_s, pipe) -> None:
        """Adopt a storm solve's final state as the session state and
        refresh the host order-key mirrors from it."""
        from volcano_tpu.scheduler.victim_kernels import VictimState

        self.state = VictimState(*[np.asarray(x) for x in out_s])
        self.pipe = np.asarray(pipe).astype(np.int64)
        self.occ = np.asarray(self.state.job_occupied).astype(np.int64)
        self.job_alloc = np.asarray(self.state.job_alloc).astype(np.float64)
        self.queue_alloc = np.asarray(
            self.state.queue_alloc
        ).astype(np.float64)

    def _append_records(self, evict_att, pipe_node, pipe_att,
                        reason: str) -> None:
        """Rebuild the ordered decision lists from the kernel's per-row
        attempt-sequence records.  Eviction record order: preempt drains
        the reversed task-order queue (prio asc, rank desc); reclaim
        evicts in pool (insertion) order — tensor_actions._VictimDriver's
        exact rule, applied within each ok-attempt group."""
        snap = self.snap
        ev = np.nonzero(evict_att >= 0)[0]
        if ev.size:
            if reason == "reclaim":
                order = np.lexsort((ev, evict_att[ev]))
            elif self.kw_preempt["order_by_priority"]:
                order = np.lexsort(
                    (-snap.run_rank[ev], snap.run_prio[ev], evict_att[ev])
                )
            else:
                order = np.lexsort((-snap.run_rank[ev], evict_att[ev]))
            self.evictions.extend((int(i), reason) for i in ev[order])
        pt = np.nonzero(pipe_att >= 0)[0]
        if pt.size:
            for t in pt[np.argsort(pipe_att[pt], kind="stable")]:
                self.pipelines.append((int(t), int(pipe_node[t])))

    # -- the passes ----------------------------------------------------------

    def reclaim_pass(self) -> bool:
        """reclaim.go:42-201 / tensor_actions.reclaim as ONE device
        program: queue-ordered, one job + one task per queue visit,
        re-arm the queue on success.  Returns False when the object
        machinery must take the whole cycle (kernel-inexpressible case
        encountered); nothing was published."""
        from volcano_tpu.scheduler.victim_kernels import reclaim_solve

        snap = self.snap
        sched = self._schedulable()
        job_cand = sched & (self._pend_per_job() > 0)
        Q = snap.queue_alloc_init.shape[0]
        queue_live = np.zeros(Q, bool)
        qs = snap.job_queue[sched]
        qs = qs[qs >= 0]
        if qs.size:
            queue_live[qs] = True
        if not job_cand.any() or not queue_live.any():
            return True
        prof = vtprof.PROFILER
        tok = prof.dispatch_begin(reclaim_solve) if prof is not None \
            else None
        out_s, pipe, rec, abort = reclaim_solve(
            self.consts, self.state,
            self.task_req_dev, self.task_class_dev,
            snap.job_start.astype(np.int32),
            self.job_prio.astype(np.int32),
            job_cand, queue_live, self.pipe.astype(np.int32),
            use_gang=self.kw_reclaim["use_gang"],
            use_prop=self.kw_reclaim["use_prop"],
            use_conformance=self.kw_reclaim["use_conformance"],
            order_by_priority=self.kw_reclaim["order_by_priority"],
            has_proportion=self.has_proportion,
            job_key_order=self.job_key_order,
        )
        if tok is not None:
            prof.dispatch_end(tok, "reclaim_solve", phase="reclaim")
        # ONE device round trip for the whole pass (vtprof.device_get is
        # the sanctioned whole-pass fetch boundary)
        out_s, pipe, ea, pn, pa, abort = vtprof.device_get(
            (out_s, pipe, rec.evict_att, rec.pipe_node, rec.pipe_att, abort),
            kernel="reclaim_solve", phase="reclaim",
        )
        if bool(abort):
            return False
        self._absorb(out_s, pipe)
        self._append_records(ea, pn, pa, "reclaim")
        return True

    def preempt_pass(self, placed_mask: np.ndarray) -> bool:
        """preempt.go:45-273 / tensor_actions.preempt as ONE device
        program: phase 1 same-queue cross-job preemption under statement
        semantics, phase 2 within-job.  Returns False when the object
        sub-cycle must take over (nothing recorded by this pass survives —
        the kernel aborted before recording)."""
        from volcano_tpu.scheduler.victim_kernels import preempt_solve

        snap = self.snap
        J = snap.job_queue.shape[0]
        T = snap.task_req.shape[0]
        sched = self._schedulable()
        attempt_rows = snap.task_valid & ~placed_mask
        if attempt_rows.any():
            unplaced = np.bincount(
                snap.task_job[attempt_rows], minlength=J
            )[:J]
        else:
            unplaced = np.zeros(J, np.int64)
        # ANY pending task (incl. best-effort) keeps a job a preemptor —
        # the host preemptor walk includes empty-request tasks, which the
        # pre-preempt re-pack placed into these arrays
        pend_ok = sched & (self._pend_per_job("pend_any_per_job") > 0)
        is_pre = pend_ok & (unplaced > 0)
        under = np.nonzero(is_pre)[0].astype(np.int32)
        nu = under.size
        # queues in first-appearance order over schedulable jobs —
        # preempt.go iterates the queue set it discovered, not by share
        jq = snap.job_queue[: self.n_jobs][
            snap.job_schedulable[: self.n_jobs]
        ]
        jq = jq[jq >= 0]
        _, first = np.unique(jq, return_index=True)
        qorder = jq[np.sort(first)].astype(np.int32)
        nq = qorder.size
        if nu == 0 or nq == 0:
            return True
        Q = snap.queue_alloc_init.shape[0]
        under_pad = np.zeros(J, np.int32)
        under_pad[:nu] = under
        qpad = np.zeros(Q, np.int32)
        qpad[:nq] = qorder

        # large storms: the batched-rounds kernel serves the bulk, the
        # exact loop mops up stragglers (or everything, below threshold)
        mode = self.fc.conf.solve_mode
        n_storm = int(unplaced[is_pre].sum())
        if mode == "batch" or (
            mode == "auto" and n_storm > CONTENTION_BATCH_THRESHOLD
        ):
            # rounds-eligible jobs only: a queueless job's commit would
            # credit queue 0 (the exact kernels guard qt < 0), and a gang
            # whose remaining min-need exceeds one round's proposal window
            # can never satisfy the all-or-nothing commit — both classes
            # go straight to the exact loop instead of burning rounds
            from volcano_tpu.scheduler.victim_kernels import (
                ROUNDS_P_CHUNK,
            )

            need = np.maximum(
                snap.job_min_available.astype(np.int64)
                - self.occ - self.pipe, 0,
            )
            # jobs with a best-effort pending row take the exact loop: the
            # rounds kernel's capacity math has no do-while eviction (an
            # empty request consumes zero capacity => zero victims)
            be_jobs = np.zeros(J, bool)
            pe = self.aux["pe_rows"]
            n = min(T, pe.size)
            if n:
                is_be = np.zeros(T, bool)
                is_be[:n] = self.fc.mirror.p_best_effort[pe[:n]]
                rows_be = np.nonzero(is_be & snap.task_valid)[0]
                if rows_be.size:
                    be_jobs[np.unique(snap.task_job[rows_be])] = True
            eligible = (
                is_pre & (snap.job_queue >= 0) & (need <= ROUNDS_P_CHUNK)
                & ~be_jobs
            )
            if eligible.any():
                attempt_rows = self._rounds_stage(attempt_rows, eligible)
            left = attempt_rows & is_pre[snap.task_job] & snap.task_valid
            if not left.any():
                return True
            counts_left = np.bincount(
                snap.task_job[left], minlength=J
            )[:J]
            is_pre = pend_ok & (counts_left > 0)
            if not is_pre.any():
                return True
        prof = vtprof.PROFILER
        tok = prof.dispatch_begin(preempt_solve) if prof is not None \
            else None
        out_s, pipe, rec, att_total, last_v, any_p1, abort = preempt_solve(
            self.consts, self.state,
            self.task_req_dev, self.task_class_dev, attempt_rows,
            snap.job_start.astype(np.int32),
            snap.job_ntasks.astype(np.int32),
            self.job_prio.astype(np.int32),
            is_pre, under_pad, np.int32(nu), qpad, np.int32(nq),
            self.pipe.astype(np.int32),
            use_gang=self.kw_preempt["use_gang"],
            use_drf=self.kw_preempt["use_drf"],
            use_conformance=self.kw_preempt["use_conformance"],
            order_by_priority=self.kw_preempt["order_by_priority"],
            job_key_order=self.job_key_order,
            gang_pipelined=self.gang_pipelined,
        )
        if tok is not None:
            prof.dispatch_end(tok, "preempt_solve", phase="preempt")
        (out_s, pipe, ea, pn, pa, att_total, last_v, any_p1,
         abort) = vtprof.device_get(
            (out_s, pipe, rec.evict_att, rec.pipe_node, rec.pipe_att,
             att_total, last_v, any_p1, abort),
            kernel="preempt_solve", phase="preempt",
        )
        if bool(abort):
            return False
        self._absorb(out_s, pipe)
        if bool(any_p1):
            metrics.update_preemption_victims(int(last_v))
        for _ in range(int(att_total)):
            metrics.register_preemption_attempt()
        self._append_records(ea, pn, pa, "preempt")
        return True

    def _rounds_stage(self, attempt_rows: np.ndarray,
                      is_pre: np.ndarray) -> np.ndarray:
        """Run the batched-rounds kernel over the storm and absorb what it
        committed; returns the surviving attemptable-row mask for the
        exact tail.  Never aborts — rounds are capacity-safe by
        construction, and anything they could not serve is simply left
        for the exact loop."""
        from volcano_tpu.scheduler.victim_kernels import preempt_rounds

        snap = self.snap
        J = snap.job_queue.shape[0]
        T = snap.task_req.shape[0]
        rows = np.nonzero(attempt_rows & is_pre[snap.task_job])[0]
        counts = np.bincount(
            snap.task_job[rows], minlength=J
        )[:J].astype(np.int32)
        pstart = np.zeros(J, np.int32)
        if J > 1:
            pstart[1:] = np.cumsum(counts[:-1]).astype(np.int32)
        rows_packed = np.zeros(T, np.int32)
        rows_packed[: rows.size] = rows
        prof = vtprof.PROFILER
        tok = prof.dispatch_begin(preempt_rounds) if prof is not None \
            else None
        out_s, pipe, rec, att_total, last_v, any_commit, _, _ = (
            preempt_rounds(
                self.consts, self.state,
                self.task_req_dev, self.task_class_dev,
                rows_packed, pstart, counts,
                self.job_prio.astype(np.int32),
                is_pre, self.pipe.astype(np.int32),
                use_gang=self.kw_preempt["use_gang"],
                use_drf=self.kw_preempt["use_drf"],
                use_conformance=self.kw_preempt["use_conformance"],
                order_by_priority=self.kw_preempt["order_by_priority"],
                job_key_order=self.job_key_order,
                gang_pipelined=self.gang_pipelined,
            )
        )
        if tok is not None:
            prof.dispatch_end(tok, "preempt_rounds", phase="preempt")
        (out_s, pipe, ea, pn, pa, att_total, last_v,
         any_commit) = vtprof.device_get(
            (out_s, pipe, rec.evict_att, rec.pipe_node, rec.pipe_att,
             att_total, last_v, any_commit),
            kernel="preempt_rounds", phase="preempt",
        )
        if int(att_total) == 0:
            return attempt_rows
        self._absorb(out_s, pipe)
        if bool(any_commit):
            metrics.update_preemption_victims(int(last_v))
        for _ in range(int(att_total)):
            metrics.register_preemption_attempt()
        self._append_records(ea, pn, pa, "preempt")
        return attempt_rows & ~(pa >= 0)

    # -- integration back into the fast snapshot -----------------------------

    def fold_into_snapshot(self, m) -> None:
        """After the reclaim pass: write the advanced node/job/queue state
        back into the snapshot arrays the allocate solve reads, and re-pack
        the task arrays without the pipelined preemptor rows (the kernels
        walk contiguous per-job row ranges)."""
        snap, aux = self.snap, self.aux
        snap.node_idle[:] = np.asarray(self.state.idle)
        snap.node_releasing[:] = np.asarray(self.state.releasing)
        snap.node_used[:] = np.asarray(self.state.used)
        snap.node_task_count[:] = np.asarray(self.state.task_count)
        snap.job_alloc_init[:] = self.job_alloc.astype(np.float32)
        snap.queue_alloc_init[:] = self.queue_alloc.astype(np.float32)
        # evictions dropped victims from ready counts; the solve's gang
        # admission must see it
        snap.job_ready_init[:] = self.occ.astype(np.int32)
        if not self.pipelines:
            return
        consumed = {t for t, _ in self.pipelines}
        pe_rows = aux["pe_rows"]
        keep = np.asarray(
            [i for i in range(pe_rows.size) if i not in consumed], np.int64
        )
        _rebuild_task_arrays(m, self.fc, snap, aux, pe_rows[keep])
        self.refresh_for_preempt(snap)


def _rebuild_task_arrays(m, fc, snap, aux, new_pe_rows) -> None:
    """Re-pack snap's task/class arrays over the surviving pending rows."""
    from volcano_tpu.scheduler.fastpath import _task_arrays

    n_jobs = aux["n_jobs"]
    R = snap.node_idle.shape[1]
    N = snap.node_idle.shape[0]
    ta = _task_arrays(
        m, new_pe_rows, aux["pod_j"], n_jobs, N, R, aux["node_rows"],
        aux["n_nodes"], fc.nodeaffinity_weight,
        snap.job_start, snap.job_ntasks,
        min_T=snap.task_req.shape[0],
    )
    snap.task_req = ta["task_req"]
    snap.task_job = ta["task_job"]
    snap.task_class = ta["task_class"]
    snap.task_valid = ta["task_valid"]
    snap.class_node_mask = ta["class_mask"]
    snap.class_node_score = ta["class_score"]
    snap.task_uids = ta["pod_keys"]
    aux["pe_rows"] = new_pe_rows
    aux["n_tasks"] = ta["n_tasks"]
