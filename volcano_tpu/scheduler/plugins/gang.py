"""Gang plugin: all-or-nothing co-scheduling on min_available.

Parity: reference KB/pkg/scheduler/plugins/gang/gang.go:47-162.
"""

from __future__ import annotations

from volcano_tpu.scheduler import metrics
from volcano_tpu.scheduler.framework import Plugin
from volcano_tpu.scheduler.session import Session, ValidateResult

NOT_ENOUGH_PODS = "NotEnoughPods"
NOT_ENOUGH_RESOURCES = "NotEnoughResources"


class GangPlugin(Plugin):
    name = "gang"

    def on_session_open(self, ssn: Session) -> None:
        def valid_job_fn(job):
            vtn = job.valid_task_num()
            if vtn < job.min_available:
                return ValidateResult(
                    passed=False,
                    reason=NOT_ENOUGH_PODS,
                    message=(
                        f"Not enough valid tasks for gang-scheduling, "
                        f"valid: {vtn}, min: {job.min_available}"
                    ),
                )
            return None

        ssn.add_job_valid_fn(self.name, valid_job_fn)

        def preemptable_fn(preemptor, preemptees):
            victims = []
            for preemptee in preemptees:
                job = ssn.jobs[preemptee.job_uid]
                occupied = job.ready_task_num()
                # victim allowed only if its job would stay at/above gang size
                if job.min_available <= occupied - 1 or job.min_available == 1:
                    victims.append(preemptee)
            return victims

        ssn.add_preemptable_fn(self.name, preemptable_fn)
        ssn.add_reclaimable_fn(self.name, preemptable_fn)

        def job_order_fn(l, r):
            l_ready, r_ready = l.ready(), r.ready()
            if l_ready and r_ready:
                return 0
            if l_ready:
                return 1
            if r_ready:
                return -1
            return 0

        ssn.add_job_order_fn(self.name, job_order_fn)
        ssn.add_job_ready_fn(self.name, lambda job: job.ready())
        ssn.add_job_pipelined_fn(self.name, lambda job: job.pipelined())

    def on_session_close(self, ssn: Session) -> None:
        unschedulable_jobs = 0
        for job in ssn.jobs.values():
            if job.ready():
                # clear a stale Unschedulable condition so the next failure
                # episode is a fresh transition (and a fresh event)
                if job.pod_group is not None and any(
                    c.kind == "Unschedulable"
                    for c in job.pod_group.status.conditions
                ):
                    job.pod_group.status.conditions = [
                        c
                        for c in job.pod_group.status.conditions
                        if c.kind != "Unschedulable"
                    ]
            else:
                unready = job.min_available - job.ready_task_num()
                unschedulable_jobs += 1
                metrics.update_unschedule_task_count(job.name, int(unready))
                metrics.register_job_retry(job.name)
                if job.pod_group is not None:
                    from volcano_tpu.api.objects import PodGroupCondition

                    # gang.go:138-139 appends FitError(); "" means the cycle
                    # produced no fit data (quota-blocked job) — append
                    # nothing rather than a misleading "0 nodes" claim
                    fe = job.fit_error()
                    cond = PodGroupCondition(
                        kind="Unschedulable",
                        status="True",
                        reason=NOT_ENOUGH_RESOURCES,
                        message=(
                            f"{unready}/{len(job.tasks)} tasks in gang "
                            f"unschedulable" + (f": {fe}" if fe else "")
                        ),
                    )
                    prev = next(
                        (
                            c
                            for c in job.pod_group.status.conditions
                            if c.kind == "Unschedulable"
                        ),
                        None,
                    )
                    job.pod_group.status.conditions = [
                        c
                        for c in job.pod_group.status.conditions
                        if c.kind != "Unschedulable"
                    ] + [cond]
                    # unschedulable warning event (cache.go:467 analogue) —
                    # only on condition transitions, so a parked gang job
                    # doesn't generate store writes every idle cycle
                    if prev is None or prev.message != cond.message:
                        from volcano_tpu import events

                        events.record(
                            ssn.cache.store, "PodGroup",
                            f"{job.namespace}/{job.name}", "Unschedulable",
                            cond.message, type=events.WARNING,
                        )
        metrics.update_unschedule_job_count(unschedulable_jobs)
