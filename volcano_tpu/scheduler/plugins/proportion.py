"""Proportion plugin: weighted fair queue shares via iterative water-filling.

Parity: reference KB/pkg/scheduler/plugins/proportion/proportion.go:58-243.
Each round, unmet queues split the remaining cluster resources by weight;
a queue whose deserved reaches its request is capped and marked met; repeat
until nothing remains. QueueOrder by share = max_r allocated/deserved;
Overused when deserved <= allocated (epsilon-tolerant); reclaim victims only
while the victim's queue stays at/above its deserved share.
"""

from __future__ import annotations

from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import TaskStatus, allocated_status
from volcano_tpu.scheduler.framework import Plugin
from volcano_tpu.scheduler.session import EventHandler, Session


class _QueueAttr:
    __slots__ = ("uid", "name", "weight", "deserved", "allocated", "request", "share")

    def __init__(self, uid, name, weight):
        self.uid = uid
        self.name = name
        self.weight = weight
        self.deserved = Resource()
        self.allocated = Resource()
        self.request = Resource()
        self.share = 0.0

    def update_share(self):
        res = 0.0
        for rn in self.deserved.names():
            res = max(res, Resource.share(self.allocated.get(rn), self.deserved.get(rn)))
        self.share = res


class ProportionPlugin(Plugin):
    name = "proportion"

    def __init__(self, arguments=None):
        super().__init__(arguments)
        self.total = Resource()
        self.queue_attrs = {}

    def on_session_open(self, ssn: Session) -> None:
        self.total = Resource()
        self.queue_attrs = {}
        for node in ssn.nodes.values():
            self.total.add(node.allocatable)

        # Only queues that have jobs participate (proportion.go:66-99).
        for job in ssn.jobs.values():
            if job.queue not in self.queue_attrs:
                queue = ssn.queues.get(job.queue)
                if queue is None:
                    continue
                self.queue_attrs[job.queue] = _QueueAttr(
                    queue.uid, queue.name, queue.weight
                )
            attr = self.queue_attrs[job.queue]
            for status, tasks in job.task_status_index.items():
                if allocated_status(status):
                    for t in tasks.values():
                        attr.allocated.add(t.resreq)
                        attr.request.add(t.resreq)
                elif status == TaskStatus.PENDING:
                    for t in tasks.values():
                        attr.request.add(t.resreq)

        # water-filling (proportion.go:101-144)
        remaining = self.total.clone()
        met = set()
        while True:
            total_weight = sum(
                a.weight for a in self.queue_attrs.values() if a.uid not in met
            )
            if total_weight == 0:
                break
            deserved_this_round = Resource()
            for attr in self.queue_attrs.values():
                if attr.uid in met:
                    continue
                old_deserved = attr.deserved.clone()
                attr.deserved.add(
                    remaining.clone().multi(attr.weight / total_weight)
                )
                if not attr.deserved.less_equal(attr.request):
                    attr.deserved = Resource.min(attr.deserved, attr.request)
                    met.add(attr.uid)
                attr.update_share()
                delta = attr.deserved.clone()
                # deserved grew monotonically, so subtraction is safe
                delta.milli_cpu -= old_deserved.milli_cpu
                delta.memory -= old_deserved.memory
                for k, v in old_deserved.scalars.items():
                    delta.scalars[k] = delta.scalars.get(k, 0.0) - v
                deserved_this_round.add(delta)
            remaining.milli_cpu -= deserved_this_round.milli_cpu
            remaining.memory -= deserved_this_round.memory
            for k, v in deserved_this_round.scalars.items():
                remaining.scalars[k] = remaining.scalars.get(k, 0.0) - v
            if remaining.is_empty():
                break

        def queue_order_fn(l, r):
            la = self.queue_attrs.get(l.uid)
            ra = self.queue_attrs.get(r.uid)
            ls = la.share if la else 0.0
            rs = ra.share if ra else 0.0
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        ssn.add_queue_order_fn(self.name, queue_order_fn)

        def reclaimable_fn(reclaimer, reclaimees):
            victims = []
            hypothetical = {}
            for reclaimee in reclaimees:
                job = ssn.jobs[reclaimee.job_uid]
                attr = self.queue_attrs.get(job.queue)
                if attr is None:
                    continue
                if job.queue not in hypothetical:
                    hypothetical[job.queue] = attr.allocated.clone()
                allocated = hypothetical[job.queue]
                if allocated.less(reclaimee.resreq):
                    continue
                allocated.sub(reclaimee.resreq)
                if attr.deserved.less_equal(allocated):
                    victims.append(reclaimee)
            return victims

        ssn.add_reclaimable_fn(self.name, reclaimable_fn)

        def overused_fn(queue):
            attr = self.queue_attrs.get(queue.uid)
            if attr is None:
                return False
            return attr.deserved.less_equal(attr.allocated)

        ssn.add_overused_fn(self.name, overused_fn)

        def on_allocate(event):
            job = ssn.jobs[event.task.job_uid]
            attr = self.queue_attrs.get(job.queue)
            if attr:
                attr.allocated.add(event.task.resreq)
                attr.update_share()

        def on_deallocate(event):
            job = ssn.jobs[event.task.job_uid]
            attr = self.queue_attrs.get(job.queue)
            if attr:
                attr.allocated.sub(event.task.resreq)
                attr.update_share()

        ssn.add_event_handler(
            EventHandler(allocate_func=on_allocate,
                         deallocate_func=on_deallocate, owner="proportion")
        )

    def resync(self, ssn: Session) -> None:
        """Recompute per-queue allocated/share from current session task
        state after a bulk device apply (deserved shares stay frozen for
        the cycle, as on the host path). Pipelined tasks count, matching
        the event path."""
        for attr in self.queue_attrs.values():
            attr.allocated = Resource()
        for job in ssn.jobs.values():
            attr = self.queue_attrs.get(job.queue)
            if attr is None:
                continue
            attr.allocated.add(job.allocated)
            for t in job.task_status_index.get(TaskStatus.PIPELINED, {}).values():
                attr.allocated.add(t.resreq)
        for attr in self.queue_attrs.values():
            attr.update_share()

    def on_session_close(self, ssn: Session) -> None:
        self.total = Resource()
        self.queue_attrs = {}
