"""NodeOrder plugin: weighted-sum node scoring.

Parity: reference KB/pkg/scheduler/plugins/nodeorder/nodeorder.go:99-226,
which sums the upstream k8s priorities: LeastRequested,
BalancedResourceAllocation, NodeAffinity (preferred terms), InterPodAffinity.
Weights come from plugin arguments (leastrequested.weight etc., default 1).

Score formulas (upstream k8s priorities, 0-10 scale per component):
  least_requested  = ((cap-req)*10/cap for cpu + same for mem) / 2
  balanced         = 10 - |cpuFraction - memFraction| * 10
  node_affinity    = sum of weights of matching preferred node terms
  interpod         = sum of matching preferred pod-affinity weights on node
"""

from __future__ import annotations

from volcano_tpu.api.objects import match_expressions
from volcano_tpu.scheduler.conf import get_plugin_arg
from volcano_tpu.scheduler.framework import Plugin
from volcano_tpu.scheduler.model import NodeInfo, TaskInfo
from volcano_tpu.scheduler.session import Session


def least_requested_score(task: TaskInfo, node: NodeInfo) -> float:
    """(capacity - requested) * 10 / capacity, averaged over cpu+mem.

    "requested" counts resources already used plus this task's request.
    """
    score = 0.0
    for dim in ("cpu", "memory"):
        cap = node.allocatable.get(dim)
        req = node.used.get(dim) + task.resreq.get(dim)
        if cap > 0:
            score += max(0.0, (cap - req)) * 10.0 / cap
    return score / 2.0


def balanced_resource_score(task: TaskInfo, node: NodeInfo) -> float:
    cap_cpu = node.allocatable.get("cpu")
    cap_mem = node.allocatable.get("memory")
    if cap_cpu <= 0 or cap_mem <= 0:
        return 0.0
    cpu_frac = (node.used.get("cpu") + task.resreq.get("cpu")) / cap_cpu
    mem_frac = (node.used.get("memory") + task.resreq.get("memory")) / cap_mem
    if cpu_frac >= 1.0 or mem_frac >= 1.0:
        return 0.0
    return 10.0 - abs(cpu_frac - mem_frac) * 10.0


def node_affinity_score(task: TaskInfo, node: NodeInfo) -> float:
    aff = task.pod.spec.affinity
    if aff is None:
        return 0.0
    score = 0.0
    for weight, term in aff.preferred_node_terms:
        if match_expressions(node.node.labels, term):
            score += weight
    return score


def interpod_affinity_score(task: TaskInfo, node: NodeInfo) -> float:
    aff = task.pod.spec.affinity
    if aff is None:
        return 0.0
    score = 0.0
    for t in node.tasks.values():
        labels = t.pod.meta.labels
        for selector in aff.pod_affinity:
            if all(labels.get(k) == v for k, v in selector.items()):
                score += 1.0
        for selector in aff.pod_anti_affinity:
            if all(labels.get(k) == v for k, v in selector.items()):
                score -= 1.0
    return score


class NodeOrderPlugin(Plugin):
    name = "nodeorder"

    def on_session_open(self, ssn: Session) -> None:
        args = self.arguments
        w_least = get_plugin_arg(args, "leastrequested.weight", 1.0)
        w_balanced = get_plugin_arg(args, "balancedresource.weight", 1.0)
        w_nodeaff = get_plugin_arg(args, "nodeaffinity.weight", 1.0)
        w_podaff = get_plugin_arg(args, "podaffinity.weight", 1.0)

        def node_order_fn(task: TaskInfo, node: NodeInfo) -> float:
            return (
                w_least * least_requested_score(task, node)
                + w_balanced * balanced_resource_score(task, node)
                + w_nodeaff * node_affinity_score(task, node)
                + w_podaff * interpod_affinity_score(task, node)
            )

        ssn.add_node_order_fn(self.name, node_order_fn)
