"""Plugin registry bootstrap: importing this package registers all built-ins
(parity: reference KB/pkg/scheduler/plugins/factory.go:31-42)."""

from volcano_tpu.scheduler.framework import register_plugin_builder
from volcano_tpu.scheduler.plugins import (
    conformance,
    drf,
    gang,
    nodeorder,
    predicates,
    priority,
    proportion,
)

register_plugin_builder("gang", gang.GangPlugin)
register_plugin_builder("priority", priority.PriorityPlugin)
register_plugin_builder("drf", drf.DRFPlugin)
register_plugin_builder("proportion", proportion.ProportionPlugin)
register_plugin_builder("predicates", predicates.PredicatesPlugin)
register_plugin_builder("nodeorder", nodeorder.NodeOrderPlugin)
register_plugin_builder("conformance", conformance.ConformancePlugin)
