"""Priority plugin: order tasks and jobs by pod/PriorityClass priority.

Parity: reference KB/pkg/scheduler/plugins/priority/priority.go:39-82.
"""

from __future__ import annotations

from volcano_tpu.scheduler.framework import Plugin
from volcano_tpu.scheduler.session import Session


class PriorityPlugin(Plugin):
    name = "priority"

    def on_session_open(self, ssn: Session) -> None:
        def task_order_fn(l, r):
            if l.priority == r.priority:
                return 0
            return -1 if l.priority > r.priority else 1

        ssn.add_task_order_fn(self.name, task_order_fn)

        def job_order_fn(l, r):
            if l.priority == r.priority:
                return 0
            return -1 if l.priority > r.priority else 1

        ssn.add_job_order_fn(self.name, job_order_fn)
