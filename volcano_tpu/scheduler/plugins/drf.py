"""DRF plugin: Dominant Resource Fairness across jobs.

Parity: reference KB/pkg/scheduler/plugins/drf/drf.go:60-177.
share(job) = max over resource dims of allocated/clusterTotal; jobs with
lower share schedule first; a preemption victim is admissible if, after the
hypothetical transfer, the preemptor's share stays <= the victim's job share
(within shareDelta).
"""

from __future__ import annotations

from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import allocated_status
from volcano_tpu.scheduler.framework import Plugin
from volcano_tpu.scheduler.session import EventHandler, Session

SHARE_DELTA = 0.000001


class DRFPlugin(Plugin):
    name = "drf"

    def __init__(self, arguments=None):
        super().__init__(arguments)
        self.total = Resource()
        self.job_attrs = {}  # job uid -> {"allocated": Resource, "share": float}

    def on_session_open(self, ssn: Session) -> None:
        self.total = Resource()
        self.job_attrs = {}
        for node in ssn.nodes.values():
            self.total.add(node.allocatable)

        for job in ssn.jobs.values():
            allocated = Resource()
            for status, tasks in job.task_status_index.items():
                if allocated_status(status):
                    for t in tasks.values():
                        allocated.add(t.resreq)
            self.job_attrs[job.uid] = {
                "allocated": allocated,
                "share": allocated.dominant_share(self.total),
            }

        def preemptable_fn(preemptor, preemptees):
            latt = self.job_attrs[preemptor.job_uid]
            lalloc = latt["allocated"].clone().add(preemptor.resreq)
            ls = lalloc.dominant_share(self.total)

            victims = []
            hypothetical = {}
            for preemptee in preemptees:
                if preemptee.job_uid not in hypothetical:
                    hypothetical[preemptee.job_uid] = self.job_attrs[preemptee.job_uid][
                        "allocated"
                    ].clone()
                ralloc = hypothetical[preemptee.job_uid].sub(preemptee.resreq)
                rs = ralloc.dominant_share(self.total)
                if ls < rs or abs(ls - rs) <= SHARE_DELTA:
                    victims.append(preemptee)
            return victims

        ssn.add_preemptable_fn(self.name, preemptable_fn)

        def job_order_fn(l, r):
            ls = self.job_attrs[l.uid]["share"]
            rs = self.job_attrs[r.uid]["share"]
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        ssn.add_job_order_fn(self.name, job_order_fn)

        def on_allocate(event):
            attr = self.job_attrs[event.task.job_uid]
            attr["allocated"].add(event.task.resreq)
            attr["share"] = attr["allocated"].dominant_share(self.total)

        def on_deallocate(event):
            attr = self.job_attrs[event.task.job_uid]
            attr["allocated"].sub(event.task.resreq)
            attr["share"] = attr["allocated"].dominant_share(self.total)

        ssn.add_event_handler(
            EventHandler(allocate_func=on_allocate,
                         deallocate_func=on_deallocate, owner="drf")
        )

    def resync(self, ssn: Session) -> None:
        """Recompute shares from current session task state — called after a
        bulk device apply (which accounts shares on device and skips
        per-task events) so a host residue pass orders jobs correctly.
        Pipelined tasks count: the event path charges them via pipeline's
        allocate event."""
        from volcano_tpu.api.types import TaskStatus

        for job in ssn.jobs.values():
            allocated = job.allocated.clone()
            for t in job.task_status_index.get(TaskStatus.PIPELINED, {}).values():
                allocated.add(t.resreq)
            self.job_attrs[job.uid] = {
                "allocated": allocated,
                "share": allocated.dominant_share(self.total),
            }

    def on_session_close(self, ssn: Session) -> None:
        self.total = Resource()
        self.job_attrs = {}
