"""Predicates plugin: node filtering checks.

Parity: reference KB/pkg/scheduler/plugins/predicates/predicates.go:57-205,
which chains the upstream k8s predicates. Checks, in order:
max task num, node condition, node unschedulable, node selector + required
node affinity, host ports, taints/tolerations, memory/disk/pid pressure,
pod (anti)affinity against pods resident on the node.
"""

from __future__ import annotations

from typing import Optional

from volcano_tpu.api.objects import match_expressions
from volcano_tpu.scheduler.framework import Plugin
from volcano_tpu.scheduler.model import NodeInfo, TaskInfo
from volcano_tpu.scheduler.session import Session


def node_selector_fits(task: TaskInfo, node: NodeInfo) -> bool:
    """PodMatchNodeSelector: node_selector labels AND required node affinity."""
    spec = task.pod.spec
    labels = node.node.labels
    for k, v in spec.node_selector.items():
        if labels.get(k) != v:
            return False
    aff = spec.affinity
    if aff and aff.node_terms:
        # OR across terms, AND within a term
        if not any(match_expressions(labels, term) for term in aff.node_terms):
            return False
    return True


def taints_tolerated(task: TaskInfo, node: NodeInfo) -> bool:
    """PodToleratesNodeTaints: NoSchedule/NoExecute taints must be tolerated."""
    tolerations = task.pod.spec.tolerations
    for taint in node.node.taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            return False
    return True


def host_ports_free(task: TaskInfo, node: NodeInfo) -> bool:
    wanted = set(task.pod.spec.host_ports)
    if not wanted:
        return True
    for resident in node.tasks.values():
        if wanted.intersection(resident.pod.spec.host_ports):
            return False
    return True


def _match_selector(labels, selector) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def pod_affinity_fits(task: TaskInfo, node: NodeInfo) -> bool:
    """Required pod (anti)affinity with node-level topology."""
    aff = task.pod.spec.affinity
    if aff is None:
        return True
    resident = [t.pod for t in node.tasks.values()]
    for selector in aff.pod_affinity:
        if not any(_match_selector(p.meta.labels, selector) for p in resident):
            return False
    for selector in aff.pod_anti_affinity:
        if any(_match_selector(p.meta.labels, selector) for p in resident):
            return False
        # self-anti-affinity: a pod that anti-matches itself conflicts with
        # like-labeled pods already placed (standard k8s semantics)
    return True


PRESSURE_CONDITIONS = ("MemoryPressure", "DiskPressure", "PIDPressure")


class PredicatesPlugin(Plugin):
    name = "predicates"

    def on_session_open(self, ssn: Session) -> None:
        def predicate_fn(task: TaskInfo, node: NodeInfo) -> Optional[str]:
            # reasons are canonical (node-free) so JobInfo.fit_error() can
            # histogram them across nodes; the caller knows which node failed
            n = node.node
            max_tasks = node.allocatable.max_task_num
            if max_tasks is not None and len(node.tasks) + 1 > max_tasks:
                return "node(s) had too many tasks"
            if not n.ready():
                return "node(s) were not ready"
            if n.unschedulable:
                return "node(s) were unschedulable"
            if not node_selector_fits(task, node):
                return "node(s) didn't match node selector"
            if not host_ports_free(task, node):
                return "node(s) didn't have free ports"
            if not taints_tolerated(task, node):
                return "node(s) had taints that the pod didn't tolerate"
            for cond in n.conditions:
                if cond.kind in PRESSURE_CONDITIONS and cond.status == "True":
                    return f"node(s) had {cond.kind}"
            if not pod_affinity_fits(task, node):
                return "node(s) didn't satisfy pod affinity/anti-affinity"
            # volume binding predicate: bound-PV node affinity / static-PV
            # availability (the k8s CheckVolumeBinding analogue; the
            # reference reaches it through the VolumeBinder seam instead,
            # cache.go:173-185)
            volume_fit = getattr(ssn.cache, "volume_fit", None)
            if volume_fit is not None:
                reason = volume_fit(task, node)
                if reason is not None:
                    return reason
            return None

        ssn.add_predicate_fn(self.name, predicate_fn)
