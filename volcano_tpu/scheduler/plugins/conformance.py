"""Conformance plugin: protect system-critical pods from eviction.

Parity: reference KB/pkg/scheduler/plugins/conformance/conformance.go:41-65.
"""

from __future__ import annotations

from volcano_tpu.scheduler.framework import Plugin
from volcano_tpu.scheduler.session import Session

_CRITICAL_CLASSES = ("system-cluster-critical", "system-node-critical")


class ConformancePlugin(Plugin):
    name = "conformance"

    def on_session_open(self, ssn: Session) -> None:
        def evictable_fn(evictor, evictees):
            victims = []
            for evictee in evictees:
                if evictee.priority_class in _CRITICAL_CLASSES:
                    continue
                if evictee.namespace == "kube-system":
                    continue
                victims.append(evictee)
            return victims

        ssn.add_preemptable_fn(self.name, evictable_fn)
        ssn.add_reclaimable_fn(self.name, evictable_fn)
