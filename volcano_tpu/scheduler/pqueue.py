"""Binary-heap priority queue over a less-function.

Mirrors the reference's container/heap-based PriorityQueue
(KB/pkg/scheduler/util/priority_queue.go): comparisons call the less fn
lazily at sift time, so if the ordering keys mutate while items sit in the
queue (DRF/proportion shares do), pop order reflects heap structure rather
than a full re-sort — same observable behavior as the reference.
"""

from __future__ import annotations

from typing import Any, Callable, List


class PriorityQueue:
    def __init__(self, less: Callable[[Any, Any], bool]):
        self._less = less
        self._items: List[Any] = []

    def __len__(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def push(self, item: Any) -> None:
        self._items.append(item)
        self._sift_up(len(self._items) - 1)

    def pop(self) -> Any:
        items = self._items
        last = len(items) - 1
        items[0], items[last] = items[last], items[0]
        out = items.pop()
        if items:
            self._sift_down(0)
        return out

    def _sift_up(self, i: int) -> None:
        items, less = self._items, self._less
        while i > 0:
            parent = (i - 1) // 2
            if not less(items[i], items[parent]):
                break
            items[i], items[parent] = items[parent], items[i]
            i = parent

    def _sift_down(self, i: int) -> None:
        items, less = self._items, self._less
        n = len(items)
        while True:
            left, right = 2 * i + 1, 2 * i + 2
            smallest = i
            if left < n and less(items[left], items[smallest]):
                smallest = left
            if right < n and less(items[right], items[smallest]):
                smallest = right
            if smallest == i:
                return
            items[i], items[smallest] = items[smallest], items[i]
            i = smallest
