"""Vectorized residue engine: the host residue pass without the per-task
Python node scan.

BASELINE.md's r5 host-residue cost curve was the system's last
multi-minute path: each residue task paid ``util.predicate_nodes`` +
``prioritize_nodes`` — ~8 predicate function calls and a score sum per
node, ~0.13 s/task at 10k nodes (64.6 s for 500 volume-constrained
tasks).  Most volume shapes now solve on device (volsolve.py); whatever
still falls out — intern-cap overflow, count-inexpressible claim pools,
best-effort pods of dynamic jobs — runs HERE: the same
queue/job/task-order loop as ``AllocateAction._execute_host``, but the
per-task inner step is batched numpy over the node axis:

  * resource fit replicates ``Resource.less_equal`` op-for-op on
    [N, R] f64 columns (strict-less OR abs-diff-under-epsilon per dim);
  * static predicates (ready/unschedulable/pressure/selector/affinity/
    taints) come from one cached [N] mask per distinct task class,
    computed by the SAME ``_static_predicate`` helper the snapshot
    builders use — O(classes x N) once per pass, not O(tasks x N);
  * host ports / pod-(anti)affinity read per-node resident port sets and
    per-selector match-count columns built in ONE resident sweep and
    updated incrementally as the pass places tasks;
  * volume claims resolve through the session ``VolumeBinder``'s own
    state (assumptions included) into [N] masks, with per-affinity-
    signature caching;
  * scores replicate the nodeorder plugin's float arithmetic
    expression-for-expression in f64, so the argmax (first max, node
    order) picks the identical node.

Decision parity: the engine is bit-for-bit equal to the per-task loop —
``tests/test_volume_parity.py`` runs both on seeded mixed clusters and
asserts identical binds, statuses, and fit-error histograms.  When a
head task has NO feasible node the engine re-runs that one task through
``util.predicate_nodes`` so the per-reason histogram (PodGroup message
parity) is byte-identical; that costs the old per-task price only for
unschedulable heads.

Scope: the engine serves ONLY filtered residue passes (``job_filter``
set).  The unfiltered host path keeps the per-task loop — it is the
oracle every parity suite measures against, and vectorizing the oracle
would leave nothing to verify the vectors with.  An unknown
predicate/score chain (a plugin the engine does not model) also falls
back to the loop; the ``residue-vectorized`` vtlint rule keeps per-task
node scans from creeping back into THIS module and tensor_actions.
"""

from __future__ import annotations

import time
from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

from volcano_tpu.api.resource import MIN_MEMORY, MIN_MILLI_CPU, MIN_SCALAR
from volcano_tpu.scheduler import util
from volcano_tpu.scheduler.cache import VolumeBindingError


def _active_fns(ssn, registry, flag):
    """Plugin names the session's tier dispatch would actually call."""
    return [plugin.name for _, plugin, _ in ssn._ordered(registry, flag)]


def chain_known(ssn) -> bool:
    """Whether the session's predicate/score chains are exactly the set
    this engine replicates (the predicates plugin once, the nodeorder
    plugin at most once).  Anything else — a custom plugin, a double
    registration — keeps the per-task loop, same discipline as
    TensorBackend.supported."""
    preds = _active_fns(ssn, ssn.predicate_fns, "enabled_predicate")
    if preds not in ([], ["predicates"]):
        return False
    orders = _active_fns(ssn, ssn.node_order_fns, "enabled_node_order")
    return orders in ([], ["nodeorder"])


def _nodeorder_weights(ssn) -> Tuple[float, float, float, float]:
    from volcano_tpu.scheduler.conf import get_plugin_arg

    for tier in ssn.tiers:
        for opt in tier.plugins:
            if opt.name == "nodeorder":
                args = opt.arguments
                return (
                    get_plugin_arg(args, "leastrequested.weight", 1.0),
                    get_plugin_arg(args, "balancedresource.weight", 1.0),
                    get_plugin_arg(args, "nodeaffinity.weight", 1.0),
                    get_plugin_arg(args, "podaffinity.weight", 1.0),
                )
    return 0.0, 0.0, 0.0, 0.0


class _Engine:
    """Per-pass node-axis state.  All float columns are f64 and every
    update replays the host's arithmetic in the host's order, so scores
    and epsilon fits are bit-identical to the per-task loop."""

    def __init__(self, ssn):
        self.ssn = ssn
        self.nodes: List = util.get_node_list(ssn.nodes)
        self.n = len(self.nodes)
        self.scoring = bool(
            _active_fns(ssn, ssn.node_order_fns, "enabled_node_order")
        )
        # the predicates plugin may be absent from the tiers: then the
        # host chain filters on resource fit ALONE and so must we
        self.predicates_on = bool(
            _active_fns(ssn, ssn.predicate_fns, "enabled_predicate")
        )
        self.w_least, self.w_bal, self.w_aff, self.w_pod = (
            _nodeorder_weights(ssn) if self.scoring else (0.0,) * 4
        )
        # resource dims: cpu/memory + every scalar any node or task knows;
        # a task scalar outside this set falls back per-task (rare: a
        # scalar only requests mention would mean no node offers it)
        scalars = set()
        for ni in self.nodes:
            scalars.update(ni.idle.scalars)
            scalars.update(ni.releasing.scalars)
            scalars.update(ni.used.scalars)
            scalars.update(ni.allocatable.scalars)
        self.dims = ["cpu", "memory", *sorted(scalars)]
        self.dimset = set(self.dims)
        R = len(self.dims)
        self.eps = np.array(
            [MIN_MILLI_CPU, MIN_MEMORY] + [MIN_SCALAR] * (R - 2), np.float64
        )
        self.idle = np.zeros((self.n, R), np.float64)
        self.releasing = np.zeros((self.n, R), np.float64)
        self.used2 = np.zeros((self.n, 2), np.float64)   # cpu, memory (scores)
        self.cap2 = np.zeros((self.n, 2), np.float64)
        self.counts = np.zeros(self.n, np.int64)
        self.max_tasks = np.full(self.n, np.iinfo(np.int64).max, np.int64)
        for i, ni in enumerate(self.nodes):
            self._vec(ni.idle, self.idle[i])
            self._vec(ni.releasing, self.releasing[i])
            self.used2[i, 0] = ni.used.milli_cpu
            self.used2[i, 1] = ni.used.memory
            self.cap2[i, 0] = ni.allocatable.milli_cpu
            self.cap2[i, 1] = ni.allocatable.memory
            self.counts[i] = len(ni.tasks)
            if ni.allocatable.max_task_num is not None:
                self.max_tasks[i] = ni.allocatable.max_task_num
        self.node_index = {ni.name: i for i, ni in enumerate(self.nodes)}
        # lazy per-class static masks / raw node-affinity score columns
        self._class_cache: Dict[object, Tuple[np.ndarray, np.ndarray]] = {}
        # lazy resident port sets / selector count columns
        self._port_sets: Optional[List[set]] = None
        self._port_masks: Dict[FrozenSet[int], np.ndarray] = {}
        self._sel_counts: Dict[tuple, np.ndarray] = {}
        # volume resolution caches (per-affinity-signature node masks)
        self._aff_masks: Dict[tuple, np.ndarray] = {}
        self._labels: Optional[List[dict]] = None

    def _vec(self, res, out) -> None:
        out[0] = res.milli_cpu
        out[1] = res.memory
        for i, name in enumerate(self.dims[2:], start=2):
            out[i] = res.scalars.get(name, 0.0)

    # -- fit (Resource.less_equal, op-for-op) --------------------------------

    def _fits(self, req_vec: np.ndarray, pool: np.ndarray) -> np.ndarray:
        return np.all(
            (req_vec[None, :] < pool)
            | (np.abs(pool - req_vec[None, :]) < self.eps[None, :]),
            axis=1,
        )

    # -- static predicate class columns --------------------------------------

    def _class_cols(self, task) -> Tuple[np.ndarray, np.ndarray]:
        from volcano_tpu.scheduler.plugins.nodeorder import node_affinity_score
        from volcano_tpu.scheduler.snapshot import (
            _static_predicate, _task_class_key,
        )

        key = _task_class_key(task)
        hit = self._class_cache.get(key)
        if hit is not None:
            return hit
        mask = np.zeros(self.n, bool)
        aff = np.zeros(self.n, np.float64)
        for i, ni in enumerate(self.nodes):
            mask[i] = _static_predicate(task, ni)
            if self.scoring:
                # scored for EVERY node: with the predicates plugin off
                # the host scores all fit-feasible nodes, masked or not
                aff[i] = node_affinity_score(task, ni)
        self._class_cache[key] = (mask, aff)
        return mask, aff

    # -- resident ports / selector counts ------------------------------------

    def _ensure_residents(self) -> None:
        if self._port_sets is not None:
            return
        self._port_sets = [set() for _ in range(self.n)]
        self._resident_labels: List[List[dict]] = [[] for _ in range(self.n)]
        for i, ni in enumerate(self.nodes):
            ps = self._port_sets[i]
            rl = self._resident_labels[i]
            for t in ni.tasks.values():
                ps.update(t.pod.spec.host_ports)
                rl.append(t.pod.meta.labels)

    def _ports_mask(self, ports: FrozenSet[int]) -> np.ndarray:
        mask = self._port_masks.get(ports)
        if mask is None:
            self._ensure_residents()
            mask = np.fromiter(
                (not (ports & s) for s in self._port_sets),
                bool, count=self.n,
            )
            self._port_masks[ports] = mask
        return mask

    def _sel_col(self, sel_items: tuple) -> np.ndarray:
        col = self._sel_counts.get(sel_items)
        if col is None:
            self._ensure_residents()
            col = np.zeros(self.n, np.float64)
            for i, labels_list in enumerate(self._resident_labels):
                c = 0
                for labels in labels_list:
                    if all(labels.get(k) == v for k, v in sel_items):
                        c += 1
                col[i] = c
            self._sel_counts[sel_items] = col
        return col

    # -- volumes (VolumeBinder._resolve_claim, vectorized) -------------------

    def _node_labels(self) -> List[dict]:
        if self._labels is None:
            self._labels = [ni.node.labels for ni in self.nodes]
        return self._labels

    def _affinity_mask(self, affinity: Dict[str, str]) -> np.ndarray:
        if not affinity:
            return np.ones(self.n, bool)
        key = tuple(sorted(affinity.items()))
        mask = self._aff_masks.get(key)
        if mask is None:
            labels = self._node_labels()
            mask = np.fromiter(
                (
                    all(labels[i].get(k) == v for k, v in affinity.items())
                    for i in range(self.n)
                ),
                bool, count=self.n,
            )
            self._aff_masks[key] = mask
        return mask

    def _volume_mask(self, task) -> Optional[np.ndarray]:
        """AND over the task's pending claims of the nodes where
        _resolve_claim would pass — computed fresh per task because the
        binder's assumption state moves as the pass places siblings."""
        vb = getattr(self.ssn.cache, "volume_binder", None)
        if vb is None or task.pod is None or not task.pod.volumes:
            return None
        claims = vb._pending_claims(task)
        if not claims:
            return None
        mask = np.ones(self.n, bool)
        for pvc in claims:
            assumed = vb._claim_assumed.get(pvc.meta.key)
            if pvc.volume_name or assumed:
                pv = vb._pv(pvc.volume_name or assumed)
                if pv is None:
                    return np.zeros(self.n, bool)
                if pv.node_affinity:
                    mask = mask & self._affinity_mask(pv.node_affinity)
            elif vb._is_static_class(pvc.storage_class):
                want = vb._qty(pvc.size) if pvc.size else 0.0
                claim_mask = np.zeros(self.n, bool)
                for pv in vb._pvs():
                    if pv.claim_ref or pv.meta.name in vb._assumed_pvs:
                        continue
                    if pv.storage_class != pvc.storage_class:
                        continue
                    cap = vb._qty(pv.capacity) if pv.capacity else float("inf")
                    if cap < want:
                        continue
                    claim_mask = claim_mask | self._affinity_mask(
                        pv.node_affinity
                    )
                    if claim_mask.all():
                        break
                mask = mask & claim_mask
            # dynamic pending class: fits everywhere
            if not mask.any():
                break
        return mask

    # -- the per-task step ----------------------------------------------------

    def place(self, task):
        """(node_info, use_idle) for the host-identical best node, or
        None when no node is feasible.  Falls back to signaling None for
        request shapes outside the engine's dim set (caller re-runs the
        per-task loop for exactness)."""
        req = task.init_resreq
        if not set(req.scalars) <= self.dimset:
            return "fallback"
        req_vec = np.zeros(len(self.dims), np.float64)
        self._vec(req, req_vec)
        fit_idle = self._fits(req_vec, self.idle)
        fit_rel = self._fits(req_vec, self.releasing)
        feasible = fit_idle | fit_rel
        if not feasible.any():
            return None
        static_mask, aff_col = self._class_cols(task)
        spec = task.pod.spec
        aff = spec.affinity
        sel_req = sel_anti = ()
        if aff is not None:
            sel_req = [tuple(sorted(s.items())) for s in aff.pod_affinity]
            sel_anti = [
                tuple(sorted(s.items())) for s in aff.pod_anti_affinity
            ]
        if self.predicates_on:
            feasible &= static_mask
            feasible &= self.counts + 1 <= self.max_tasks
            if spec.host_ports:
                feasible &= self._ports_mask(frozenset(spec.host_ports))
            for s in sel_req:
                feasible &= self._sel_col(s) > 0
            for s in sel_anti:
                feasible &= self._sel_col(s) == 0
            vol_mask = self._volume_mask(task)
            if vol_mask is not None:
                feasible &= vol_mask
        if not feasible.any():
            return None
        if self.scoring:
            score = self._score(task, req, aff_col, sel_req, sel_anti)
        else:
            score = np.zeros(self.n, np.float64)
        score = np.where(feasible, score, -np.inf)
        i = int(np.argmax(score))  # first max == select_best_node
        return self.nodes[i], bool(fit_idle[i])

    def _score(self, task, req, aff_col, sel_req, sel_anti) -> np.ndarray:
        # nodeorder.py formulas, expression-for-expression in f64 so the
        # floats are the exact ones the host plugin would produce
        rr = task.resreq
        cap_cpu, cap_mem = self.cap2[:, 0], self.cap2[:, 1]
        used_cpu = self.used2[:, 0] + rr.milli_cpu
        used_mem = self.used2[:, 1] + rr.memory
        with np.errstate(divide="ignore", invalid="ignore"):
            t_cpu = np.where(
                cap_cpu > 0,
                np.maximum(0.0, cap_cpu - used_cpu) * 10.0 / cap_cpu, 0.0,
            )
            t_mem = np.where(
                cap_mem > 0,
                np.maximum(0.0, cap_mem - used_mem) * 10.0 / cap_mem, 0.0,
            )
            least = (t_cpu + t_mem) / 2.0
            cpu_frac = used_cpu / cap_cpu
            mem_frac = used_mem / cap_mem
        balanced = np.where(
            (cap_cpu > 0) & (cap_mem > 0)
            & (cpu_frac < 1.0) & (mem_frac < 1.0),
            10.0 - np.abs(cpu_frac - mem_frac) * 10.0,
            0.0,
        )
        score = self.w_least * least
        score = score + self.w_bal * balanced
        score = score + self.w_aff * aff_col
        if sel_req or sel_anti:
            inter = np.zeros(self.n, np.float64)
            for s in sel_req:
                inter = inter + self._sel_col(s)
            for s in sel_anti:
                inter = inter - self._sel_col(s)
            score = score + self.w_pod * inter
        return score

    # -- post-placement bookkeeping ------------------------------------------

    def account(self, task, node_name: str, use_idle: bool) -> None:
        """Mirror NodeInfo.add_task's effect on the engine columns (the
        session object itself was already updated by ssn.allocate /
        ssn.pipeline)."""
        i = self.node_index[node_name]
        rr = np.zeros(len(self.dims), np.float64)
        self._vec(task.resreq, rr)
        if use_idle:
            self.idle[i] = np.maximum(self.idle[i] - rr, 0.0)
        else:
            self.releasing[i] = np.maximum(self.releasing[i] - rr, 0.0)
        self.used2[i, 0] += task.resreq.milli_cpu
        self.used2[i, 1] += task.resreq.memory
        self.counts[i] += 1
        # resident port/selector state follows the placement so later
        # tasks see this pass's pods, like the host walking node.tasks
        spec = task.pod.spec
        if spec.host_ports and self._port_sets is not None:
            placed = set(spec.host_ports)
            self._port_sets[i].update(placed)
            for pset, mask in self._port_masks.items():
                if pset & placed:
                    mask[i] = False
        labels = task.pod.meta.labels
        if self._port_sets is not None:
            self._resident_labels[i].append(labels)
        for sel_items, col in self._sel_counts.items():
            if all(labels.get(k) == v for k, v in sel_items):
                col[i] += 1


def vector_allocate(ssn, job_filter, stats: Optional[dict] = None) -> bool:
    """The residue allocate pass with the batched inner step, driven by
    the SAME ``allocate_loop`` skeleton as the per-task oracle
    (actions/allocate.py) — only the inner step differs, so a loop-shape
    change can never silently break the parity contract.  Returns False
    (having done nothing) when the session's chains are not the known
    set — the caller then runs the per-task loop."""
    from volcano_tpu.scheduler.actions.allocate import (
        allocate_loop, fit_first_predicate_fn,
    )

    if not chain_known(ssn):
        return False
    t0 = time.perf_counter()
    engine = _Engine(ssn)
    all_nodes = engine.nodes
    counter = [0]
    # the reason-histogram twin of the vector step — THE SAME wrapper the
    # oracle loop uses, paid only for unschedulable heads
    predicate_fn = fit_first_predicate_fn(ssn)

    def inner(job, task) -> bool:
        counter[0] += 1
        verdict = engine.place(task)
        if verdict == "fallback":
            # request shape outside the engine's dim set: the one-task
            # exact loop decides (and its predicate sweep sees the same
            # session state the engine mirrors)
            reasons: dict = {}
            feasible = util.predicate_nodes(
                task, all_nodes, predicate_fn, reasons
            )
            if feasible:
                scores = util.prioritize_nodes(
                    task, feasible, ssn.node_order_fn
                )
                node = util.select_best_node(scores)
                verdict = (node, task.init_resreq.less_equal(node.idle))
            else:
                verdict = None
                job.fit_errors = reasons
        if verdict is None:
            # head task unschedulable: the per-reason histogram must be
            # byte-identical to the loop's — re-run this ONE task through
            # the exact predicate sweep (unless the fallback above
            # already did)
            if not job.fit_errors:
                reasons = {}
                util.predicate_nodes(task, all_nodes, predicate_fn, reasons)
                job.fit_errors = reasons
            job.fit_total_nodes = len(all_nodes)
            return False

        node, use_idle = verdict
        if use_idle:
            try:
                ssn.allocate(task, node.name)
                engine.account(task, node.name, True)
            except VolumeBindingError:
                # volume state changed between predicate and allocate
                # (sibling claimed the PV); task stays pending, exactly
                # the loop's handling
                pass
        else:
            delta = node.idle.clone()
            delta.fit_delta(task.init_resreq)
            job.nodes_fit_delta[node.name] = delta
            job.fit_total_nodes = len(all_nodes)
            ssn.pipeline(task, node.name)
            engine.account(task, node.name, False)
        return True

    allocate_loop(ssn, job_filter, inner)
    if stats is not None:
        stats["tasks"] = stats.get("tasks", 0) + counter[0]
        stats["seconds"] = stats.get("seconds", 0.0) + (
            time.perf_counter() - t0
        )
    return True
