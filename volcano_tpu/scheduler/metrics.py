"""Scheduler metrics with reference-compatible names.

Collector names/semantics mirror KB/pkg/scheduler/metrics/metrics.go:38-121
(namespace ``volcano``). Backed by simple in-process counters/histograms with
a Prometheus-text exposition, so tests and operators can scrape the same
series names the reference exports.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict, List, Tuple

_histograms: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], List[float]] = defaultdict(list)
_counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = defaultdict(float)
_gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = defaultdict(float)


def _key(name: str, labels: Dict[str, str]):
    return (name, tuple(sorted(labels.items())))


def observe(name: str, value: float, **labels) -> None:
    _histograms[_key(name, labels)].append(value)


def inc(name: str, value: float = 1.0, **labels) -> None:
    _counters[_key(name, labels)] += value


def set_gauge(name: str, value: float, **labels) -> None:
    _gauges[_key(name, labels)] = value


def reset() -> None:
    _histograms.clear()
    _counters.clear()
    _gauges.clear()


# -- recording helpers mirroring the reference call sites --------------------

def update_e2e_duration(start: float) -> None:
    observe("volcano_e2e_scheduling_latency_milliseconds", (time.perf_counter() - start) * 1e3)


def update_action_duration(action: str, start: float) -> None:
    observe(
        "volcano_action_scheduling_latency_microseconds",
        (time.perf_counter() - start) * 1e6,
        action=action,
    )


def update_plugin_duration(plugin: str, on_session: str, start: float) -> None:
    observe(
        "volcano_plugin_scheduling_latency_microseconds",
        (time.perf_counter() - start) * 1e6,
        plugin=plugin,
        OnSession=on_session,
    )


def update_task_schedule_duration(duration_s: float) -> None:
    observe("volcano_task_scheduling_latency_microseconds", duration_s * 1e6)


def update_pod_e2e_latency(ms: float) -> None:
    """Reference-parity per-pod e2e latency (metrics.go E2eSchedulingLatency
    family): pod first seen on the bus (creation) -> bind decision, in
    milliseconds.  Emitted from the vtrace bind spans (volcano_tpu/trace.py)
    — populated only while tracing is armed, so the disarmed hot path stays
    untouched."""
    observe("volcano_e2e_job_scheduling_latency_milliseconds", ms)


def register_schedule_attempt(succeeded: bool) -> None:
    inc("volcano_schedule_attempts_total", result="scheduled" if succeeded else "unschedulable")


def register_preemption_attempt() -> None:
    inc("volcano_total_preemption_attempts")


def update_preemption_victims(count: int) -> None:
    set_gauge("volcano_pod_preemption_victims", count)


def update_unschedule_task_count(job: str, count: int) -> None:
    set_gauge("volcano_unschedule_task_count", count, job_id=job)


def update_unschedule_job_count(count: int) -> None:
    set_gauge("volcano_unschedule_job_count", count)


def register_job_retry(job: str) -> None:
    inc("volcano_job_retry_counts", job_id=job)


def register_residue_tasks(cls: str, count: int) -> None:
    """Tasks the fast cycle routed to the host residue (slow) class this
    cycle, labeled by WHY: ``volume-shape`` (count-inexpressible claim
    pools), ``volume-claim-cap`` (claim intern overflow),
    ``intern-overflow`` (port/selector bitset caps), ``best-effort``
    (empty-request pods of dynamic jobs), ``contended-claims`` (capacity
    group shared with a residue job), ``batch-wave`` (volume jobs
    stepping aside so a batch-scale port/affinity wave keeps the
    batched-rounds kernel).  Monotone counter — `vtctl
    describe job` / operators read it to explain why a pod took the slow
    path."""
    inc("volcano_residue_tasks_total", float(count), **{"class": cls})


# -- store WAL durability series (volcano_tpu/store/wal.py) -------------------

def register_wal_append(n: int = 1) -> None:
    """Records appended to the store's write-ahead log (one per mutation
    request/op; a whole decision segment is ONE record)."""
    inc("volcano_store_wal_appended_records_total", float(n))


def register_wal_fsync(n: int = 1) -> None:
    """Group-commit fsyncs of the WAL tail — the ACK barrier.  The ratio
    to appended_records shows how well group commit amortizes."""
    inc("volcano_store_wal_fsync_total", float(n))


def register_wal_recovery(n: int) -> None:
    """Records replayed from the WAL tail during crash recovery."""
    inc("volcano_store_wal_recovery_replayed_records", float(n))


# -- elastic autoscaler series (volcano_tpu/elastic/) -------------------------

def update_pool_size(pool: str, size: int) -> None:
    set_gauge("volcano_elastic_pool_size", size, pool=pool)


def update_pending_demand(pool: str, nodes: int) -> None:
    set_gauge("volcano_elastic_pending_demand_nodes", nodes, pool=pool)


def register_scale_event(pool: str, direction: str) -> None:
    inc("volcano_elastic_scale_events_total", pool=pool, direction=direction)


def register_drain_eviction(pool: str) -> None:
    inc("volcano_elastic_drain_evictions_total", pool=pool)


def expose_text() -> str:
    """Prometheus text exposition of all recorded series."""
    lines = []
    for (name, labels), value in sorted(_counters.items()):
        lines.append(f"{name}{_fmt(labels)} {value}")
    for (name, labels), value in sorted(_gauges.items()):
        lines.append(f"{name}{_fmt(labels)} {value}")
    for (name, labels), values in sorted(_histograms.items()):
        lines.append(f"{name}_count{_fmt(labels)} {len(values)}")
        lines.append(f"{name}_sum{_fmt(labels)} {sum(values)}")
    return "\n".join(lines) + "\n"


def _fmt(labels) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


def get_histogram(name: str, **labels) -> List[float]:
    return _histograms.get(_key(name, labels), [])


def get_counter(name: str, **labels) -> float:
    return _counters.get(_key(name, labels), 0.0)
