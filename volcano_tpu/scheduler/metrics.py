"""Scheduler metrics with reference-compatible names (vtload core).

Collector names/semantics mirror KB/pkg/scheduler/metrics/metrics.go:38-121
(namespace ``volcano``).  r8 rebuilt the backing store on **bounded
log-linear bucket histograms** (HDR-style): ``observe()`` folds every
sample into a fixed bucket universe — ``SUBBUCKETS`` linear sub-buckets
per decade between ``10^EMIN`` and ``10^EMAX`` — so a series that has
seen 10^6 observations occupies exactly the same state as one that has
seen 10^2 (the r1–r7 implementation appended every sample to an unbounded
Python list, a memory leak under sustained load and no percentile
readout).  Quantile error is bounded by one sub-bucket width: at most
``9/SUBBUCKETS`` of the value (10% at the default 90).

Exposition (:func:`expose_text`) is proper Prometheus text format:
``# HELP`` / ``# TYPE`` per family, cumulative ``_bucket{le="..."}``
lines (only non-empty boundaries plus the mandatory ``le="+Inf"``),
``_sum`` / ``_count``, byte-stable ordering (families alphabetical,
series by sorted label tuple) — conformance is asserted by the mini
parser in ``tests/test_metrics.py``.

Cardinality guard: at most :data:`MAX_SERIES_PER_METRIC` distinct label
sets per metric name.  Beyond the cap new series are dropped (the
observation is discarded, never an error) and counted in
``volcano_metrics_dropped_series_total{metric=...}`` — so
``register_job_retry``-style per-job labels cannot grow without bound
under churn.

Measurement discipline (enforced by the vtlint ``metric-discipline``
rule): counters end ``_total``, duration series carry a unit suffix, and
latency values are derived from monotonic clocks (``time.monotonic`` /
``time.perf_counter``), never wall-clock ``time.time`` — the one
sanctioned exception is the cross-process first-seen→bind series, whose
start edge is an epoch creation timestamp.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

#: linear sub-buckets per decade (HDR-style log-linear).  Worst-case
#: relative quantile error = 9/SUBBUCKETS (one sub-bucket width).
SUBBUCKETS = 90
#: decade range: finite boundaries span [10^EMIN, 10^EMAX]
EMIN = -9
EMAX = 9
#: finite bucket universe (underflow + per-decade linear sub-buckets);
#: values >= 10^EMAX count only toward +Inf
MAX_BUCKETS = (EMAX - EMIN) * SUBBUCKETS + 2
#: label-cardinality cap per metric name (the guard above)
MAX_SERIES_PER_METRIC = 512

_LO = 10.0 ** EMIN
_HI = 10.0 ** EMAX
#: index of the +Inf-only overflow bucket
_OVERFLOW = (EMAX - EMIN) * SUBBUCKETS + 1

_DROPPED_SERIES = "volcano_metrics_dropped_series_total"


def _bucket_index(v: float) -> int:
    """Fixed log-linear bucket index for ``v`` (0 = underflow, holds
    zero/negative/NaN too; ``_OVERFLOW`` = values beyond the last finite
    boundary, reported only under ``le="+Inf"``)."""
    if not v > _LO:  # <= _LO, zero, negative, NaN
        return 0
    if v >= _HI:
        return _OVERFLOW
    e = math.floor(math.log10(v))
    # repair float edges: log10 can land one decade off at exact powers
    if v < 10.0 ** e:
        e -= 1
    elif v >= 10.0 ** (e + 1):
        e += 1
    m = v / (10.0 ** e)
    # ceil-minus-one keeps exact boundary values in their own (lower)
    # bucket: le is INCLUSIVE in the Prometheus contract
    sub = math.ceil((m - 1.0) * SUBBUCKETS / 9.0) - 1
    if sub < 0:
        sub = 0
    elif sub >= SUBBUCKETS:
        sub = SUBBUCKETS - 1
    return 1 + (e - EMIN) * SUBBUCKETS + sub


def _bucket_upper(idx: int) -> float:
    """Inclusive upper boundary (the ``le`` value) of a finite bucket."""
    if idx <= 0:
        return _LO
    e = EMIN + (idx - 1) // SUBBUCKETS
    sub = (idx - 1) % SUBBUCKETS
    return (10.0 ** e) * (1.0 + 9.0 * (sub + 1) / SUBBUCKETS)


class Histogram:
    """One bounded series: sparse bucket counts + count/sum/min/max.

    State is bounded by the bucket universe (``MAX_BUCKETS`` entries at
    most), never by observation volume."""

    __slots__ = ("buckets", "count", "sum", "vmin", "vmax")

    def __init__(self):
        self.buckets: Dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    def observe(self, v: float) -> None:
        idx = _bucket_index(v)
        self.buckets[idx] = self.buckets.get(idx, 0) + 1
        self.count += 1
        self.sum += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v

    def cumulative(self) -> List[Tuple[float, int]]:
        """Non-empty finite boundaries as ``(le, cumulative_count)``,
        ascending, PLUS the mandatory ``(+Inf, count)`` terminator."""
        out: List[Tuple[float, int]] = []
        cum = 0
        for idx in sorted(self.buckets):
            cum += self.buckets[idx]
            if idx < _OVERFLOW:
                out.append((_bucket_upper(idx), cum))
        out.append((math.inf, self.count))
        return out

    def quantile(self, q: float) -> float:
        """Value at quantile ``q`` in [0, 1]: the inclusive upper bound
        of the bucket holding that rank (error ≤ one sub-bucket width).
        Overflow-bucket ranks report the observed max; empty series 0.
        One implementation — the snapshot owns the rank walk."""
        return HistogramSnapshot(self).quantile(q)


class HistogramSnapshot:
    """Read-side view returned by :func:`get_histogram` — quantile
    readout plus enough list-likeness (``len``, iteration over
    bucket-representative values) for existing call sites."""

    __slots__ = ("count", "sum", "buckets", "vmin", "vmax")

    def __init__(self, hist: Optional[Histogram]):
        if hist is None:
            self.count = 0
            self.sum = 0.0
            self.buckets: List[Tuple[float, int]] = [(math.inf, 0)]
            self.vmin = math.inf
            self.vmax = -math.inf
        else:
            self.count = hist.count
            self.sum = hist.sum
            self.buckets = hist.cumulative()
            self.vmin = hist.vmin
            self.vmax = hist.vmax

    def quantile(self, q: float) -> float:
        if self.count == 0:
            return 0.0
        rank = max(1, math.ceil(q * self.count))
        for le, cum in self.buckets:
            if cum >= rank:
                if math.isinf(le):
                    return self.vmax
                return min(le, self.vmax)
        return self.vmax

    def __len__(self) -> int:
        return self.count

    def __iter__(self) -> Iterator[float]:
        """Bucket-representative values (each boundary repeated by its
        bucket's count), ascending — the bounded stand-in for the raw
        sample list the pre-r8 implementation kept."""
        prev = 0
        for le, cum in self.buckets:
            rep = self.vmax if math.isinf(le) else min(le, self.vmax)
            for _ in range(cum - prev):
                yield rep
            prev = cum

    def __bool__(self) -> bool:
        return self.count > 0


_mu = threading.Lock()
_histograms: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Histogram] = {}
_counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
_gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
#: distinct label sets seen per metric name (the cardinality guard)
_series_counts: Dict[str, int] = {}


def _key(name: str, labels: Dict[str, str]):
    return (name, tuple(sorted(labels.items())))


def _admit(family: dict, key) -> bool:
    """Cardinality guard, called under ``_mu``: admit a NEW series only
    below the per-name cap; a rejected series bumps the dropped counter
    (itself bounded by the number of metric names)."""
    if key in family:
        return True
    name = key[0]
    n = _series_counts.get(name, 0)
    if n >= MAX_SERIES_PER_METRIC:
        dk = (_DROPPED_SERIES, (("metric", name),))
        _counters[dk] = _counters.get(dk, 0.0) + 1.0
        return False
    _series_counts[name] = n + 1
    return True


def observe(name: str, value: float, **labels) -> None:
    key = _key(name, labels)
    with _mu:
        h = _histograms.get(key)
        if h is None:
            if not _admit(_histograms, key):
                return
            h = _histograms[key] = Histogram()
        h.observe(value)


def inc(name: str, value: float = 1.0, **labels) -> None:
    key = _key(name, labels)
    with _mu:
        if key not in _counters and not _admit(_counters, key):
            return
        _counters[key] = _counters.get(key, 0.0) + value


def set_gauge(name: str, value: float, **labels) -> None:
    key = _key(name, labels)
    with _mu:
        if key not in _gauges and not _admit(_gauges, key):
            return
        _gauges[key] = value


def reset() -> None:
    with _mu:
        _histograms.clear()
        _counters.clear()
        _gauges.clear()
        _series_counts.clear()


def get_histogram(name: str, **labels) -> HistogramSnapshot:
    with _mu:
        return HistogramSnapshot(_histograms.get(_key(name, labels)))


def get_counter(name: str, **labels) -> float:
    with _mu:
        return _counters.get(_key(name, labels), 0.0)


def quantile(name: str, q: float, **labels) -> float:
    """Percentile readout for a histogram series (p50 = 0.5, p99 = 0.99,
    p999 = 0.999): 0.0 when the series is empty."""
    return get_histogram(name, **labels).quantile(q)


# -- recording helpers mirroring the reference call sites --------------------

def update_e2e_duration(start: float) -> None:
    observe("volcano_e2e_scheduling_latency_milliseconds", (time.perf_counter() - start) * 1e3)


def update_action_duration(action: str, start: float) -> None:
    observe(
        "volcano_action_scheduling_latency_microseconds",
        (time.perf_counter() - start) * 1e6,
        action=action,
    )


def update_plugin_duration(plugin: str, on_session: str, start: float) -> None:
    observe(
        "volcano_plugin_scheduling_latency_microseconds",
        (time.perf_counter() - start) * 1e6,
        plugin=plugin,
        OnSession=on_session,
    )


def update_task_schedule_duration(duration_s: float) -> None:
    observe("volcano_task_scheduling_latency_microseconds", duration_s * 1e6)


def update_pod_e2e_latency(ms: float) -> None:
    """Reference-parity per-pod e2e latency (metrics.go E2eSchedulingLatency
    family): pod first seen on the bus (creation) -> bind decision, in
    milliseconds.  Emitted from the vtrace bind spans (volcano_tpu/trace.py)
    while tracing is armed, and by the vtload open-loop harness
    (volcano_tpu/loadgen/) for every pod it submits — the series the
    ``bench.py --open-loop`` p50/p99/p999 report reads."""
    observe("volcano_e2e_job_scheduling_latency_milliseconds", ms)


def register_schedule_attempt(succeeded: bool) -> None:
    inc("volcano_schedule_attempts_total", result="scheduled" if succeeded else "unschedulable")


def register_preemption_attempt() -> None:
    # reference-parity name (metrics.go TotalPreemptionAttempts): predates
    # the _total suffix convention, kept verbatim for scrape compatibility
    inc("volcano_total_preemption_attempts")  # vtlint: disable=metric-discipline


def update_preemption_victims(count: int) -> None:
    set_gauge("volcano_pod_preemption_victims", count)


def update_unschedule_task_count(job: str, count: int) -> None:
    set_gauge("volcano_unschedule_task_count", count, job_id=job)


def update_unschedule_job_count(count: int) -> None:
    set_gauge("volcano_unschedule_job_count", count)


def register_job_retry(job: str) -> None:
    # reference-parity name (metrics.go JobRetryCounts), kept verbatim;
    # the per-job label is fenced by the cardinality guard above
    inc("volcano_job_retry_counts", job_id=job)  # vtlint: disable=metric-discipline


def register_residue_tasks(cls: str, count: int) -> None:
    """Tasks the fast cycle routed to the host residue (slow) class this
    cycle, labeled by WHY: ``volume-shape`` (count-inexpressible claim
    pools), ``volume-claim-cap`` (claim intern overflow),
    ``intern-overflow`` (port/selector bitset caps), ``best-effort``
    (empty-request pods of dynamic jobs), ``contended-claims`` (capacity
    group shared with a residue job), ``batch-wave`` (volume jobs
    stepping aside so a batch-scale port/affinity wave keeps the
    batched-rounds kernel).  Monotone counter — `vtctl
    describe job` / operators read it to explain why a pod took the slow
    path."""
    inc("volcano_residue_tasks_total", float(count), **{"class": cls})


# -- vtprof critical-path series (volcano_tpu/vtprof.py) ----------------------

def register_jit_compile(kernel: str, n: int = 1) -> None:
    """XLA compiles observed for one registered kernel (compile-cache
    growth seen by the vtprof sentinel).  In steady state this series
    must be FLAT — shape-bucketing discipline is the contract; any
    post-warmup advance is an anomaly."""
    inc("volcano_jit_compiles_total", float(n), kernel=kernel)


def register_kernel_dispatch(kernel: str, n: int = 1) -> None:
    inc("volcano_kernel_dispatch_total", float(n), kernel=kernel)


def observe_prof_segment(phase: str, segment: str, seconds: float) -> None:
    """One cycle's share of a (phase, segment) cell — segment in
    host/dispatch/wait/transfer, the vtprof critical-path taxonomy."""
    observe("volcano_prof_segment_seconds", seconds,
            phase=phase, segment=segment)


def observe_kernel_device_seconds(kernel: str, seconds: float) -> None:
    """Device wait+transfer the host spent on one kernel in one cycle."""
    observe("volcano_kernel_device_seconds", seconds, kernel=kernel)


def update_device_bytes(component: str, nbytes: int) -> None:
    """Memory watermark gauge: array bytes held per component
    (mirror / snapshot / solve_out / device)."""
    set_gauge("volcano_device_bytes", float(nbytes), component=component)


def register_prof_anomaly(kind: str) -> None:
    inc("volcano_prof_anomalies_total", kind=kind)


# -- vtaudit state-digest series (volcano_tpu/vtaudit.py) ---------------------

def register_audit_check(n: int = 1) -> None:
    """Digest verification passes the mirror completed against a store
    checkpoint (beacon or lock-synchronous compare)."""
    inc("volcano_audit_digest_checks_total", float(n))


def register_audit_divergence(n: int = 1) -> None:
    """Digest mismatches — in steady state this series must stay at
    ZERO; any advance is the steady-state-divergence anomaly."""
    inc("volcano_audit_divergence_total", float(n))


def observe_beacon_lag(seconds: float) -> None:
    """Age of the beacon a verification pass consumed (beacon wall-clock
    stamp to verify time) — how stale the audited checkpoint was."""
    observe("volcano_audit_beacon_lag_seconds", seconds)


# -- store WAL durability series (volcano_tpu/store/wal.py) -------------------

def register_wal_append(n: int = 1) -> None:
    """Records appended to the store's write-ahead log (one per mutation
    request/op; a whole decision segment is ONE record)."""
    inc("volcano_store_wal_appended_records_total", float(n))


def register_wal_fsync(n: int = 1) -> None:
    """Group-commit fsyncs of the WAL tail — the ACK barrier.  The ratio
    to appended_records shows how well group commit amortizes."""
    inc("volcano_store_wal_fsync_total", float(n))


def observe_wal_fsync(seconds: float) -> None:
    """Duration of one group-commit fsync — the histogram that makes the
    ACK barrier's tail latency visible on /metrics and in ``vtctl top``
    (the ``_total`` counters above only show volume)."""
    observe("volcano_store_wal_fsync_seconds", seconds)


def register_wal_recovery(n: int) -> None:
    """Records replayed from the WAL tail during crash recovery."""
    inc("volcano_store_wal_recovery_replayed_records_total", float(n))


# -- vtrepl replication series (volcano_tpu/store/replica.py) -----------------

def update_repl_lag(seconds: float) -> None:
    """Follower replication lag: 0 while caught up with the leader's
    seq, else seconds since this follower was last caught up."""
    set_gauge("volcano_repl_lag_seconds", seconds)


def register_repl_shipped(n: int = 1) -> None:
    """Synced records shipped over /repl/feed (leader side; a whole
    decision segment is ONE record, same unit as wal_appended)."""
    inc("volcano_repl_shipped_segments_total", float(n))


def update_repl_applied_seq(seq: int) -> None:
    """Newest leader seq this replica has applied — cross-replica skew
    at a glance next to the leader's ship_seq."""
    set_gauge("volcano_repl_applied_seq", seq)


def register_repl_redirect(n: int = 1) -> None:
    """Mutations rejected with a NotLeader redirect (a writer pointed at
    a follower replica; steadily advancing = a client not refollowing)."""
    inc("volcano_repl_follower_redirects_total", float(n))


# -- vtdelta incremental-scheduling series (scheduler/delta/) -----------------

def register_delta_micro_cycle(n: int = 1) -> None:
    """Micro-cycle snapshot builds: the dirty-set diff replaced the full
    O(P) pod sweeps.  A cycle that later rebuilds full for contention
    still counts — the series counts BUILDS, not published cycles."""
    inc("volcano_delta_micro_cycles_total", float(n))


def register_delta_fallback(reason: str) -> None:
    """Full snapshot builds while delta mode is on, by trigger: arm /
    init / resync / node-add / node-remove / job-remove / job-requeue /
    job-dropped / dynamic / dirty-storm / contention."""
    inc("volcano_delta_full_fallbacks_total", reason=reason)


def register_delta_shed(n: int = 1) -> None:
    """Gangs newly shed to the Backlogged condition by the admission
    controller's high watermark (re-admitted gangs don't decrement —
    monotone counter; live depth is the cycle row's shed_gangs field)."""
    inc("volcano_delta_shed_gangs_total", float(n))


# -- vtfleet process-supervision series (store/procmesh, vtfleet.py) ----------

def register_proc_restart(shard: int, replica: int = 0) -> None:
    """Supervisor respawns of one mesh member — the crash-forensics
    counter the SIGKILL-storm acceptance reconciles against the
    supervisor's own restart count."""
    inc("volcano_proc_restarts_total",
        shard=f"{int(shard):02d}", replica=str(int(replica)))


def update_proc_up(shard: int, up: bool, replica: int = 0) -> None:
    """Liveness gauge per supervised mesh member (1 while the child
    process is alive, 0 between its death and the respawn)."""
    set_gauge("volcano_proc_up", 1.0 if up else 0.0,
              shard=f"{int(shard):02d}", replica=str(int(replica)))


# -- elastic autoscaler series (volcano_tpu/elastic/) -------------------------

def update_pool_size(pool: str, size: int) -> None:
    set_gauge("volcano_elastic_pool_size", size, pool=pool)


def update_pending_demand(pool: str, nodes: int) -> None:
    set_gauge("volcano_elastic_pending_demand_nodes", nodes, pool=pool)


def register_scale_event(pool: str, direction: str) -> None:
    inc("volcano_elastic_scale_events_total", pool=pool, direction=direction)


def register_drain_eviction(pool: str) -> None:
    inc("volcano_elastic_drain_evictions_total", pool=pool)


# -- exposition ---------------------------------------------------------------

#: HELP strings for the exposition (fallback is generated); keep these
#: one-line — they land verbatim in the text format
_HELP: Dict[str, str] = {
    "volcano_e2e_scheduling_latency_milliseconds":
        "End-to-end scheduling cycle latency in milliseconds",
    "volcano_e2e_job_scheduling_latency_milliseconds":
        "Pod first-seen to bind-decision latency in milliseconds",
    "volcano_action_scheduling_latency_microseconds":
        "Per-action scheduling latency in microseconds",
    "volcano_plugin_scheduling_latency_microseconds":
        "Per-plugin callback latency in microseconds",
    "volcano_task_scheduling_latency_microseconds":
        "Per-task scheduling latency in microseconds",
    "volcano_schedule_attempts_total":
        "Schedule attempts by result",
    "volcano_residue_tasks_total":
        "Tasks routed to the host residue path, by reason class",
    "volcano_audit_digest_checks_total":
        "Mirror-vs-store digest verification passes completed",
    "volcano_audit_divergence_total":
        "State digest mismatches detected (steady state: zero)",
    "volcano_audit_beacon_lag_seconds":
        "Age of the digest beacon consumed by a verification pass",
    "volcano_store_wal_appended_records_total":
        "Records appended to the store write-ahead log",
    "volcano_store_wal_fsync_total":
        "Group-commit fsyncs of the WAL tail (the ACK barrier)",
    "volcano_store_wal_fsync_seconds":
        "Duration of one group-commit WAL fsync in seconds",
    "volcano_store_wal_recovery_replayed_records_total":
        "WAL records replayed during crash recovery",
    "volcano_repl_lag_seconds":
        "Follower replication lag behind the leader in seconds",
    "volcano_repl_shipped_segments_total":
        "Synced WAL records shipped to followers over /repl/feed",
    "volcano_repl_applied_seq":
        "Newest leader sequence number applied by this replica",
    "volcano_repl_follower_redirects_total":
        "Writes rejected by a follower with a NotLeader redirect",
    "volcano_decision_drain_batch_seconds":
        "Wall seconds one async-applier batch took to reach the store",
    "volcano_jit_compiles_total":
        "XLA compiles per kernel (steady state must stay flat)",
    "volcano_kernel_dispatch_total":
        "Jitted kernel dispatches per kernel",
    "volcano_prof_segment_seconds":
        "Per-cycle critical-path share by phase and segment",
    "volcano_kernel_device_seconds":
        "Per-cycle device wait+transfer seconds per kernel",
    "volcano_device_bytes":
        "Array bytes held per component (memory watermark)",
    "volcano_prof_anomalies_total":
        "vtprof sentinel trips (steady-state recompiles, leaks) by kind",
    "volcano_delta_micro_cycles_total":
        "Micro-cycle snapshot builds (dirty-set diff, no full sweep)",
    "volcano_delta_full_fallbacks_total":
        "Full snapshot builds under delta mode, by trigger reason",
    "volcano_delta_shed_gangs_total":
        "Gangs shed to the Backlogged condition by admission control",
    "volcano_proc_restarts_total":
        "Supervisor respawns of a mesh shard process, by shard/replica",
    "volcano_proc_up":
        "Liveness of a supervised mesh member (1 alive, 0 dead)",
    "volcano_fleet_harvests_total":
        "Fleet observability harvest rounds completed",
    "volcano_fleet_harvest_errors_total":
        "Procs unreachable during fleet harvest rounds",
    _DROPPED_SERIES:
        "Observations dropped by the per-metric label-cardinality cap",
}


def _help_line(name: str, mtype: str) -> str:
    return _HELP.get(name, f"volcano-tpu {mtype} {name}")


def _fmt(labels) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


def _num(v: float) -> str:
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return f"{f:.10g}"


def _le(le: float) -> str:
    return "+Inf" if math.isinf(le) else _num(le)


def expose_text() -> str:
    """Prometheus text exposition of all recorded series: HELP/TYPE per
    family, histogram ``_bucket``/``_sum``/``_count`` encoding, byte-
    stable ordering (families alphabetical, series by label tuple)."""
    with _mu:
        counters = sorted(_counters.items())
        gauges = sorted(_gauges.items())
        hists = sorted(
            (k, HistogramSnapshot(h)) for k, h in _histograms.items()
        )
    families: Dict[str, Tuple[str, list]] = {}
    for (name, labels), value in counters:
        families.setdefault(name, ("counter", []))[1].append((labels, value))
    for (name, labels), value in gauges:
        families.setdefault(name, ("gauge", []))[1].append((labels, value))
    for (name, labels), snap in hists:
        families.setdefault(name, ("histogram", []))[1].append((labels, snap))
    lines: List[str] = []
    for name in sorted(families):
        mtype, series = families[name]
        lines.append(f"# HELP {name} {_help_line(name, mtype)}")
        lines.append(f"# TYPE {name} {mtype}")
        for labels, value in series:
            if mtype != "histogram":
                lines.append(f"{name}{_fmt(labels)} {_num(value)}")
                continue
            for le, cum in value.buckets:
                blabels = labels + (("le", _le(le)),)
                lines.append(f"{name}_bucket{_fmt(blabels)} {cum}")
            lines.append(f"{name}_sum{_fmt(labels)} {_num(value.sum)}")
            lines.append(f"{name}_count{_fmt(labels)} {value.count}")
    return "\n".join(lines) + "\n"
