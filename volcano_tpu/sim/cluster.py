"""Cluster: store + scheduler + controller + simulated kubelet.

The kubelet model: a bound pod (node_name set) starts Running on the next
kubelet step; a pod marked ``deleting`` is reaped (deleted from the store)
on the next step — the window in between is exactly the reference's
Releasing state that pipelined tasks wait on (SURVEY.md §3.5).

Fault injection mirrors the reference e2e suite's "kill pods via API"
approach (job_error_handling.go:142+): ``fail_pod`` / ``complete_pod`` /
``evict_pod`` mutate pod phase through the store so every watcher sees the
same event stream a real kubelet would produce.
"""

from __future__ import annotations

from typing import Optional

from volcano_tpu.api.objects import (
    Metadata,
    Node,
    PersistentVolume,
    PriorityClass,
    Queue,
    StorageClass,
)
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import PodPhase
from volcano_tpu.controller import JobController
from volcano_tpu.scheduler.conf import full_conf
from volcano_tpu.scheduler.scheduler import Scheduler
from volcano_tpu.store import Store


class _SimClock:
    """Picklable view of the cluster's step clock (vtctl pickles the
    simulated cluster between invocations; a lambda would not survive)."""

    def __init__(self, cluster: "Cluster"):
        self.cluster = cluster

    def __call__(self) -> float:
        return self.cluster.now


class Cluster:
    def __init__(
        self,
        scheduler_conf=None,
        with_controller: bool = True,
        with_scheduler: bool = True,
    ):
        self.store = Store()
        self.controller: Optional[JobController] = (
            JobController(self.store) if with_controller else None
        )
        self.scheduler: Optional[Scheduler] = None
        if with_scheduler:
            self.scheduler = Scheduler(self.store, conf=scheduler_conf or full_conf())
        # sim clock: one tick per step(); provision delays / hysteresis
        # windows are measured in steps.  The elastic autoscaler is OFF by
        # default — constructed lazily by the first add_node_pool, so a
        # pool-less cluster never pays a pump (zero hot-path change).
        self.now = 0.0
        self.elastic = None

    # -- topology -------------------------------------------------------------

    def add_queue(self, name: str, weight: int = 1) -> Queue:
        return self.store.create(
            "Queue", Queue(meta=Metadata(name=name, namespace=""), weight=weight)
        )

    def add_node(self, name: str, resources=None, **node_kw) -> Node:
        alloc = (
            resources
            if isinstance(resources, Resource)
            else Resource.from_resource_list(resources or {"cpu": "4", "memory": "8Gi"})
        )
        return self.store.create(
            "Node",
            Node(meta=Metadata(name=name, namespace=""), allocatable=alloc, **node_kw),
        )

    def add_storage_class(
        self, name: str, provisioner: str = "volcano.tpu/dynamic"
    ) -> StorageClass:
        """provisioner="" declares a static-only class: claims bind to
        pre-created PVs (``add_pv``) chosen by the scheduler's VolumeBinder."""
        return self.store.create(
            "StorageClass",
            StorageClass(
                meta=Metadata(name=name, namespace=""), provisioner=provisioner
            ),
        )

    def add_pv(
        self,
        name: str,
        capacity: str = "",
        storage_class: str = "",
        node_affinity=None,
    ) -> PersistentVolume:
        """Pre-created volume; ``node_affinity`` is a node-label selector
        (e.g. {"kubernetes.io/hostname": "n0"} for a local volume)."""
        return self.store.create(
            "PV",
            PersistentVolume(
                meta=Metadata(name=name, namespace=""),
                capacity=capacity,
                storage_class=storage_class,
                node_affinity=dict(node_affinity or {}),
            ),
        )

    def add_node_pool(
        self,
        name: str,
        resources=None,
        labels=None,
        taints=None,
        min_size: int = 0,
        max_size: int = 8,
        provision_delay: float = 0.0,
        hysteresis: float = 0.0,
        priority: int = 0,
    ):
        """Declare an elastic NodePool and switch on the autoscaler pump
        (volcano_tpu/elastic/).  Delays/hysteresis are in sim steps."""
        from volcano_tpu.api.objects import NodePool
        from volcano_tpu.elastic import ElasticController

        alloc = (
            resources
            if isinstance(resources, Resource)
            else Resource.from_resource_list(resources or {"cpu": "4", "memory": "8Gi"})
        )
        pool = self.store.create(
            "NodePool",
            NodePool(
                meta=Metadata(name=name, namespace=""),
                resources=alloc,
                labels=dict(labels or {}),
                taints=list(taints or []),
                min_size=min_size,
                max_size=max_size,
                provision_delay=provision_delay,
                hysteresis=hysteresis,
                priority=priority,
            ),
        )
        if self.elastic is None:
            self.elastic = ElasticController(self.store, clock=_SimClock(self))
        return pool

    def add_priority_class(self, name: str, value: int, global_default=False):
        return self.store.create(
            "PriorityClass",
            PriorityClass(
                meta=Metadata(name=name, namespace=""),
                value=value,
                global_default=global_default,
            ),
        )

    # -- job submission (through admission, like the API server path) --------

    def submit_job(self, job):
        """Mutate + validate + persist, the webhook-gated create path.
        Raises AdmissionError on rejection."""
        from volcano_tpu.admission import admit_and_create

        return admit_and_create(self.store, job)

    # -- kubelet --------------------------------------------------------------

    def kubelet_step(self) -> bool:
        """One pass of the simulated kubelets over all pods — and over
        Provisioning elastic nodes, which flip Ready once the sim clock
        passes their provision delay (elastic/lifecycle.py)."""
        from volcano_tpu import trace

        changed = False
        for pod in self.store.items("Pod"):
            if pod.deleting:
                self.store.delete("Pod", pod.meta.key)
                changed = True
            elif pod.node_name and pod.phase == PodPhase.PENDING:
                pod.phase = PodPhase.RUNNING
                self.store.update("Pod", pod)
                changed = True
                if trace.TRACER is not None:
                    tid = trace.gang_trace(pod.meta)
                    if tid:
                        # the sim IS the kubelet in local mode: the Ready
                        # flip joins the gang's trace here too
                        with trace.span("kubelet.ready", trace_id=tid,
                                        pod=pod.meta.key,
                                        node=pod.node_name):
                            pass
        if self.elastic is not None:
            from volcano_tpu.elastic import kubelet_provisioning_step

            changed |= kubelet_provisioning_step(self.store, self.now)
        return changed

    # -- fault injection ------------------------------------------------------

    def fail_pod(self, key: str, exit_code: int = 1) -> None:
        pod = self.store.get("Pod", key)
        pod.phase = PodPhase.FAILED
        pod.exit_code = exit_code
        self.store.update("Pod", pod)

    def complete_pod(self, key: str) -> None:
        pod = self.store.get("Pod", key)
        pod.phase = PodPhase.SUCCEEDED
        self.store.update("Pod", pod)

    def evict_pod(self, key: str) -> None:
        pod = self.store.get("Pod", key)
        pod.deleting = True
        self.store.update("Pod", pod)

    # -- stepping -------------------------------------------------------------

    def pump_controller(self) -> bool:
        return self.controller.pump() if self.controller else False

    def pump_elastic(self) -> bool:
        return self.elastic.pump() if self.elastic else False

    def schedule_once(self) -> bool:
        if self.scheduler is None:
            return False
        rv = self.store.resource_version
        self.scheduler.run_once()
        return self.store.resource_version != rv

    def step(self) -> bool:
        """controller pump -> elastic pump -> scheduler cycle -> kubelet;
        True if anything moved.  The sim clock advances one tick per step
        (provision delays and hysteresis windows count steps).

        A step that only waits out a provision delay still counts as
        movement: the clock tick IS the progress, and run_until_idle must
        not report quiescence while nodes are Provisioning and gangs wait
        on them.  (A pending scale-DOWN hysteresis window is NOT movement
        — the cluster is in a stable, fully schedulable state.)"""
        self.now += 1.0
        moved = self.pump_controller()
        moved |= self.pump_elastic()
        moved |= self.schedule_once()
        moved |= self.kubelet_step()
        moved |= self.pump_controller()
        if not moved and self.elastic is not None:
            from volcano_tpu.elastic import PROVISIONING, node_state

            moved = any(
                node_state(n) == PROVISIONING
                for n in self.store.items("Node")
            )
        return moved

    def run_until_idle(self, max_steps: int = 64) -> int:
        """Step until quiescent; returns steps taken. The equivalent of the
        reference e2e's phase-waiter polling loops."""
        for i in range(max_steps):
            if not self.step():
                return i
        raise RuntimeError(f"cluster did not quiesce in {max_steps} steps")
