"""Simulated cluster: the e2e substrate replacing the reference's kind rig.

The reference tests multi-node behavior with Docker-in-docker kind clusters
(hack/run-e2e-kind.sh). Here a ``Cluster`` wires the store, scheduler,
controller, and a simulated kubelet together with deterministic stepping —
fault injection is just mutating pods.
"""

from volcano_tpu.sim.cluster import Cluster

__all__ = ["Cluster"]
