"""Fast structural deep-clone for API objects.

The store shadows every written object (no-op suppression + Event.old), so
object copying sits on the hot write path — at bench scale that is one copy
per bind. ``copy.deepcopy`` pays generic dispatch, memo bookkeeping, and
``__reduce_ex__`` per node; this walker knows the API-object shape (flat
dataclasses of primitives, dicts, lists, tuples, enums, and ``Resource``)
and caches per-class field lists, which makes it ~20x faster on a Pod.

Falls back to ``copy.deepcopy`` for any type it has not been taught, so
correctness never depends on the fast path.
"""

from __future__ import annotations

import copy
import dataclasses
import enum
from typing import Any, Dict, Tuple

# atomics returned as-is; enums join lazily via _register
_ATOMIC = {str, int, float, bool, type(None), bytes}

# class -> tuple of attribute names to walk, or None for deepcopy fallback
_FIELDS: Dict[type, Tuple[str, ...]] = {}


def _register(t: type, obj: Any):
    if issubclass(t, enum.Enum):
        _ATOMIC.add(t)
        return ()
    if dataclasses.is_dataclass(t):
        names = tuple(f.name for f in dataclasses.fields(t))
        _FIELDS[t] = names
        return names
    slots = getattr(t, "__slots__", None)
    if slots is not None and not hasattr(obj, "__dict__"):
        _FIELDS[t] = tuple(slots)
        return tuple(slots)
    _FIELDS[t] = None
    return None


def deep_clone(o: Any) -> Any:
    t = o.__class__
    if t in _ATOMIC:
        return o
    if t is dict:
        return {k: deep_clone(v) for k, v in o.items()}
    if t is list:
        return [deep_clone(v) for v in o]
    if t is tuple:
        return tuple(deep_clone(v) for v in o)
    fields = _FIELDS.get(t)
    if fields is None:
        if t in _FIELDS:  # registered as not-fast-cloneable
            return copy.deepcopy(o)
        fields = _register(t, o)
        if t in _ATOMIC:
            return o
        if fields is None:
            return copy.deepcopy(o)
    new = object.__new__(t)
    for f in fields:
        setattr(new, f, deep_clone(getattr(o, f)))
    return new
