"""Shared enums: task status, job phases, lifecycle events and actions.

Parity sources:
  * TaskStatus           — reference KB/pkg/scheduler/api/types.go:20-53
  * JobPhase             — reference pkg/apis/batch/v1alpha1/job.go:180-214
  * JobEvent / JobAction — reference pkg/apis/batch/v1alpha1/job.go:92-146
  * PodGroupPhase        — reference KB/pkg/apis/scheduling/v1alpha1/types.go:27-44
"""

from __future__ import annotations

import enum


class TaskStatus(enum.IntFlag):
    """Scheduler-side view of a task/pod. Bitmask so status sets are cheap."""

    PENDING = 1 << 0      # pending in the store, no node assigned
    ALLOCATED = 1 << 1    # scheduler assigned a host (session-local)
    PIPELINED = 1 << 2    # assigned a host, waiting on releasing resources
    BINDING = 1 << 3      # bind request in flight
    BOUND = 1 << 4        # bound to a host
    RUNNING = 1 << 5      # running on the host
    RELEASING = 1 << 6    # being deleted
    SUCCEEDED = 1 << 7
    FAILED = 1 << 8
    UNKNOWN = 1 << 9


#: statuses whose resources are charged against the node (helpers.go:66-73)
ALLOCATED_STATUSES = (
    TaskStatus.BOUND | TaskStatus.BINDING | TaskStatus.RUNNING | TaskStatus.ALLOCATED
)


def allocated_status(status: TaskStatus) -> bool:
    return bool(status & ALLOCATED_STATUSES)


class PodPhase(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


def task_status_of_pod(pod) -> TaskStatus:
    """Map a pod's phase + deletion mark + node assignment to a TaskStatus.

    Parity: reference KB/pkg/scheduler/api/helpers.go:38-63.
    """
    phase = pod.phase
    if phase == PodPhase.RUNNING:
        return TaskStatus.RELEASING if pod.deleting else TaskStatus.RUNNING
    if phase == PodPhase.PENDING:
        if pod.deleting:
            return TaskStatus.RELEASING
        return TaskStatus.BOUND if pod.node_name else TaskStatus.PENDING
    if phase == PodPhase.SUCCEEDED:
        return TaskStatus.SUCCEEDED
    if phase == PodPhase.FAILED:
        return TaskStatus.FAILED
    return TaskStatus.UNKNOWN


class JobPhase(str, enum.Enum):
    PENDING = "Pending"
    INQUEUE = "Inqueue"
    ABORTING = "Aborting"
    ABORTED = "Aborted"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    COMPLETING = "Completing"
    COMPLETED = "Completed"
    TERMINATING = "Terminating"
    TERMINATED = "Terminated"
    FAILED = "Failed"


class JobEvent(str, enum.Enum):
    ANY = "*"
    POD_FAILED = "PodFailed"
    POD_EVICTED = "PodEvicted"
    JOB_UNKNOWN = "Unknown"
    TASK_COMPLETED = "TaskCompleted"
    OUT_OF_SYNC = "OutOfSync"          # internal: object changed
    COMMAND_ISSUED = "CommandIssued"   # internal: Command CR received


class JobAction(str, enum.Enum):
    ABORT_JOB = "AbortJob"
    RESTART_JOB = "RestartJob"
    RESTART_TASK = "RestartTask"
    TERMINATE_JOB = "TerminateJob"
    COMPLETE_JOB = "CompleteJob"
    RESUME_JOB = "ResumeJob"
    SYNC_JOB = "SyncJob"
    ENQUEUE_JOB = "EnqueueJob"


class PodGroupPhase(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    UNKNOWN = "Unknown"
    INQUEUE = "Inqueue"


class PodGroupConditionType(str, enum.Enum):
    UNSCHEDULABLE = "Unschedulable"
