from volcano_tpu.api.resource import Resource, MIN_MILLI_CPU, MIN_MEMORY, MIN_SCALAR
from volcano_tpu.api.types import (
    TaskStatus,
    JobPhase,
    JobEvent,
    JobAction,
    PodGroupPhase,
    PodPhase,
    allocated_status,
)
from volcano_tpu.api.job import (
    Job,
    JobSpec,
    JobStatus,
    TaskSpec,
    LifecyclePolicy,
    VolumeSpec,
    TASK_SPEC_KEY,
    JOB_NAME_KEY,
    JOB_VERSION_KEY,
    POD_GROUP_KEY,
)
from volcano_tpu.api.objects import (
    Command,
    Node,
    NodePool,
    NodePoolStatus,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodGroup,
    PodGroupStatus,
    Queue,
    StorageClass,
    Toleration,
    Taint,
)

__all__ = [n for n in dir() if not n.startswith("_")]
