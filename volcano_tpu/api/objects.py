"""Core cluster objects: Pod, Node, PodGroup, Queue, Command.

These are the analogs of the reference's CRD + k8s core types, reduced to
the fields the scheduler/controller/admission paths actually consume:

  * PodGroup/Queue — reference KB/pkg/apis/scheduling/v1alpha1/types.go:90-222
  * Command       — reference pkg/apis/bus/v1alpha1/types.go:7-27
  * Pod/Node      — the subset of k8s core/v1 used by the predicates and cache
"""

from __future__ import annotations

import os
import secrets
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import PodGroupPhase, PodPhase

_uid_lock = threading.Lock()
_uid_next = 1
# process-unique token: daemons on separate RemoteStores each run their own
# counter, so uids (and Event object names built from them) must not collide
# across processes
_uid_token = f"{os.getpid():x}{secrets.token_hex(2)}"


def _advance_uids(n: int) -> int:
    global _uid_next
    with _uid_lock:
        start = _uid_next
        _uid_next += n
    return start


def new_uid(prefix: str = "obj") -> str:
    return f"{prefix}-{_uid_token}-{_advance_uids(1):08d}"


def reserve_uids(prefix: str, n: int) -> Tuple[str, int]:
    """Reserve ``n`` consecutive uid-counter slots in one lock hold and
    return ``(token, start)``: slot ``start + i`` names the uid
    ``f"{prefix}-{token}-{start + i:08d}"``.  A decision segment reserves
    its whole Event block this way, so the server can derive every Event
    name without a per-row uid round trip (store/segment.py)."""
    del prefix  # part of the derived name, not the reservation
    return _uid_token, _advance_uids(n)


@dataclass
class Metadata:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: int = 0
    creation_timestamp: float = 0.0  # epoch seconds, stamped by Store.create
    owner: Optional[Tuple[str, str]] = None  # (kind, name) of controlling object

    def __post_init__(self):
        if not self.uid:
            self.uid = new_uid(self.name or "obj")

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists; empty key + Exists tolerates all
    value: str = ""
    effect: str = ""  # empty matches all effects

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if not self.key:
            return self.operator == "Exists"
        if self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


@dataclass
class Affinity:
    """Node + pod (anti)affinity, reduced to label-match terms.

    node_terms: OR-of-AND label requirements, each a list of
    (key, op, values) with op in {In, NotIn, Exists, DoesNotExist, Gt, Lt}.
    preferred_node_terms: (weight, term) pairs for scoring.
    pod_affinity/pod_anti_affinity: label selectors matched against other
    pods on the node (topology = node, the only topology in the simulator).
    """

    node_terms: List[List[Tuple[str, str, Tuple[str, ...]]]] = field(default_factory=list)
    preferred_node_terms: List[Tuple[int, List[Tuple[str, str, Tuple[str, ...]]]]] = field(
        default_factory=list
    )
    pod_affinity: List[Dict[str, str]] = field(default_factory=list)
    pod_anti_affinity: List[Dict[str, str]] = field(default_factory=list)


def match_expressions(labels: Dict[str, str], term) -> bool:
    """Evaluate one AND-term of (key, op, values) against a label map."""
    for key, op, values in term:
        v = labels.get(key)
        if op == "In":
            if v is None or v not in values:
                return False
        elif op == "NotIn":
            if v is not None and v in values:
                return False
        elif op == "Exists":
            if v is None:
                return False
        elif op == "DoesNotExist":
            if v is not None:
                return False
        elif op == "Gt":
            if v is None or not v.lstrip("-").isdigit() or int(v) <= int(values[0]):
                return False
        elif op == "Lt":
            if v is None or not v.lstrip("-").isdigit() or int(v) >= int(values[0]):
                return False
        else:
            return False
    return True


@dataclass
class PodSpec:
    resources: Resource = field(default_factory=Resource)       # sum of containers
    init_resources: Resource = field(default_factory=Resource)  # max of init containers
    image: str = ""                                             # container image
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    host_ports: List[int] = field(default_factory=list)
    priority_class: str = ""
    priority: int = 0
    restart_policy: str = "OnFailure"
    scheduler_name: str = "volcano-tpu"
    best_effort: bool = False  # derived: empty resreq

    def resreq(self) -> Resource:
        return self.resources.clone()

    def init_resreq(self) -> Resource:
        r = self.resources.clone()
        r.set_max(self.init_resources)
        return r


@dataclass
class Pod:
    meta: Metadata
    spec: PodSpec = field(default_factory=PodSpec)
    phase: PodPhase = PodPhase.PENDING
    node_name: str = ""
    deleting: bool = False
    exit_code: int = 0          # of first failed container, for policy matching
    subdomain: str = ""
    hostname: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    volumes: List[str] = field(default_factory=list)  # mounted claim/config names

    @property
    def key(self) -> str:
        return self.meta.key


@dataclass
class NodeCondition:
    kind: str  # Ready | OutOfDisk | MemoryPressure | DiskPressure | PIDPressure
    status: str = "True"


@dataclass
class Node:
    meta: Metadata
    allocatable: Resource = field(default_factory=Resource)
    capacity: Resource = field(default_factory=Resource)
    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False
    conditions: List[NodeCondition] = field(default_factory=lambda: [NodeCondition("Ready")])

    def __post_init__(self):
        if self.capacity.is_empty() and not self.allocatable.is_empty():
            self.capacity = self.allocatable.clone()
        # node name is both metadata and a label (kubernetes.io/hostname)
        self.labels.setdefault("kubernetes.io/hostname", self.meta.name)

    def ready(self) -> bool:
        for c in self.conditions:
            if c.kind == "Ready":
                return c.status == "True"
        return False


@dataclass
class NodePoolStatus:
    """Observed lifecycle counts, written by the elastic controller."""

    size: int = 0           # owned nodes in any lifecycle state
    ready: int = 0
    provisioning: int = 0
    draining: int = 0
    pending_demand: int = 0  # unclipped bin-pack node demand last reconcile
    scale_ups: int = 0
    scale_downs: int = 0


@dataclass
class NodePool:
    """Elastic node pool: a homogeneous template the autoscaler grows and
    shrinks between ``min_size`` and ``max_size`` against gang demand
    (volcano_tpu/elastic/; Aryl's pool-scaling https://arxiv.org/pdf/2202.07896,
    heterogeneous pools as first-class sizing units per Gavel
    https://arxiv.org/pdf/2008.09213).

    ``resources``/``labels``/``taints`` describe every member node; members
    carry the ``volcano.tpu/pool`` label back to the pool.  ``provision_delay``
    is the (sim-clock) seconds a scale-up node spends Provisioning (Ready
    condition False) before the kubelet flips it Ready; ``hysteresis`` is how
    long demand must stay at zero before scale-down may cordon+drain.
    ``priority`` orders pools for demand absorption (higher first).
    """

    meta: Metadata
    resources: Resource = field(default_factory=Resource)  # template allocatable
    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    min_size: int = 0
    max_size: int = 8
    provision_delay: float = 0.0
    hysteresis: float = 0.0
    priority: int = 0
    status: NodePoolStatus = field(default_factory=NodePoolStatus)


@dataclass
class PodGroupCondition:
    kind: str
    status: str
    reason: str = ""
    message: str = ""


@dataclass
class PodGroupStatus:
    phase: PodGroupPhase = PodGroupPhase.PENDING
    conditions: List[PodGroupCondition] = field(default_factory=list)
    running: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class PodGroup:
    meta: Metadata
    min_member: int = 1
    queue: str = "default"
    priority_class_name: str = ""
    min_resources: Resource = field(default_factory=Resource)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)


@dataclass
class QueueStatus:
    unknown: int = 0
    pending: int = 0
    running: int = 0


@dataclass
class Queue:
    meta: Metadata
    weight: int = 1
    status: QueueStatus = field(default_factory=QueueStatus)


@dataclass
class PriorityClass:
    meta: Metadata
    value: int = 0
    global_default: bool = False


@dataclass
class Command:
    """Async operation channel from the CLI to the controller."""

    meta: Metadata
    action: str = ""
    target: Optional[Tuple[str, str]] = None  # (kind, name)
    reason: str = ""
    message: str = ""


@dataclass
class ConfigMap:
    """Key/value payload attached to jobs by controller plugins (hostfiles,
    ssh keys)."""

    meta: Metadata
    data: Dict[str, str] = field(default_factory=dict)


@dataclass
class Service:
    """Headless service the svc plugin creates per job for task DNS."""

    meta: Metadata
    cluster_ip: str = "None"
    selector: Dict[str, str] = field(default_factory=dict)


@dataclass
class PodDisruptionBudget:
    """Gang grouping for plain controller-owned pods (reference: PDB
    informer + SetPDB, KB/pkg/scheduler/cache/event_handlers.go:494-510,
    api/job_info.go:194-202): pods sharing the PDB's controlling owner
    form one shadow job whose MinAvailable comes from the budget."""

    meta: Metadata  # meta.owner = the controlling object, shared with pods
    min_available: int = 1


@dataclass
class PersistentVolumeClaim:
    """Volume claim created for Job.spec.volumes entries.

    WaitForFirstConsumer semantics: the claim stays ``Pending`` until a pod
    that mounts it is scheduled; the scheduler's VolumeBinder picks (or
    provisions) a PV at allocate time and commits it at bind time
    (reference: KB/pkg/scheduler/cache/interface.go VolumeBinder,
    cache.go:451-463).
    """

    meta: Metadata
    size: str = ""
    storage_class: str = ""
    volume_name: str = ""      # bound PV name; empty while Pending
    phase: str = "Pending"     # Pending | Bound


@dataclass
class StorageClass:
    """Provisioning policy for claims (reference: StorageClass informer,
    KB/pkg/scheduler/cache/cache.go:272-278).

    ``provisioner`` empty means static-only: claims of this class must bind
    to a pre-created PV. Non-empty means dynamic: a PV is provisioned at
    bind time wherever the pod lands.
    """

    meta: Metadata
    provisioner: str = "volcano.tpu/dynamic"
    volume_binding_mode: str = "WaitForFirstConsumer"


@dataclass
class PersistentVolume:
    """A provisioned volume (reference: PV informer, cache.go:258-264).

    ``node_affinity`` is a node-label selector (empty = reachable from any
    node — network storage); local volumes set it to pin claims to one
    node, which constrains scheduling of pods mounting them.
    """

    meta: Metadata
    capacity: str = ""
    storage_class: str = ""
    node_affinity: Dict[str, str] = field(default_factory=dict)
    claim_ref: str = ""        # bound PVC key; empty while Available
    provisioned: bool = False  # dynamically created at bind (vs pre-created)

    @property
    def phase(self) -> str:
        return "Bound" if self.claim_ref else "Available"
