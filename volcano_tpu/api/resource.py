"""Multi-dimensional resource arithmetic with epsilon-tolerant comparisons.

Behavioral parity with the reference scheduler's resource model
(reference: vendor/.../kube-batch/pkg/scheduler/api/resource_info.go):

* two first-class dimensions (cpu in millicores, memory in bytes) plus an
  open-ended map of scalar resources (e.g. accelerators);
* comparisons are epsilon-tolerant: a difference below MIN_MILLI_CPU /
  MIN_MEMORY / MIN_SCALAR counts as equal (resource_info.go:70-72, 255-280);
* ``sub`` refuses to go negative (resource_info.go:145-163);
* ``fit_delta`` subtracts request + epsilon so "negative means insufficient"
  (resource_info.go:196-216).

This module is the *host-side* scalar semantics. The scheduler's hot path
uses the same constants on [N, R] device tensors (see scheduler/snapshot.py);
this class is the oracle those tensors are validated against.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, Mapping, Optional

# Epsilon thresholds (reference resource_info.go:70-72).
MIN_MILLI_CPU = 10.0
MIN_MEMORY = 10.0 * 1024 * 1024
MIN_SCALAR = 10.0

# Canonical name of the accelerator scalar resource in examples/tests.
# The reference hard-codes the NVIDIA device-plugin name; we schedule
# generic accelerators (TPU chips included) through the same scalar map.
ACCELERATOR_RESOURCE = "accelerator"

_MEM_UNITS = {
    "k": 1000.0, "M": 1000.0**2, "G": 1000.0**3, "T": 1000.0**4,
    "Ki": 1024.0, "Mi": 1024.0**2, "Gi": 1024.0**3, "Ti": 1024.0**4,
    "": 1.0,
}


def parse_quantity(name: str, value) -> float:
    """Parse a k8s-style quantity string into the canonical float unit.

    cpu -> millicores, memory -> bytes, scalars -> milli-units
    (the reference stores scalars via MilliValue, resource_info.go:86).
    """
    if isinstance(value, (int, float)):
        num = float(value)
        if name == "cpu":
            return num * 1000.0
        return num * 1000.0 if name not in ("cpu", "memory") else num
    s = str(value).strip()
    if name == "cpu":
        if s.endswith("m"):
            return float(s[:-1])
        return float(s) * 1000.0
    if name == "memory":
        for suffix in sorted(_MEM_UNITS, key=len, reverse=True):
            if suffix and s.endswith(suffix):
                return float(s[: -len(suffix)]) * _MEM_UNITS[suffix]
        return float(s)
    # scalar resources: stored in milli-units
    if s.endswith("m"):
        return float(s[:-1])
    return float(s) * 1000.0


class Resource:
    """A point in resource space: (milli_cpu, memory, scalars...)."""

    __slots__ = ("milli_cpu", "memory", "scalars", "max_task_num")

    def __init__(
        self,
        milli_cpu: float = 0.0,
        memory: float = 0.0,
        scalars: Optional[Mapping[str, float]] = None,
        max_task_num: Optional[int] = None,
    ):
        self.milli_cpu = float(milli_cpu)
        self.memory = float(memory)
        self.scalars: Dict[str, float] = dict(scalars or {})
        # Only used by predicates (pod-count capacity); excluded from arithmetic.
        self.max_task_num = max_task_num

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_resource_list(cls, rl: Optional[Mapping[str, object]]) -> "Resource":
        """Build from a k8s-style resource list, e.g. {"cpu": "2", "memory": "4Gi"}."""
        r = cls()
        for name, q in (rl or {}).items():
            if name == "cpu":
                r.milli_cpu += parse_quantity(name, q)
            elif name == "memory":
                r.memory += parse_quantity(name, q)
            elif name == "pods":
                r.max_task_num = int(float(q))
            else:
                r.scalars[name] = r.scalars.get(name, 0.0) + parse_quantity(name, q)
        return r

    def clone(self) -> "Resource":
        return Resource(self.milli_cpu, self.memory, dict(self.scalars), self.max_task_num)

    # -- predicates ---------------------------------------------------------

    def is_empty(self) -> bool:
        if not (self.milli_cpu < MIN_MILLI_CPU and self.memory < MIN_MEMORY):
            return False
        return all(q < MIN_SCALAR for q in self.scalars.values())

    def is_zero(self, name: str) -> bool:
        if name == "cpu":
            return self.milli_cpu < MIN_MILLI_CPU
        if name == "memory":
            return self.memory < MIN_MEMORY
        return self.scalars.get(name, 0.0) < MIN_SCALAR

    def less(self, other: "Resource") -> bool:
        """Strictly less in every dimension (reference Less, :229-253)."""
        if not (self.milli_cpu < other.milli_cpu and self.memory < other.memory):
            return False
        if not self.scalars:
            return bool(other.scalars)
        for name, q in self.scalars.items():
            if q >= other.scalars.get(name, 0.0):
                return False
        return True

    def less_equal(self, other: "Resource") -> bool:
        """Epsilon-tolerant <= in every dimension (reference LessEqual, :255-280)."""
        ok = (
            self.milli_cpu < other.milli_cpu
            or abs(other.milli_cpu - self.milli_cpu) < MIN_MILLI_CPU
        ) and (
            self.memory < other.memory or abs(other.memory - self.memory) < MIN_MEMORY
        )
        if not ok:
            return False
        for name, q in self.scalars.items():
            oq = other.scalars.get(name, 0.0)
            if not (q < oq or abs(oq - q) < MIN_SCALAR):
                return False
        return True

    # -- arithmetic (mutating, fluent — mirrors the reference API) ----------

    def add(self, other: "Resource") -> "Resource":
        self.milli_cpu += other.milli_cpu
        self.memory += other.memory
        for name, q in other.scalars.items():
            self.scalars[name] = self.scalars.get(name, 0.0) + q
        return self

    def sub(self, other: "Resource") -> "Resource":
        if not other.less_equal(self):
            raise ValueError(
                f"resource not sufficient: {self} sub {other}"
            )
        self.milli_cpu -= other.milli_cpu
        self.memory -= other.memory
        for name, q in other.scalars.items():
            if name in self.scalars:
                self.scalars[name] -= q
        return self

    def multi(self, ratio: float) -> "Resource":
        self.milli_cpu *= ratio
        self.memory *= ratio
        for name in self.scalars:
            self.scalars[name] *= ratio
        return self

    def set_max(self, other: "Resource") -> "Resource":
        """Elementwise max (reference SetMaxResource, :164-191)."""
        self.milli_cpu = max(self.milli_cpu, other.milli_cpu)
        self.memory = max(self.memory, other.memory)
        for name, q in other.scalars.items():
            if q > self.scalars.get(name, 0.0):
                self.scalars[name] = q
        return self

    def fit_delta(self, req: "Resource") -> "Resource":
        """Subtract req + epsilon per requested dim; negative => insufficient."""
        if req.milli_cpu > 0:
            self.milli_cpu -= req.milli_cpu + MIN_MILLI_CPU
        if req.memory > 0:
            self.memory -= req.memory + MIN_MEMORY
        for name, q in req.scalars.items():
            if q > 0:
                self.scalars[name] = self.scalars.get(name, 0.0) - (q + MIN_SCALAR)
        return self

    # -- access -------------------------------------------------------------

    def get(self, name: str) -> float:
        if name == "cpu":
            return self.milli_cpu
        if name == "memory":
            return self.memory
        return self.scalars.get(name, 0.0)

    def names(self) -> Iterable[str]:
        return ["cpu", "memory", *self.scalars.keys()]

    @staticmethod
    def min(l: "Resource", r: "Resource") -> "Resource":
        res = Resource(min(l.milli_cpu, r.milli_cpu), min(l.memory, r.memory))
        if l.scalars and r.scalars:
            for name, q in l.scalars.items():
                res.scalars[name] = min(q, r.scalars.get(name, 0.0))
        return res

    @staticmethod
    def share(l: float, r: float) -> float:
        """l/r with 0/0 = 0 and x/0 = 1 (reference helpers.Share)."""
        if r == 0:
            return 0.0 if l == 0 else 1.0
        return l / r

    def dominant_share(self, total: "Resource") -> float:
        """Max over dims of allocated/total — the DRF share (drf.go:161-172)."""
        res = 0.0
        for name in total.names():
            res = max(res, Resource.share(self.get(name), total.get(name)))
        return res

    # -- dunder -------------------------------------------------------------

    def __eq__(self, other) -> bool:
        if not isinstance(other, Resource):
            return NotImplemented
        names = set(self.scalars) | set(other.scalars)
        return (
            self.milli_cpu == other.milli_cpu
            and self.memory == other.memory
            and all(self.scalars.get(n, 0.0) == other.scalars.get(n, 0.0) for n in names)
        )

    def __repr__(self) -> str:
        s = f"Resource(cpu={self.milli_cpu:.0f}m, mem={self.memory:.0f}"
        for name, q in self.scalars.items():
            s += f", {name}={q:.0f}"
        return s + ")"

    def approx_equal(self, other: "Resource") -> bool:
        """Equal within the epsilon thresholds — used by parity tests."""
        names = set(self.scalars) | set(other.scalars)
        return (
            abs(self.milli_cpu - other.milli_cpu) < MIN_MILLI_CPU
            and abs(self.memory - other.memory) < MIN_MEMORY
            and all(
                abs(self.scalars.get(n, 0.0) - other.scalars.get(n, 0.0)) < MIN_SCALAR
                for n in names
            )
        )
