"""The Job object: multi-task gang jobs with lifecycle policies.

Parity source: reference pkg/apis/batch/v1alpha1/job.go:26-274 and
labels.go:19-25. A Job owns a set of task groups (TaskSpec), each stamping
out ``replicas`` pods from a template; ``min_available`` is the gang size;
``policies`` map (event, exit_code) -> action for the error-handling state
machine; ``plugins`` inject distributed-training plumbing (ssh/svc/env).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from volcano_tpu.api.objects import Metadata, PodSpec
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobAction, JobEvent, JobPhase

# Annotation/label keys linking pods back to jobs
# (parity: reference pkg/apis/batch/v1alpha1/labels.go:19-25).
TASK_SPEC_KEY = "volcano.tpu/task-spec"
JOB_NAME_KEY = "volcano.tpu/job-name"
JOB_VERSION_KEY = "volcano.tpu/job-version"
POD_GROUP_KEY = "scheduling.volcano.tpu/group-name"

DEFAULT_MAX_RETRY = 3


@dataclass
class LifecyclePolicy:
    """(event | exit_code) -> action, with optional timeout.

    Admission enforces event XOR exit_code (admit_job.go policy checks).
    """

    action: JobAction
    event: Optional[JobEvent] = None
    exit_code: Optional[int] = None
    timeout_seconds: Optional[float] = None


@dataclass
class VolumeSpec:
    mount_path: str
    volume_claim_name: str = ""   # existing claim; empty => generated/emptyDir
    size: str = ""                # claim template shorthand
    storage_class: str = ""       # "" = default dynamic class


@dataclass
class TaskSpec:
    name: str = ""
    replicas: int = 0
    template: PodSpec = field(default_factory=PodSpec)
    policies: List[LifecyclePolicy] = field(default_factory=list)


@dataclass
class JobSpec:
    scheduler_name: str = "volcano-tpu"
    min_available: int = 0
    volumes: List[VolumeSpec] = field(default_factory=list)
    tasks: List[TaskSpec] = field(default_factory=list)
    policies: List[LifecyclePolicy] = field(default_factory=list)
    plugins: Dict[str, List[str]] = field(default_factory=dict)
    queue: str = ""
    max_retry: int = DEFAULT_MAX_RETRY
    priority_class: str = ""

    def total_replicas(self) -> int:
        return sum(t.replicas for t in self.tasks)


@dataclass
class JobState:
    phase: JobPhase = JobPhase.PENDING
    reason: str = ""
    message: str = ""


@dataclass
class JobStatus:
    state: JobState = field(default_factory=JobState)
    pending: int = 0
    running: int = 0
    succeeded: int = 0
    failed: int = 0
    terminating: int = 0
    min_available: int = 0
    version: int = 0
    retry_count: int = 0
    controlled_resources: Dict[str, str] = field(default_factory=dict)


@dataclass
class Job:
    meta: Metadata
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)

    @property
    def key(self) -> str:
        return self.meta.key

    def task(self, name: str) -> Optional[TaskSpec]:
        for t in self.spec.tasks:
            if t.name == name:
                return t
        return None


def make_pod_name(job_name: str, task_name: str, index: int) -> str:
    """Pod naming contract ``<job>-<task>-<idx>`` (reference
    pkg/controllers/job/helpers PodNameFmt)."""
    return f"{job_name}-{task_name}-{index}"


def calc_pg_min_resources(job: Job) -> Resource:
    """MinResources for the PodGroup: sum requests of the top-``min_available``
    tasks ordered by pod priority (parity: job_controller_actions.go:467-496).
    """
    res = Resource()
    tasks = sorted(job.spec.tasks, key=lambda t: -t.template.priority)
    remaining = job.spec.min_available
    for t in tasks:
        take = min(t.replicas, remaining)
        for _ in range(take):
            res.add(t.template.resreq())
        remaining -= take
        if remaining <= 0:
            break
    return res
