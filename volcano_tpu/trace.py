"""vtrace: end-to-end scheduling traces + a per-process flight recorder.

The reference answers "what happened inside cycle N" with glog V-levels
and "why is this pod pending" with Events; neither survives a crash nor
crosses a process boundary.  This module gives every control-plane
process a span runtime with the chaos-style arming discipline
(volcano_tpu/chaos.py): **disarmed is the default and costs one module
attribute check per instrumentation site** (``TRACER is None``), armed is
opt-in through ``VOLCANO_TPU_TRACE``.

Concepts
--------

* A **span** is one timed unit of work (a scheduler cycle, one action,
  one plugin callback, a store request).  Spans carry a ``trace_id`` /
  ``span_id`` / ``parent_id`` triple; nesting is ambient (thread-local):
  a span opened inside another becomes its child, a span opened with no
  ambient context roots a fresh trace.
* The **flight recorder** is a bounded per-process ring buffer of
  completed spans.  It is served live by the ``/debug/trace`` admin
  endpoint (store server and MetricsServer — exempt from chaos injection,
  like ``/chaos``) and dumped as a JSON artifact on daemon crash or
  invariant violation (:func:`crash_dump`).
* **Cross-daemon propagation** rides two channels: the synchronous hop
  attaches the active context to every RemoteStore request as an
  ``X-Volcano-Trace`` header (the store server continues it), and the
  asynchronous hop rides the objects — ``vtctl job run`` stamps the root
  trace id into the Job's ``volcano.sh/trace-id`` annotation
  (:func:`stamp`), the controller copies it onto the PodGroup and pods,
  and the scheduler/kubelet join that trace at bind / Ready-flip time.

Arming: ``VOLCANO_TPU_TRACE=1`` (defaults) or a JSON dict
``{"ring": 4096, "dir": "/path/for/crash/dumps"}``.  ``0``/``off``/unset
disarm.  Tests arm in-process via :func:`arm`/:func:`disarm`.

Discipline (enforced by the vtlint ``trace-span-discipline`` rule): spans
are opened with ``with span(...)`` only — no manual begin/end pairs — and
never inside jit-traced bodies; device work is timed exclusively at
block-until-ready boundaries.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from volcano_tpu.locksan import make_lock

ENV_VAR = "VOLCANO_TPU_TRACE"
#: wire header carrying "trace_id span_id" between RemoteStore and server
HEADER = "X-Volcano-Trace"
#: object annotation carrying a gang's trace id across the store bus
TRACE_ID_KEY = "volcano.sh/trace-id"
DEFAULT_RING = 4096

_uid_mu = threading.Lock()
_uid_n = 0


def new_id(prefix: str) -> str:
    """Process-unique, creation-ordered id (pid-salted so ids from
    different daemons never collide in a merged dump)."""
    global _uid_n
    with _uid_mu:
        _uid_n += 1
        n = _uid_n
    return f"{prefix}-{os.getpid():x}-{n:08d}"


class _Ctx(threading.local):
    """Ambient trace context: each thread nests its own span stack."""

    trace_id = ""
    span_id = ""
    component = ""


_ctx = _Ctx()
#: process-default component name (first set_component wins); threads can
#: override for themselves (the chaos soak runs three "daemons" in one
#: process)
_proc_component = ""


def set_component(name: str) -> None:
    """Name the daemon this thread's spans belong to ("scheduler",
    "controller", "kubelet", "apiserver", ...)."""
    global _proc_component
    _ctx.component = name
    if not _proc_component:
        _proc_component = name


def component() -> str:
    return _ctx.component or _proc_component


def current() -> Tuple[str, str]:
    """(trace_id, span_id) of the ambient context — what the RemoteStore
    client attaches to the X-Volcano-Trace header."""
    return _ctx.trace_id, _ctx.span_id


def format_header(trace_id: str, span_id: str) -> str:
    return f"{trace_id} {span_id}"


def parse_header(value: str) -> Tuple[str, str]:
    parts = (value or "").split()
    if not parts:
        return "", ""
    return parts[0], parts[1] if len(parts) > 1 else ""


class Tracer:
    """The flight recorder: a bounded ring of completed span records."""

    def __init__(self, ring: int = DEFAULT_RING, dump_dir: str = ""):
        self.ring_size = max(int(ring), 1)
        self.dump_dir = dump_dir
        self._mu = make_lock("Tracer._mu")
        self._ring: deque = deque(maxlen=self.ring_size)

    def record(self, rec: Dict[str, Any]) -> None:
        with self._mu:
            self._ring.append(rec)

    def records(self) -> List[Dict[str, Any]]:
        """Snapshot of the ring, oldest first."""
        with self._mu:
            return list(self._ring)

    def dump(self, reason: str = "") -> Dict[str, Any]:
        return {
            "pid": os.getpid(),
            "component": component(),
            "reason": reason,
            "ring": self.ring_size,
            "spans": self.records(),
        }

    def dump_to(self, path: str, reason: str = "",
                extra: Optional[Dict[str, Any]] = None) -> str:
        """Atomic artifact write (temp + rename); ``extra`` merges
        additional top-level keys into the payload (crash_dump attaches
        the time-series ring this way)."""
        payload = self.dump(reason)
        if extra:
            payload.update(extra)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return path


class _NoopSpan:
    """Shared do-nothing span returned while disarmed: entering, exiting,
    annotating and linking are all no-ops, so instrumentation sites never
    branch on armed-ness themselves."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **attrs):
        return self

    def link(self, *trace_ids):
        return self


NOOP = _NoopSpan()


class Span:
    """A live span; records into the tracer ring on ``__exit__``.

    Entering installs (trace_id, span_id) as the ambient context, so
    nested spans become children and outbound RemoteStore requests carry
    this context in their header.  ``trace_id=...`` joins an explicit
    trace (a gang's) instead of the ambient one; ``link(t)`` marks the
    span as participating in another trace without re-rooting it (the
    per-cycle span tree links every traced gang it schedules)."""

    __slots__ = ("_tracer", "name", "trace_id", "span_id", "parent_id",
                 "attrs", "links", "_t0", "_start", "_prev")

    def __init__(self, tracer: Tracer, name: str,
                 trace_id: Optional[str] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        self._tracer = tracer
        self.name = name
        ambient_trace, ambient_span = _ctx.trace_id, _ctx.span_id
        if trace_id:
            self.trace_id = trace_id
            # only a same-trace ambient span can be the parent
            self.parent_id = ambient_span if ambient_trace == trace_id else ""
        elif ambient_trace:
            self.trace_id = ambient_trace
            self.parent_id = ambient_span
        else:
            self.trace_id = new_id("t")
            self.parent_id = ""
        self.span_id = new_id("s")
        self.attrs = dict(attrs) if attrs else {}
        self.links: List[str] = []
        self._prev = (ambient_trace, ambient_span)

    def __enter__(self) -> "Span":
        _ctx.trace_id, _ctx.span_id = self.trace_id, self.span_id
        self._start = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        dur = time.perf_counter() - self._t0
        _ctx.trace_id, _ctx.span_id = self._prev
        if exc and exc[0] is not None:
            self.attrs["error"] = getattr(exc[0], "__name__", str(exc[0]))
        self._tracer.record({
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "component": component(),
            "start": self._start,
            "dur": dur,
            "attrs": self.attrs,
            "links": self.links,
        })
        return False

    def annotate(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def link(self, *trace_ids: str) -> "Span":
        for t in trace_ids:
            if t and t != self.trace_id and t not in self.links:
                self.links.append(t)
        return self


def _tracer_from_env(raw: str) -> Optional[Tracer]:
    raw = (raw or "").strip()
    if not raw or raw in ("0", "off", "none"):
        return None
    if raw.startswith("{"):
        try:
            cfg = json.loads(raw)
        except ValueError:
            cfg = {}
        return Tracer(ring=int(cfg.get("ring", DEFAULT_RING)),
                      dump_dir=str(cfg.get("dir", "")))
    return Tracer()


#: the process tracer; None = disarmed, and every instrumentation site is
#: a single ``trace.TRACER is None`` attribute check (the faultpoint-style
#: guard the chaos layer established)
TRACER: Optional[Tracer] = _tracer_from_env(os.environ.get(ENV_VAR, ""))


def arm(tracer: Optional[Tracer] = None) -> Tracer:
    """Arm tracing in-process (tests, embedders); returns the tracer."""
    global TRACER
    TRACER = tracer or Tracer()
    return TRACER


def disarm() -> None:
    global TRACER
    TRACER = None


def span(name: str, trace_id: Optional[str] = None, **attrs):
    """Open a span: ``with span("scheduler.cycle") as s: ...``.  Disarmed
    this returns the shared no-op and allocates nothing."""
    tr = TRACER
    if tr is None:
        return NOOP
    return Span(tr, name, trace_id, attrs)


@contextmanager
def context(trace_id: str, span_id: str = ""):
    """Install an ambient context without opening a span — the server
    side of header propagation (the request span then parents to the
    client's span across the process boundary)."""
    prev = (_ctx.trace_id, _ctx.span_id)
    _ctx.trace_id, _ctx.span_id = trace_id, span_id
    try:
        yield
    finally:
        _ctx.trace_id, _ctx.span_id = prev


@contextmanager
def request_context(header_value: str, name: str, **attrs):
    """Continue a client's ``X-Volcano-Trace`` context around one server
    request: installs the remote context (when present) and opens the
    request span under it."""
    tid, sid = parse_header(header_value)
    if tid:
        with context(tid, sid):
            with span(name, **attrs) as s:
                yield s
    else:
        with span(name, **attrs) as s:
            yield s


def stamp(meta) -> str:
    """Write the ambient trace id into an object's annotations (the
    ``vtctl job run`` root does this on the Job) so watch-driven daemons
    can join the trace.  Returns the id written ("" when disarmed or no
    ambient trace)."""
    if TRACER is None:
        return ""
    tid = _ctx.trace_id
    if tid:
        meta.annotations[TRACE_ID_KEY] = tid
    return tid


def gang_trace(meta) -> str:
    """The trace id an object carries, "" when untraced."""
    return meta.annotations.get(TRACE_ID_KEY, "")


# -- reconstruction -----------------------------------------------------------


def spans_for_trace(records: List[Dict[str, Any]],
                    trace_id: str) -> List[Dict[str, Any]]:
    """Every span belonging to ``trace_id``: direct members, spans that
    ``link`` it (a scheduler cycle serving many gangs), and the full
    subtree under any selected span (the cycle's actions/plugins keep the
    cycle's own trace id but describe the linked gang's scheduling too).
    Sorted by start time."""
    children: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
    for r in records:
        children.setdefault((r["trace"], r["parent"]), []).append(r)
    selected: Dict[str, Dict[str, Any]] = {}
    frontier = [r for r in records
                if r["trace"] == trace_id or trace_id in r.get("links", ())]
    while frontier:
        nxt: List[Dict[str, Any]] = []
        for r in frontier:
            if r["span"] in selected:
                continue
            selected[r["span"]] = r
            nxt.extend(children.get((r["trace"], r["span"]), ()))
        frontier = nxt
    return sorted(selected.values(), key=lambda r: (r["start"], r["span"]))


def trace_ids(records: List[Dict[str, Any]]) -> List[str]:
    """Distinct trace ids in the ring, oldest root first."""
    seen: List[str] = []
    for r in records:
        if r["trace"] not in seen:
            seen.append(r["trace"])
    return seen


#: span names that are pure cycle machinery: every idle scheduler cycle
#: roots a fresh trace of these (and, on an armed daemon, its contexted
#: store reads land as store.* spans in the same trace), so "the last
#: trace" must look past them
_MACHINERY = frozenset({
    "scheduler.cycle", "scheduler.residue", "session.snapshot",
    "session.close", "action", "plugin", "statement.commit",
    "statement.discard", "device.allocate_solve", "device.dynamic_solve",
})


def _is_machinery(name: str) -> bool:
    return name in _MACHINERY or name.startswith("store.")


def latest_trace(records: List[Dict[str, Any]]) -> str:
    """The most recent trace carrying a non-machinery span (a submitted
    gang, a CLI op) — what ``vtctl trace last`` renders by default.
    Falls back to the newest trace of any kind."""
    best = ""
    for r in records:
        if not _is_machinery(r["name"]):
            best = r["trace"]
    if best:
        return best
    return records[-1]["trace"] if records else ""


def render_tree(records: List[Dict[str, Any]], trace_id: str) -> str:
    """Human span tree for one trace (vtctl trace last)."""
    spans = spans_for_trace(records, trace_id)
    if not spans:
        return f"no spans recorded for trace {trace_id}\n"
    by_id = {r["span"]: r for r in spans}
    kids: Dict[str, List[Dict[str, Any]]] = {}
    roots: List[Dict[str, Any]] = []
    for r in spans:
        if r["parent"] in by_id:
            kids.setdefault(r["parent"], []).append(r)
        else:
            roots.append(r)
    lines = [f"trace {trace_id} ({len(spans)} spans)"]

    def fmt(r, depth):
        attrs = " ".join(f"{k}={v}" for k, v in sorted(r["attrs"].items()))
        linked = " ~linked" if trace_id in r.get("links", ()) else ""
        comp = f"[{r['component']}] " if r.get("component") else ""
        lines.append(
            f"{'  ' * depth}{r['name']} {comp}{r['dur'] * 1e3:.2f}ms"
            f"{linked}{(' ' + attrs) if attrs else ''}"
        )
        for c in kids.get(r["span"], ()):
            fmt(c, depth + 1)

    for r in roots:
        fmt(r, 1)
    return "\n".join(lines) + "\n"


# -- debug endpoint / crash artifacts -----------------------------------------


def debug_payload() -> Dict[str, Any]:
    """The ``/debug/trace`` response body (store server + MetricsServer)."""
    tr = TRACER
    if tr is None:
        return {"armed": False, "pid": os.getpid(), "now": time.time(),
                "spans": []}
    out = tr.dump()
    out["armed"] = True
    # the serving process's wall clock at response build: the vtfleet
    # harvester estimates this proc's clock offset from it (midpoint of
    # the harvest round-trip) to align spans onto one fleet timeline
    out["now"] = time.time()
    return out


def crash_dump(reason: str) -> Optional[str]:
    """Dump the flight recorder as a JSON artifact — called on daemon
    crash, invariant violation, or chaos-soak divergence.  When the
    vtload time-series recorder is armed, its ring rides along under
    ``"timeseries"`` so the artifact carries the last N cycles of
    telemetry next to the spans.  Returns the path written, or None when
    disarmed/empty.  Never raises: forensics must not mask the original
    failure.  When the vtprof profiler is armed, its sentinel trips ride
    under ``"anomalies"`` and its critical-path summary under
    ``"profile"``."""
    from volcano_tpu import timeseries, vtprof

    tr = TRACER
    if tr is None:
        return None
    directory = tr.dump_dir or "."
    name = f"vtrace-{component() or 'proc'}-{os.getpid()}-{reason}.json"
    path = os.path.join(directory, name)
    extra = None
    if timeseries.RECORDER is not None:
        extra = {"timeseries": timeseries.RECORDER.samples()}
    if vtprof.PROFILER is not None:
        extra = dict(extra or {})
        extra["anomalies"] = vtprof.PROFILER.anomalies_snapshot()
        extra["profile"] = vtprof.PROFILER.summary()
    from volcano_tpu import vtaudit

    if vtaudit.has_debug_source():
        # the mirror's digest view + last verify verdict ride along so a
        # steady-state-divergence dump names the mismatched kinds
        extra = dict(extra or {})
        extra["audit"] = vtaudit.debug_payload()
    try:
        os.makedirs(directory, exist_ok=True)
        return tr.dump_to(path, reason, extra=extra)
    except OSError:
        return None
