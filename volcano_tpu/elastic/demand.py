"""Gang-aware demand estimation: Unschedulable PodGroups -> node counts.

The signal is the ``Unschedulable`` PodGroup condition the gang plugin (and
the fastpath mirror's status twin) already publish every cycle a gang
cannot be fully placed — the estimator never second-guesses the scheduler,
it only answers "how many template nodes would let the scheduler place
what it just said it could not".

Three properties drive the design:

* **gang atomicity** — a gang's pending requests are first-fit-decreasing
  bin-packed as a unit; if the pool cannot absorb the WHOLE remainder of a
  gang (template too small, or the pool would exceed ``max_size``), the
  gang contributes nothing — never provision half a gang's worth of nodes
  that can only host a forever-partial placement.
* **deserved-share clipping, loanable when idle (Aryl,
  https://arxiv.org/pdf/2202.07896)** — when the aggregate demand exceeds
  the pool's headroom, each queue's grant is clipped to its weighted share
  of the headroom; while other queues are idle their quota is loaned
  freely (a single demanding queue may take the whole pool).  Reclaim
  remains the enforcement path once a lender wakes up — the estimator
  only shapes GROWTH, it never evicts.
* **determinism** — pools order by (priority desc, name), gangs by
  (priority desc, key), requests by (cpu, memory) descending; two
  reconciles over the same store state produce the same plan.

Pending requests come from the gang's still-pending pods when they exist;
for a gang parked at the enqueue gate (no capacity -> PodGroup never
Inqueue -> the controller never created pods) they are derived from the
owning Job's task templates — the from-zero pool bootstrap.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from volcano_tpu.api.job import POD_GROUP_KEY
from volcano_tpu.api.objects import NodePool
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import PodGroupPhase, PodPhase

from volcano_tpu.elastic.lifecycle import (
    DRAINING,
    PROVISIONING,
    node_state,
    pods_by_node,
    pool_nodes,
    resident_pods,
)


@dataclass
class GangDemand:
    """One Unschedulable gang's outstanding placement need."""

    key: str                 # PodGroup namespace/name
    queue: str
    priority: int
    requests: List[Resource]  # pending per-pod requests, unplaced portion
    selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List = field(default_factory=list)


@dataclass
class PoolPlan:
    """The reconcile decision for one pool."""

    pool: str
    new_nodes: int = 0        # clipped scale-up this reconcile
    demand_nodes: int = 0     # unclipped bin-pack minimum (pending_demand)
    #: gangs this pool can serve at all — nonzero means live demand even
    #: when demand_nodes is 0 (covered by in-flight Provisioning bins), so
    #: the scale-down hysteresis clock must NOT start
    eligible_gangs: int = 0
    admitted: List[str] = field(default_factory=list)  # gang keys served


class _Bin:
    """One node's free capacity during the FFD walk."""

    __slots__ = ("free", "slots")

    def __init__(self, free: Resource, slots: Optional[int]):
        self.free = free
        self.slots = slots

    def fits(self, req: Resource) -> bool:
        if self.slots is not None and self.slots < 1:
            return False
        return req.less_equal(self.free)

    def take(self, req: Resource) -> None:
        self.free.sub(req)
        if self.slots is not None:
            self.slots -= 1


def _req_key(r: Resource) -> Tuple[float, float]:
    return (-r.milli_cpu, -r.memory)


def _template_bin(pool: NodePool) -> _Bin:
    res = pool.resources.clone()
    return _Bin(res, res.max_task_num)


def _ffd(requests: List[Resource], pool: NodePool,
         free_bins: List[_Bin]) -> Optional[Tuple[List[_Bin], int]]:
    """First-fit-decreasing ``requests`` into copies of ``free_bins`` and
    as many fresh template bins as needed.  Returns (bins after packing,
    new-bin count), or None when some request cannot fit even an EMPTY
    template node (the pool can never serve this gang)."""
    bins = [_Bin(b.free.clone(), b.slots) for b in free_bins]
    n_existing = len(bins)
    for req in sorted(requests, key=_req_key):
        placed = False
        for b in bins:
            if b.fits(req):
                b.take(req)
                placed = True
                break
        if not placed:
            fresh = _template_bin(pool)
            if not fresh.fits(req):
                return None  # request larger than the template: unservable
            fresh.take(req)
            bins.append(fresh)
    return bins, len(bins) - n_existing


def gang_fits_pool(gang: GangDemand, pool: NodePool) -> bool:
    """Template-level predicate agreement: the gang's selector must match
    the pool labels (+ the pool membership label) and the pool taints must
    be tolerated — the same node_selector/taints semantics the scheduler's
    predicate chain applies to member nodes."""
    from volcano_tpu.elastic.lifecycle import POOL_LABEL

    labels = dict(pool.labels)
    labels[POOL_LABEL] = pool.meta.name
    labels.setdefault("kubernetes.io/hostname", pool.meta.name)
    for k, v in gang.selector.items():
        if labels.get(k) != v:
            return False
    for taint in pool.taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue
        if not any(t.tolerates(taint) for t in gang.tolerations):
            return False
    return True


def unschedulable_gangs(store) -> List[GangDemand]:
    """Collect demand from every PodGroup carrying a true ``Unschedulable``
    condition, sorted (priority desc, key) for deterministic admission."""
    priority_classes = {
        pc.meta.name: pc.value for pc in store.items("PriorityClass")
    }
    jobs = {j.meta.key: j for j in store.items("Job")}
    pods_by_group: Dict[str, List] = {}
    for p in store.items("Pod"):
        group = p.meta.annotations.get(POD_GROUP_KEY, "")
        if group:
            pods_by_group.setdefault(f"{p.meta.namespace}/{group}", []).append(p)

    out: List[GangDemand] = []
    for pg in store.items("PodGroup"):
        if not any(
            c.kind == "Unschedulable" and c.status == "True"
            for c in pg.status.conditions
        ):
            continue
        requests: List[Resource] = []
        selector: Dict[str, str] = {}
        tolerations: List = []
        placed = 0
        members = sorted(pods_by_group.get(pg.meta.key, ()),
                         key=lambda p: p.meta.uid)
        for p in members:
            if p.deleting:
                continue
            if p.node_name or p.phase != PodPhase.PENDING:
                placed += 1
                continue
            req = p.spec.init_resreq()
            if req.is_empty():
                continue  # best-effort: backfill places it anywhere
            requests.append(req)
            selector = p.spec.node_selector
            tolerations = p.spec.tolerations
        if not members and pg.status.phase == PodGroupPhase.PENDING:
            # parked at the enqueue gate (a from-zero pool: no capacity ->
            # never Inqueue -> the controller never created pods): derive
            # the per-replica requests from the owning Job's task
            # templates.  Gated on phase PENDING so a finished job whose
            # pods were reaped can never resurrect demand.
            job = jobs.get(pg.meta.key)
            if job is not None:
                for task in job.spec.tasks:
                    req = task.template.init_resreq()
                    if req.is_empty():
                        continue
                    requests.extend(req.clone() for _ in range(task.replicas))
                    selector = task.template.node_selector
                    tolerations = task.template.tolerations
        # the gang needs min_member placements; demand only the unplaced
        # remainder (largest-first keeps FFD consistent with the packing)
        needed = max(0, pg.min_member - placed)
        if needed <= 0 or not requests:
            continue
        requests.sort(key=_req_key)
        requests = requests[:needed] if len(requests) > needed else requests
        out.append(GangDemand(
            key=pg.meta.key,
            queue=pg.queue or "default",
            priority=priority_classes.get(pg.priority_class_name, 0),
            requests=requests,
            selector=dict(selector),
            tolerations=list(tolerations),
        ))
    out.sort(key=lambda g: (-g.priority, g.key))
    return out


def free_bins(store, pool: NodePool,
              residents: Optional[dict] = None) -> Tuple[List[_Bin], int]:
    """(free capacity of each schedulable member, TOTAL member count).
    Ready members contribute allocatable minus resident requests;
    Provisioning members contribute their full template (they will be Ready
    before any newly provisioned node); Draining/cordoned members
    contribute no bins but still count toward the size bound — headroom is
    ``max_size - total``, so a pool mid-drain can never overshoot its cap.
    ``residents`` is an optional ``pods_by_node`` index (built once per
    reconcile) replacing the per-node Pod scan."""
    from volcano_tpu.scheduler.model import _sub_clamped

    bins: List[_Bin] = []
    total = 0
    for node in pool_nodes(store, pool.meta.name):
        total += 1
        state = node_state(node)
        if state == DRAINING or node.unschedulable:
            continue
        if state == PROVISIONING:
            bins.append(_template_bin(pool))
            continue
        free = node.allocatable.clone()
        slots = node.allocatable.max_task_num
        for p in resident_pods(store, node.meta.name, residents):
            _sub_clamped(free, p.spec.resreq(), Resource())
            if slots is not None:
                slots -= 1
        bins.append(_Bin(free, slots))
    return bins, total


def _weighted_split(total: int, weights: Dict[str, int]) -> Dict[str, int]:
    """Integer split of ``total`` by weight, largest-remainder rounding,
    name-ordered ties — deterministic."""
    wsum = sum(weights.values()) or 1
    shares = {q: (total * w) / wsum for q, w in weights.items()}
    out = {q: int(s) for q, s in shares.items()}
    leftover = total - sum(out.values())
    for q in sorted(weights, key=lambda q: (-(shares[q] - out[q]), q)):
        if leftover <= 0:
            break
        out[q] += 1
        leftover -= 1
    return out


def plan_pools(store, pools: List[NodePool],
               gangs: Optional[List[GangDemand]] = None,
               residents: Optional[dict] = None) -> Dict[str, PoolPlan]:
    """The whole-cluster scale-up plan: gangs (priority desc) are absorbed
    by the first pool (priority desc) whose template serves them, whole
    gangs at a time, clipped per queue by deserved share under contention
    (see module docstring)."""
    if gangs is None:
        gangs = unschedulable_gangs(store)
    if residents is None:
        residents = pods_by_node(store)
    queues = {q.meta.name: max(1, q.weight) for q in store.items("Queue")}
    plans: Dict[str, PoolPlan] = {}
    remaining = list(gangs)
    for pool in sorted(pools, key=lambda p: (-p.priority, p.meta.name)):
        plan = PoolPlan(pool=pool.meta.name)
        plans[pool.meta.name] = plan
        bins, active = free_bins(store, pool, residents)
        headroom = max(0, pool.max_size - active)

        # unclipped pass: every eligible gang's new-bin need against a
        # private copy of the free bins (pending_demand metric + the
        # contention decision)
        eligible: List[Tuple[GangDemand, int]] = []
        trial_bins = bins
        for gang in remaining:
            if not gang_fits_pool(gang, pool):
                continue
            # unservable AT THE CAP: a gang whose remainder alone needs
            # more template bins than max_size can never run here even
            # with every member node free — it must not count as demand
            # (it would pin the scale-down hysteresis clock forever while
            # idle nodes leak above min_size)
            alone = _ffd(gang.requests, pool, [])
            if alone is None or alone[1] > pool.max_size:
                continue
            packed = _ffd(gang.requests, pool, trial_bins)
            if packed is None:
                continue
            trial_bins, new = packed
            eligible.append((gang, new))
            plan.demand_nodes += new
        plan.eligible_gangs = len(eligible)
        if not eligible:
            continue

        contention = plan.demand_nodes > headroom
        budget: Dict[str, int] = {}
        if contention:
            budget = _weighted_split(
                headroom,
                {g.queue: queues.get(g.queue, 1) for g, _ in eligible},
            )

        # clipped admission, whole gangs only
        used_q: Dict[str, int] = {}
        committed = bins
        total_new = 0
        admitted_keys = set()
        for gang, _unclipped in eligible:
            packed = _ffd(gang.requests, pool, committed)
            if packed is None:
                continue
            new_bins, new = packed
            if total_new + new > headroom:
                continue  # half-gang growth is worse than none
            if contention and used_q.get(gang.queue, 0) + new > budget.get(
                    gang.queue, 0):
                continue  # over deserved share while others contend
            committed = new_bins
            total_new += new
            used_q[gang.queue] = used_q.get(gang.queue, 0) + new
            plan.admitted.append(gang.key)
            admitted_keys.add(gang.key)
        plan.new_nodes = total_new
        remaining = [g for g in remaining if g.key not in admitted_keys]
    return plans
