"""Elastic capacity: demand-driven node-pool autoscaling with a
cordon/drain lifecycle (see elastic/controller.py for the reconcile loop,
elastic/demand.py for the gang-aware estimator, elastic/lifecycle.py for
the node state machine and the vtctl cordon/drain primitives)."""

from volcano_tpu.api.objects import NodePool, NodePoolStatus  # noqa: F401
from volcano_tpu.elastic.controller import ElasticController  # noqa: F401
from volcano_tpu.elastic.demand import (  # noqa: F401
    GangDemand,
    PoolPlan,
    plan_pools,
    unschedulable_gangs,
)
from volcano_tpu.elastic.lifecycle import (  # noqa: F401
    DRAINING,
    POOL_LABEL,
    PROVISIONING,
    READY,
    begin_drain,
    cordon,
    drain,
    kubelet_provisioning_step,
    node_state,
    pods_by_node,
    pool_nodes,
    uncordon,
)

__all__ = [
    "DRAINING",
    "ElasticController",
    "GangDemand",
    "NodePool",
    "NodePoolStatus",
    "POOL_LABEL",
    "PROVISIONING",
    "PoolPlan",
    "READY",
    "begin_drain",
    "cordon",
    "drain",
    "kubelet_provisioning_step",
    "node_state",
    "plan_pools",
    "pods_by_node",
    "pool_nodes",
    "uncordon",
    "unschedulable_gangs",
]
