"""Node lifecycle primitives for elastic capacity: provision, cordon,
drain, delete.

A pool member moves through a four-state machine, every transition a plain
store write so all watchers (scheduler mirror, controller, kubelet) see the
same event stream:

  Provisioning --(kubelet, after provision_delay)--> Ready
  Ready --(scale-down: cordon)--> Draining --(empty)--> deleted

State is carried on the Node itself — the ``volcano.tpu/pool`` label names
the owning pool, the ``volcano.tpu/elastic-state`` annotation holds the
lifecycle state, and ``volcano.tpu/ready-at`` the clock reading at which
the kubelet may flip the Ready condition.  Scheduling exclusion needs NO
scheduler changes: a Provisioning node fails the existing ``Ready``
condition predicate and a Draining node is ``unschedulable`` (cordoned) —
both are masked identically by the host predicate chain
(plugins/predicates.py), the tensor snapshot's static-predicate classes
(snapshot.py ``_static_predicate``), and the fastpath mirror's lazily
recomputed class cells (fastpath.py ``_on_node`` invalidates the node's
``cls_valid`` column on every update).

Draining reuses the existing eviction/Releasing machinery: resident pods
are marked ``deleting`` (the Evictor's write) and the kubelet reaps them —
the same Releasing window pipelined tasks wait on.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from volcano_tpu.api.objects import Metadata, Node, NodeCondition, NodePool
from volcano_tpu.api.types import PodPhase

#: node label naming the owning pool (also usable in selectors/affinity)
POOL_LABEL = "volcano.tpu/pool"
#: annotation carrying the lifecycle state of an elastic node
STATE_ANNOTATION = "volcano.tpu/elastic-state"
#: annotation with the clock reading at which Provisioning flips Ready
READY_AT_ANNOTATION = "volcano.tpu/ready-at"

PROVISIONING = "Provisioning"
READY = "Ready"
DRAINING = "Draining"


def member_name(pool: str, index: int) -> str:
    return f"{pool}-{index}"


def member_index(pool: str, node_name: str) -> Optional[int]:
    """Index of a member node name, or None if not of this pool's form."""
    prefix = f"{pool}-"
    if not node_name.startswith(prefix):
        return None
    tail = node_name[len(prefix):]
    return int(tail) if tail.isdigit() else None


def node_state(node: Node) -> str:
    """Lifecycle state of a node; non-elastic nodes read as Ready."""
    return node.meta.annotations.get(STATE_ANNOTATION, READY)


def make_pool_node(pool: NodePool, index: int, ready_at: float) -> Node:
    """A Provisioning member from the pool's template.  Ready condition
    False keeps it out of every backend's placement mask until the kubelet
    flips it at ``ready_at``."""
    name = member_name(pool.meta.name, index)
    labels = dict(pool.labels)
    labels[POOL_LABEL] = pool.meta.name
    return Node(
        meta=Metadata(
            name=name,
            namespace="",
            annotations={
                STATE_ANNOTATION: PROVISIONING,
                READY_AT_ANNOTATION: repr(float(ready_at)),
            },
            owner=("NodePool", pool.meta.name),
        ),
        allocatable=pool.resources.clone(),
        labels=labels,
        taints=[t for t in pool.taints],
        conditions=[NodeCondition("Ready", "False")],
    )


def pool_nodes(store, pool: str) -> List[Node]:
    """Members of ``pool``, sorted by (member index, name) so scale
    decisions are deterministic."""
    out = [
        n for n in store.items("Node")
        if n.labels.get(POOL_LABEL) == pool
    ]
    out.sort(key=lambda n: (member_index(pool, n.meta.name)
                            if member_index(pool, n.meta.name) is not None
                            else 1 << 30, n.meta.name))
    return out


def pods_by_node(store) -> dict:
    """One pass over Pods -> node name -> resident (Pending/Running,
    non-deleting) pods.  The shared index that keeps a whole reconcile
    O(pods) instead of O(nodes x pods) — build once per pump and pass it
    wherever residency is consulted."""
    out: dict = {}
    for p in store.items("Pod"):
        if p.node_name and not p.deleting and p.phase in (
                PodPhase.PENDING, PodPhase.RUNNING):
            out.setdefault(p.node_name, []).append(p)
    return out


def resident_pods(store, node_name: str, residents: Optional[dict] = None) -> List:
    """Pods occupying the node: bound, not yet reaped, not best-effort
    leftovers — the set a drain must evict before deletion.  Pass a
    ``pods_by_node`` index to avoid the per-call Pod scan."""
    if residents is not None:
        return list(residents.get(node_name, ()))
    return [
        p for p in store.items("Pod")
        if p.node_name == node_name and not p.deleting
        and p.phase in (PodPhase.PENDING, PodPhase.RUNNING)
    ]


def cordon(store, name: str) -> Node:
    """Mark the node unschedulable (kubectl cordon).  Every backend masks
    it from placement on the next cycle; resident pods keep running."""
    node = store.get("Node", f"/{name}")
    if node is None:
        raise KeyError(f"node {name} not found")
    if not node.unschedulable:
        store.patch("Node", f"/{name}", {"unschedulable": True})
    return node


def uncordon(store, name: str) -> Node:
    """Return the node to service.  Clears the Draining lifecycle state
    too (in the SAME write): an operator cancelling an autoscaler drain
    must not leave a node that is schedulable yet still read as DRAINING
    — the controller would keep evicting its pods and delete it the
    moment it is briefly empty."""
    node = store.get("Node", f"/{name}")
    if node is None:
        raise KeyError(f"node {name} not found")
    fields = {}
    if node.unschedulable:
        fields["unschedulable"] = False
    if node.meta.annotations.get(STATE_ANNOTATION) == DRAINING:
        ann = dict(node.meta.annotations)
        ann[STATE_ANNOTATION] = READY
        fields["meta.annotations"] = ann
    if fields:
        store.patch("Node", f"/{name}", fields)
    return node


def begin_drain(store, node: Node) -> None:
    """Atomically cordon AND mark Draining in one store write — a crash
    between two separate writes would leak a permanently cordoned node
    the replacement leader reads as plain Ready (neither drained nor
    schedulable)."""
    ann = dict(node.meta.annotations)
    ann[STATE_ANNOTATION] = DRAINING
    store.patch("Node", f"/{node.meta.name}",
                {"unschedulable": True, "meta.annotations": ann})


def drain(store, name: str) -> Tuple[Node, List[str]]:
    """Cordon + evict resident pods through the existing eviction path
    (``deleting=True``; the kubelet reaps them — the Releasing window).
    Returns the node and the evicted pod keys."""
    node = cordon(store, name)
    evicted = []
    for pod in resident_pods(store, name):
        store.patch("Pod", pod.meta.key, {"deleting": True})
        evicted.append(pod.meta.key)
    return node, evicted


def kubelet_provisioning_step(store, now: float) -> bool:
    """One kubelet pass over Provisioning nodes: flip the Ready condition
    once ``now`` passes the node's ready-at stamp.  Shared by the sim
    kubelet (Cluster.kubelet_step, sim clock) and the kubelet daemon
    (cli/daemons.py, wall clock).  Returns whether anything changed."""
    from volcano_tpu.store.store import Conflict

    changed = False
    for node in store.items("Node"):
        if node.meta.annotations.get(STATE_ANNOTATION) != PROVISIONING:
            continue
        try:
            ready_at = float(node.meta.annotations.get(READY_AT_ANNOTATION, "0"))
        except ValueError:
            ready_at = 0.0
        if now < ready_at:
            continue
        rv = node.meta.resource_version
        node.conditions = [
            NodeCondition("Ready", "True") if c.kind == "Ready" else c
            for c in node.conditions
        ]
        if not any(c.kind == "Ready" for c in node.conditions):
            node.conditions.append(NodeCondition("Ready", "True"))
        node.meta.annotations[STATE_ANNOTATION] = READY
        try:
            # CAS: the elastic controller may cordon/delete this node
            # concurrently (daemon deployments); never resurrect stale state
            store.update_cas("Node", node, rv)
        except (Conflict, KeyError):
            continue  # changed under us; reconcile next period
        changed = True
    return changed
