"""elasticd: the demand-driven node-pool autoscaler.

Watch-driven reconciler in the same mold as the job controller
(controller/controller.py): ``pump()`` drains watch queues for a wake-up
signal, then reconciles every ``NodePool`` against live store state.  Off
by default — a cluster with no NodePool objects never constructs one, and
a pump over zero pools is a single empty list call, so the scheduler's hot
cycle pays nothing (acceptance: bench cfg5 is autoscaler-free).

Per reconcile, for each pool (priority desc):

1. **inventory** — members by the ``volcano.tpu/pool`` label, bucketed by
   lifecycle state (elastic/lifecycle.py).
2. **drain progress** — Draining members whose resident pods are gone are
   deleted (scale_events_total{direction=down}); stragglers get their pods
   re-marked ``deleting`` (idempotent — the eviction/Releasing path).
3. **scale up** — the gang-aware bin-pack plan (elastic/demand.py) says
   how many template nodes the Unschedulable gangs need; each is created
   Provisioning through the ``elastic.provision`` chaos faultpoint
   (fail/delay injectable), named ``<pool>-<lowest free index>`` so two
   runs of the same demand produce the same node names.
4. **floor** — a pool below ``min_size`` grows back to it regardless of
   demand.
5. **scale down** — after ``hysteresis`` seconds of zero demand, the
   emptiest Ready members above ``min_size`` are cordoned and drained;
   surplus still-Provisioning members (demand evaporated mid-provision)
   are deleted outright so no orphan Provisioning node outlives the storm.

The clock is injectable: the simulator passes its step clock (so
provision delays and hysteresis are deterministic in tests), daemons use
wall time.  Leader election gates the whole pump exactly like the job
controller's.
"""

from __future__ import annotations

import time
from typing import Dict, List

from volcano_tpu import events
from volcano_tpu.api.objects import NodePool
from volcano_tpu.elastic import demand as demand_mod
from volcano_tpu.elastic.lifecycle import (
    DRAINING,
    PROVISIONING,
    READY,
    begin_drain,
    make_pool_node,
    member_index,
    node_state,
    pods_by_node,
    pool_nodes,
    resident_pods,
)
from volcano_tpu.scheduler import metrics


class ElasticController:
    def __init__(self, store, elector=None, clock=None, chaos=None):
        self.store = store
        self.elector = elector  # optional LeaderElector (HA analogue)
        self.clock = clock or time.time
        self.chaos = chaos  # optional FaultPlan with elastic.provision rules
        self.events: List[str] = []  # human-readable log, controller-style
        # pool -> clock reading when demand was first observed at zero
        # (hysteresis anchor); reset on any nonzero-demand reconcile
        self._zero_demand_since: Dict[str, float] = {}
        # watch-driven off state: once a reconcile has seen zero pools,
        # later pumps skip even the NodePool list until a watch event
        # arrives (the NodePool watch is the wake-up for pool creation)
        self._synced = False
        self._pools_seen = False
        self._pool_w = store.watch("NodePool")
        self._node_w = store.watch("Node")
        self._pod_w = store.watch("Pod")
        self._pg_w = store.watch("PodGroup")

    # -- pump -----------------------------------------------------------------

    def pump(self) -> bool:
        """Drain watches, reconcile every pool; True if anything changed.
        Quiescent when the cluster matches demand — the simulator's
        run_until_idle contract.  While pools EXIST the reconcile is
        unconditional (hysteresis/provision timers fire without store
        events); while none exist the pump sleeps on the watches."""
        if self.elector is not None and not self.elector.try_acquire():
            return False  # standby replica: events stay queued for takeover
        drained = False
        for q in (self._pool_w, self._node_w, self._pod_w, self._pg_w):
            while q:
                q.popleft()  # wake-up signal only; reconcile lists fresh
                drained = True
        if self._synced and not drained and not self._pools_seen:
            return False  # no pools, no events: the autoscaler is off
        self._synced = True
        pools = self.store.list("NodePool")
        self._pools_seen = bool(pools)
        if not pools:
            return False
        now = self.clock()
        residents = pods_by_node(self.store)
        plans = demand_mod.plan_pools(self.store, pools, residents=residents)
        changed = False
        for pool in sorted(pools, key=lambda p: (-p.priority, p.meta.name)):
            changed |= self._reconcile(pool, plans[pool.meta.name], now,
                                       residents)
        return changed

    # -- reconcile ------------------------------------------------------------

    def _reconcile(self, pool: NodePool, plan, now: float,
                   residents: Dict[str, List]) -> bool:
        name = pool.meta.name
        changed = False
        members = pool_nodes(self.store, name)
        by_state: Dict[str, List] = {PROVISIONING: [], READY: [], DRAINING: []}
        for n in members:
            by_state.setdefault(node_state(n), []).append(n)

        changed |= self._drain_progress(pool, by_state[DRAINING], residents)
        members = pool_nodes(self.store, name)  # drains may have deleted
        size = len(members)

        # scale up: demand plan first, then the min_size floor
        want = plan.new_nodes
        floor = max(0, pool.min_size - size)
        want = max(want, floor)
        want = min(want, pool.max_size - size)
        if want > 0:
            created = self._provision(pool, members, want, now)
            size += created
            changed |= created > 0

        if plan.demand_nodes > 0 or plan.eligible_gangs > 0 or floor > 0:
            # live demand — including demand covered by in-flight
            # Provisioning bins — holds the scale-down hysteresis clock
            self._zero_demand_since.pop(name, None)
        else:
            changed |= self._maybe_scale_down(pool, by_state, size, now,
                                              residents)

        self._publish_status(pool, plan)
        return changed

    def _drain_progress(self, pool: NodePool, draining: List,
                        index: Dict[str, List]) -> bool:
        """Finish drains: delete empty Draining members, re-evict
        stragglers (idempotent)."""
        changed = False
        for node in draining:
            residents = resident_pods(self.store, node.meta.name, index)
            if not residents:
                # the index is pump-start state; re-check fresh before the
                # irreversible delete (deletions are rare, the scan is not)
                if resident_pods(self.store, node.meta.name):
                    continue
                if self.store.delete("Node", f"/{node.meta.name}") is not None:
                    metrics.register_scale_event(pool.meta.name, "down")
                    pool.status.scale_downs += 1
                    self.events.append(
                        f"ScaleDown {pool.meta.name} -{node.meta.name}")
                    events.record(
                        self.store, "NodePool", f"/{pool.meta.name}",
                        "ScaleDown", f"removed drained node {node.meta.name}",
                    )
                    changed = True
                continue
            for pod in residents:
                if not pod.deleting:
                    self.store.patch("Pod", pod.meta.key, {"deleting": True})
                    metrics.register_drain_eviction(pool.meta.name)
                    changed = True
        return changed

    def _provision(self, pool: NodePool, members: List, count: int,
                   now: float) -> int:
        """Create ``count`` Provisioning members on the lowest free
        indices.  The ``elastic.provision`` faultpoint can fail (skip —
        demand persists, the next pump retries) or delay (push ready-at)
        each attempt."""
        taken = {
            member_index(pool.meta.name, n.meta.name) for n in members
        }
        created = 0
        index = 0
        while created < count:
            while index in taken:
                index += 1
            taken.add(index)
            ready_at = now + pool.provision_delay
            if self.chaos is not None:
                rule = self.chaos.fire(
                    "elastic.provision", path=f"{pool.meta.name}-{index}")
                if rule is not None and rule.action == "fail":
                    self.events.append(
                        f"ProvisionFailed {pool.meta.name}-{index} (injected)")
                    events.record(
                        self.store, "NodePool", f"/{pool.meta.name}",
                        "ProvisionFailed",
                        f"provisioning {pool.meta.name}-{index} failed",
                        type=events.WARNING,
                    )
                    # a failure aborts the REST of this pump's batch, not
                    # just the attempt: provisioning stays strictly
                    # index-ordered (never create <pool>-1 while <pool>-0's
                    # creation is outstanding), which is what keeps faulted
                    # and fault-free runs placement-identical — member
                    # creation order is snapshot iteration order.  The
                    # index frees for the retry; demand persists, so the
                    # next pump re-plans and re-attempts from index 0.
                    taken.discard(index)
                    return created
                if rule is not None and rule.action == "delay":
                    ready_at += rule.arg
            node = make_pool_node(pool, index, ready_at)
            try:
                self.store.create("Node", node)
            except KeyError:
                continue  # name collision (non-member squatter): retry later
            created += 1
            metrics.register_scale_event(pool.meta.name, "up")
            pool.status.scale_ups += 1
            self.events.append(f"ScaleUp {pool.meta.name} +{node.meta.name}")
            events.record(
                self.store, "NodePool", f"/{pool.meta.name}", "ScaleUp",
                f"provisioning node {node.meta.name}",
            )
        return created

    def _maybe_scale_down(self, pool: NodePool, by_state: Dict[str, List],
                          size: int, now: float,
                          residents_index: Dict[str, List]) -> bool:
        """Zero demand: after the hysteresis window, drain the emptiest
        Ready members down to min_size; surplus Provisioning members are
        deleted outright — they hold no pods, and leaving them would
        orphan capacity nobody asked for.  Only EMPTY nodes are eligible:
        evicting a resident gang member would break all-or-nothing
        placement, and reclaim — not the autoscaler — is the enforcement
        path for occupied capacity.  (The drain machinery still evicts
        the rare pod that binds into the cordon window — see
        ``_drain_progress``.)"""
        name = pool.meta.name
        since = self._zero_demand_since.setdefault(name, now)
        if now - since < pool.hysteresis:
            return False
        excess = size - pool.min_size
        if excess <= 0:
            return False
        changed = False
        # surplus Provisioning nodes first: empty by construction — but
        # re-check LIVE state before each delete: in daemon deployments
        # the kubelet may have CAS-flipped the node Ready (and the
        # scheduler bound onto it) since this pump's node list
        for node in reversed(by_state[PROVISIONING]):
            if excess <= 0:
                break
            live = self.store.get("Node", f"/{node.meta.name}")
            if live is None or node_state(live) != PROVISIONING:
                continue
            if resident_pods(self.store, node.meta.name):
                continue
            if self.store.delete("Node", f"/{node.meta.name}") is not None:
                metrics.register_scale_event(name, "down")
                pool.status.scale_downs += 1
                self.events.append(f"ScaleDown {name} -{node.meta.name}")
                excess -= 1
                changed = True
        ready = [
            n for n in by_state[READY]
            if not n.unschedulable
            and not resident_pods(self.store, n.meta.name, residents_index)
        ]
        # highest member index first: the pool shrinks from the top, so
        # the surviving floor keeps the low, stable names
        ready.sort(key=lambda n: -(member_index(name, n.meta.name) or 0))
        for node in ready[:max(0, excess)]:
            # cordon + Draining in ONE write (begin_drain): a crash
            # between separate writes would leak a cordoned-but-not-
            # Draining node no later reconcile would ever finish off.
            # Selected nodes are empty; any pod that binds into the
            # cordon window is evicted by _drain_progress next pump.
            begin_drain(self.store, node)
            self.events.append(f"Drain {name} {node.meta.name}")
            events.record(
                self.store, "NodePool", f"/{name}", "Drain",
                f"cordoned and draining {node.meta.name}",
            )
            changed = True
        return changed

    def _publish_status(self, pool: NodePool, plan) -> None:
        members = pool_nodes(self.store, pool.meta.name)
        st = pool.status
        st.size = len(members)
        st.ready = sum(1 for n in members if node_state(n) == READY)
        st.provisioning = sum(
            1 for n in members if node_state(n) == PROVISIONING)
        st.draining = sum(1 for n in members if node_state(n) == DRAINING)
        st.pending_demand = plan.demand_nodes
        metrics.update_pool_size(pool.meta.name, st.size)
        metrics.update_pending_demand(pool.meta.name, plan.demand_nodes)
        try:
            # PATCH status only — a full-object update would clobber any
            # spec edit (max_size bump, hysteresis change) an operator
            # committed while this pump was reconciling from its
            # pump-start snapshot.  No-op patches are suppressed by the
            # store's shadow compare, so a quiescent pool writes nothing.
            self.store.patch("NodePool", pool.meta.key, {"status": st})
        except KeyError:
            pass  # pool deleted mid-pump; nothing to report against
