"""vtload time series: a bounded per-cycle flight recorder.

The vtrace flight recorder (volcano_tpu/trace.py) answers "what happened
inside one trace"; this module answers "what has the control plane been
doing, cycle over cycle" — the time-series half of the vtload
observability layer.  Each armed process keeps a bounded ring of samples:

* ``kind="cycle"`` — recorded by the scheduler after every completed
  cycle: wall duration, fast-path phase breakdown (the bench.py phase
  keys), backlog depth (pending tasks entering the solve), binds and
  evictions published, async-applier drain lag (queued decisions).
* ``kind="store"`` — recorded by the StoreServer at every state flush:
  event-log seq, buffered rows, WAL stats (records/fsyncs/fsync seconds)
  when the durable tier is armed.
* ``kind="anomaly"`` — recorded by the vtprof sentinels (vtprof.py) when
  both layers are armed: ``anomaly`` carries the trip class
  (``steady-state-recompile``, ``device-bytes-leak``) plus the trip's
  detail fields; ``vtctl top`` renders these as its anomaly line.

Arming follows the chaos/trace discipline: **disarmed is the default and
costs one module attribute check per site** (``RECORDER is None``);
``VOLCANO_TPU_TIMESERIES=1`` (or ``{"ring": N}``) arms at boot, tests arm
in-process via :func:`arm`.  The ring is served live at
``/debug/timeseries`` on the Store and Metrics servers (chaos-exempt,
like ``/debug/trace``), rendered by ``vtctl top``, and folded into
``trace.crash_dump()`` artifacts so a crash ships its last N cycles of
telemetry alongside its spans.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

ENV_VAR = "VOLCANO_TPU_TIMESERIES"
DEFAULT_RING = 2048


class Recorder:
    """Bounded ring of per-cycle / per-flush samples."""

    def __init__(self, ring: int = DEFAULT_RING):
        self.ring_size = max(int(ring), 1)
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=self.ring_size)
        self._seq = 0

    def record(self, kind: str, **fields: Any) -> None:
        with self._mu:
            self._seq += 1
            self._ring.append(
                {"seq": self._seq, "kind": kind, "ts": time.time(), **fields}
            )

    def samples(self) -> List[Dict[str, Any]]:
        """Snapshot of the ring, oldest first."""
        with self._mu:
            return list(self._ring)

    def payload(self) -> Dict[str, Any]:
        return {
            "armed": True,
            "pid": os.getpid(),
            # serving-side clock stamp for vtfleet's offset estimate
            "now": time.time(),
            "ring": self.ring_size,
            "samples": self.samples(),
        }


def _recorder_from_env(raw: str) -> Optional[Recorder]:
    raw = (raw or "").strip()
    if not raw or raw in ("0", "off", "none"):
        return None
    if raw.startswith("{"):
        try:
            cfg = json.loads(raw)
        except ValueError:
            cfg = {}
        return Recorder(ring=int(cfg.get("ring", DEFAULT_RING)))
    return Recorder()


#: the process recorder; None = disarmed, and every instrumentation site
#: is a single ``timeseries.RECORDER is None`` attribute check (the
#: faultpoint-style guard chaos/trace established)
RECORDER: Optional[Recorder] = _recorder_from_env(os.environ.get(ENV_VAR, ""))


def arm(recorder: Optional[Recorder] = None) -> Recorder:
    """Arm recording in-process (tests, embedders); returns the recorder."""
    global RECORDER
    RECORDER = recorder or Recorder()
    return RECORDER


def disarm() -> None:
    global RECORDER
    RECORDER = None


def record(kind: str, **fields: Any) -> None:
    """Record one sample when armed; free no-op otherwise."""
    rec = RECORDER
    if rec is not None:
        rec.record(kind, **fields)


def samples() -> List[Dict[str, Any]]:
    rec = RECORDER
    return rec.samples() if rec is not None else []


def debug_payload() -> Dict[str, Any]:
    """The ``/debug/timeseries`` response body (store + metrics servers)."""
    rec = RECORDER
    if rec is None:
        return {"armed": False, "pid": os.getpid(), "now": time.time(),
                "samples": []}
    return rec.payload()
