"""late-binding: chaos plans, membership, and config are read per-call.

The PR-15 Replicator bug, made a permanent invariant: a component must
not capture another component's *late-bound* state — the chaos plan, the
replication membership, a leader URL, live config — into its own
attributes or into closure defaults at construction time.  Construction
happens once; the captured snapshot then silently diverges from the live
value (chaos plans are swapped per test phase, membership changes on
failover), and the component keeps acting on the world as it was.

What fires (construction scope = ``__init__``-family methods and class
bodies):

* ``self.x = <expr>`` where ``<expr>`` reads ``<something>.<late-attr>``
  through another object (``srv.chaos``, ``self.srv.peers``) — the
  attribute freeze;
* a nested ``def``/``lambda`` whose *default value* reads a late-bound
  attribute (``def loop(plan=srv.chaos)``) — the closure-default freeze,
  evaluated exactly once at definition time.

What deliberately does NOT fire (the fix shapes):

* storing the owning object itself (``self.srv = srv``) and reading
  ``self.srv.chaos`` per call in method/closure *bodies* — nested-def
  bodies run later, so reads there are late by construction;
* a component constructing/owning its own plan (``self.chaos =
  env_plan()``) — calls are ownership, not capture;
* reading a *bare* ``self`` attribute (``self.role``) while
  initializing — own state, not another component's.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from volcano_tpu.analysis.core import (
    FileContext,
    Finding,
    dotted_name,
    rule,
)

#: attributes whose value is late-bound by contract: reading them through
#: another object at construction time freezes a snapshot
LATE_ATTRS = {
    "chaos",        # chaos plan — swapped per test phase (env_plan)
    "peers",        # replication membership — changes on failover
    "members",      # ditto, scheduler-side naming
    "leader_url",   # follower redirect target — changes on promotion
    "config",       # live config objects
    "cfg",
}

_INIT_METHODS = {
    "__init__", "__setstate__", "__getstate__", "__new__", "__post_init__",
}


def _late_reads(expr: ast.AST) -> Iterable[ast.Attribute]:
    """Attribute reads ``<base>.<late>`` where base is not bare ``self``
    (``srv.chaos`` and ``self.srv.chaos`` both qualify; ``self.chaos``
    does not), skipping nested-def bodies (those reads run per call)."""
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue  # body runs later — late by construction
        if isinstance(node, ast.Attribute) and node.attr in LATE_ATTRS:
            base = node.value
            if not (isinstance(base, ast.Name) and base.id == "self"):
                yield node
        stack.extend(ast.iter_child_nodes(node))


def _default_exprs(fn: ast.AST) -> Iterable[ast.AST]:
    args = fn.args
    for d in list(args.defaults) + [d for d in args.kw_defaults if d]:
        yield d


@rule(
    "late-binding",
    "late-bound state (chaos plan / membership / config) captured "
    "through another object into an attribute or closure default at "
    "construction time — the snapshot silently diverges from the live "
    "value (the PR-15 Replicator `srv.chaos` bug class); store the owning "
    "object and read the attribute per call instead",
)
def check_late_binding(ctx: FileContext) -> Iterable[Finding]:
    findings: List[Finding] = []

    def scan_construction_stmts(body: Iterable[ast.stmt], where: str) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a def nested in construction scope: its BODY is exempt
                # (runs per call), but its default values are evaluated
                # right now — a default freeze is still a freeze
                for d in _default_exprs(stmt):
                    for read in _late_reads(d):
                        findings.append(ctx.finding(
                            "late-binding", read,
                            f"default value of `{stmt.name}` captures "
                            f"`{dotted_name(read) or read.attr}` at "
                            f"{where} — defaults evaluate once, freezing "
                            "the live value; read it inside the body "
                            "instead",
                        ))
                continue
            if isinstance(stmt, ast.ClassDef):
                scan_construction_stmts(stmt.body, where)
                continue
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                value = stmt.value
                attr_target = any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in targets
                )
                if value is None or not attr_target:
                    continue
                for read in _late_reads(value):
                    findings.append(ctx.finding(
                        "late-binding", read,
                        f"`{dotted_name(read) or read.attr}` captured "
                        f"into an attribute at {where} — the snapshot "
                        "diverges from the live value when the plan/"
                        "membership changes; store the owning object and "
                        "read per call",
                    ))
                continue
            # compound statements: construction scope extends into their
            # bodies (a capture under an `if` in __init__ is still a
            # capture)
            for attr in ("body", "orelse", "finalbody"):
                sub_body = getattr(stmt, attr, None)
                if sub_body:
                    scan_construction_stmts(sub_body, where)
            for h in getattr(stmt, "handlers", None) or []:
                scan_construction_stmts(h.body, where)
            # lambda defaults hiding in the statement's own expressions
            for sub in ast.iter_child_nodes(stmt):
                if isinstance(sub, ast.stmt):
                    continue  # handled by the recursion above
                for leaf in ast.walk(sub):
                    if isinstance(leaf, ast.Lambda):
                        for d in _default_exprs(leaf):
                            for read in _late_reads(d):
                                findings.append(ctx.finding(
                                    "late-binding", read,
                                    f"lambda default captures "
                                    f"`{dotted_name(read) or read.attr}` "
                                    f"at {where}; read it in the body "
                                    "instead",
                                ))

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and item.name in _INIT_METHODS:
                    scan_construction_stmts(
                        item.body,
                        f"construction time (`{node.name}.{item.name}`)",
                    )
    return findings
