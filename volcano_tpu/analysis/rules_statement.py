"""Statement safety: every `Statement` commits or discards on every path.

`Statement` (scheduler/statement.py, reference statement.go:26-222) makes
gang preemption atomic: `evict`/`pipeline` mutate session state eagerly
and append to an op log; `commit` replays the evictions into the cache,
`discard` rolls everything back in reverse.  A path that drops a Statement
without either leaves the SESSION mutated but the CACHE untouched — ghost
evictions that the next snapshot silently resurrects, the exact bug class
all-or-nothing preemption exists to prevent.

The rule runs a may-leak dataflow over each function that constructs a
`Statement(...)`: at every exit of the construction's scope (function end,
`return`, and the end of each iteration of the loop body that created it —
including `continue`/`break` out of it), the statement must be CLOSED
(committed or discarded) on every path.  Passing the statement to a helper
does not close it; returning/storing it transfers ownership and ends
tracking.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from volcano_tpu.analysis.core import FileContext, Finding, rule, walk_functions

OPEN, CLOSED, ESCAPED = "open", "closed", "escaped"

_CLOSERS = {"commit", "discard"}


def _is_statement_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else None
    )
    return name == "Statement"


class _Outcomes:
    """States flowing out of a statement list along each exit kind."""

    def __init__(self):
        self.fall: Optional[Dict[str, str]] = None
        self.breaks: List[Dict[str, str]] = []
        self.continues: List[Dict[str, str]] = []
        self.returns: List[Tuple[Dict[str, str], int]] = []


def _join(a: Optional[Dict[str, str]], b: Optional[Dict[str, str]]):
    if a is None:
        return dict(b) if b is not None else None
    if b is None:
        return dict(a)
    out = dict(a)
    for k, v in b.items():
        prev = out.get(k)
        if prev is None:
            out[k] = v
        elif prev != v:
            # may-open joins win over closed; escaped wins over everything
            if ESCAPED in (prev, v):
                out[k] = ESCAPED
            else:
                out[k] = OPEN
    return out


class _Analyzer:
    def __init__(self, ctx: FileContext, fn: ast.AST):
        self.ctx = ctx
        self.fn = fn
        self.findings: List[Finding] = []

    # -- expression effects ---------------------------------------------------

    def _apply_expr(self, expr: ast.AST, state: Dict[str, str]) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in _CLOSERS \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in state:
                    state[f.value.id] = CLOSED

    def _escapes(self, value: ast.AST, state: Dict[str, str]) -> None:
        """A tracked name used as a whole value (returned, stored, yielded)
        transfers ownership — stop tracking it."""
        if isinstance(value, ast.Name) and value.id in state:
            state[value.id] = ESCAPED

    # -- statement walk -------------------------------------------------------

    def run(self) -> List[Finding]:
        out = self._eval(self.fn.body, {})
        for st in [out.fall] + [s for s, _ in out.returns]:
            if st:
                self._check_all_closed(st, self.fn.lineno,
                                       "function exit")
        # breaks/continues at function top level are syntax errors; ignore
        return self.findings

    def _check_all_closed(self, state: Dict[str, str], line: int, where: str):
        for var, st in state.items():
            if st == OPEN:
                self.findings.append(self.ctx.finding(
                    "statement-discipline",
                    line,
                    f"Statement {var!r} may reach {where} neither "
                    "committed nor discarded — session state would stay "
                    "mutated with no cache side effects (ghost evictions)",
                ))
                state[var] = ESCAPED  # report once

    def _eval(self, stmts: List[ast.stmt], state: Dict[str, str]) -> _Outcomes:
        out = _Outcomes()
        cur: Optional[Dict[str, str]] = dict(state)
        for stmt in stmts:
            if cur is None:
                break  # unreachable
            cur = self._eval_stmt(stmt, cur, out)
        out.fall = cur
        return out

    def _eval_stmt(self, stmt: ast.stmt, state: Dict[str, str],
                   out: _Outcomes) -> Optional[Dict[str, str]]:
        if isinstance(stmt, ast.Assign):
            self._apply_expr(stmt.value, state)
            if _is_statement_ctor(stmt.value) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                var = stmt.targets[0].id
                if state.get(var) == OPEN:
                    self.findings.append(self.ctx.finding(
                        "statement-discipline",
                        stmt,
                        f"Statement {var!r} reassigned while a previous "
                        "instance may be neither committed nor discarded",
                    ))
                state[var] = OPEN
            else:
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and t.id in state:
                        del state[t.id]
                self._escapes(stmt.value, state)
            return state
        if isinstance(stmt, ast.Expr):
            self._apply_expr(stmt.value, state)
            return state
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._apply_expr(stmt.value, state)
                self._escapes(stmt.value, state)
            out.returns.append((dict(state), stmt.lineno))
            return None
        if isinstance(stmt, ast.Break):
            out.breaks.append(dict(state))
            return None
        if isinstance(stmt, ast.Continue):
            out.continues.append(dict(state))
            return None
        if isinstance(stmt, ast.Raise):
            return None  # abort paths are not required to close
        if isinstance(stmt, ast.If):
            self._apply_expr(stmt.test, state)
            then = self._eval(stmt.body, state)
            els = self._eval(stmt.orelse, state)
            self._merge_inner(out, then)
            self._merge_inner(out, els)
            return _join(then.fall, els.fall)
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._eval_loop(stmt, state, out)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._apply_expr(item.context_expr, state)
            inner = self._eval(stmt.body, state)
            self._merge_inner(out, inner)
            return inner.fall
        if isinstance(stmt, ast.Try):
            body = self._eval(stmt.body, state)
            self._merge_inner(out, body)
            merged = _join(body.fall, dict(state))
            for handler in stmt.handlers:
                h = self._eval(handler.body, merged or state)
                self._merge_inner(out, h)
                merged = _join(merged, h.fall)
            if stmt.orelse:
                o = self._eval(stmt.orelse, merged or state)
                self._merge_inner(out, o)
                merged = _join(merged, o.fall)
            if stmt.finalbody:
                f = self._eval(stmt.finalbody, merged or state)
                self._merge_inner(out, f)
                merged = f.fall
            return merged
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return state  # nested scopes analyzed separately
        # default: scan every contained expression for closer calls
        self._apply_expr(stmt, state)
        return state

    def _merge_inner(self, outer: _Outcomes, inner: _Outcomes):
        outer.breaks.extend(inner.breaks)
        outer.continues.extend(inner.continues)
        outer.returns.extend(inner.returns)

    def _eval_loop(self, stmt, state: Dict[str, str],
                   out: _Outcomes) -> Optional[Dict[str, str]]:
        if isinstance(stmt, ast.While):
            self._apply_expr(stmt.test, state)
            always_true = isinstance(stmt.test, ast.Constant) and bool(stmt.test.value)
        else:
            self._apply_expr(stmt.iter, state)
            always_true = False
        pre_vars = set(state)
        inner = self._eval(stmt.body, state)
        # returns propagate out of the loop
        out.returns.extend(inner.returns)
        # end-of-iteration check: statements created INSIDE the loop body
        # must be closed when the iteration ends (fallthrough or continue) —
        # the next iteration would overwrite them
        for st in ([inner.fall] if inner.fall is not None else []) + inner.continues:
            created = {k: v for k, v in st.items() if k not in pre_vars}
            if created:
                self._check_all_closed(created, stmt.lineno,
                                       f"the end of the loop iteration "
                                       f"(loop at line {stmt.lineno})")
        # loop exit state: breaks + (cond-false entry unless while True) +
        # post-iteration fallthrough (vars created inside escape-checked
        # already; keep them as escaped/closed)
        exit_state: Optional[Dict[str, str]] = None
        for st in inner.breaks:
            exit_state = _join(exit_state, st)
        if not always_true:
            exit_state = _join(exit_state, {k: v for k, v in state.items()})
        if inner.fall is not None or inner.continues:
            carried = None
            for st in ([inner.fall] if inner.fall is not None else []) + inner.continues:
                kept = {k: (v if k in pre_vars else
                            (ESCAPED if v == OPEN else v)) for k, v in st.items()}
                carried = _join(carried, kept)
            exit_state = _join(exit_state, carried)
        if exit_state is None and (inner.fall is not None or not always_true):
            exit_state = dict(state)
        if stmt.orelse and exit_state is not None:
            o = self._eval(stmt.orelse, exit_state)
            self._merge_inner(out, o)
            exit_state = o.fall
        return exit_state


@rule(
    "statement-discipline",
    "a Statement must be committed or discarded on every control-flow "
    "path — dropping one leaves ghost session mutations",
)
def check_statement_discipline(ctx: FileContext) -> Iterable[Finding]:
    if "Statement" not in ctx.source:
        return
    for fn in walk_functions(ctx.tree):
        creates = any(
            _is_statement_ctor(node)
            for node in ast.walk(fn)
            if isinstance(node, ast.Call)
        )
        if not creates:
            continue
        yield from _Analyzer(ctx, fn).run()
