"""digest-maintenance: every object mutation must keep the state digest.

vtaudit (volcano_tpu/vtaudit.py) maintains an incremental, order-
independent digest of the store's audited objects — updated O(1) under
the same ``_mu`` hold as the mutation itself (``dg.set_obj`` /
``dg.apply_fields`` / ``dg.remove``).  The whole divergence-detection
story (mirror verify, /debug/digest, beacons, ``vtctl audit``) rests on
ONE invariant: no object-mutating path in the store may skip the digest
update, or the maintained rollup silently drifts from reality and the
auditor cries wolf on a healthy store.

This rule fences that invariant in the store module set
(``store/store.py``, ``store/partition.py``): inside any function that
mutates a digested container — a subscript assignment, ``del``,
``.pop``/``.clear``/``.update``/``.setdefault`` on ``self._objects`` or
``self._lazy_patch`` (directly or through a local alias), or an
in-place ``setattr`` on a live object — the function must also touch
``_digest`` (the maintenance hook lives in the same verb, same lock
hold).  Exemptions are structural, not suppressions:

* ``_materialize*``/``materialize*`` methods — materialization folds
  exactly the values the staging path ALREADY digested
  (``_stage_lazy_rows``), so it is digest-neutral by design;
* ``self._lazy_create`` — staged Event blocks; Events are outside
  ``vtaudit.AUDITED_KINDS`` (unbounded append-only log records).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Optional, Set

from volcano_tpu.analysis.core import (
    FileContext,
    Finding,
    dotted_name,
    rule,
)

_SCOPED_SUFFIXES = (
    "store/store.py",
    "store/partition.py",
)

#: the digested containers (``self.<name>``); ``_lazy_create`` is
#: deliberately absent — Events are unaudited
_CONTAINERS = {"_objects", "_lazy_patch"}

_MUTATOR_METHODS = {"pop", "clear", "update", "setdefault", "popitem"}


def _is_exempt(fn: ast.AST) -> bool:
    """Materialization is digest-neutral by design (see module doc)."""
    return getattr(fn, "name", "").lstrip("_").startswith("materialize")


def _touches_digest(fn: ast.AST) -> bool:
    """True when the function references ``_digest`` — as an attribute
    (``self._digest``) or a key (``self.__dict__["_digest"]``)."""
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Attribute) and sub.attr == "_digest":
            return True
        if isinstance(sub, ast.Constant) and sub.value == "_digest":
            return True
    return False


def _container_root(expr: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """The digested container an expression drills into, or None.  Peels
    subscripts and ``.get(...)`` reads, so ``self._objects[kind]``,
    ``self._lazy_patch.get(kind)`` and aliases thereof all resolve."""
    cur = expr
    while True:
        if isinstance(cur, ast.Subscript):
            cur = cur.value
            continue
        if (
            isinstance(cur, ast.Call)
            and isinstance(cur.func, ast.Attribute)
            and cur.func.attr == "get"
        ):
            cur = cur.func.value
            continue
        break
    name = dotted_name(cur)
    if name is None:
        return None
    tail = name.split(".")[-1]
    if tail in _CONTAINERS:
        return tail
    return aliases.get(name)


def _collect_aliases(fn: ast.AST) -> Dict[str, str]:
    """Local names bound from a digested container (``pods =
    self._objects["Pod"]``, ``lp = self._lazy_patch.get(kind)``) —
    transitively, in source order (good enough for the straight-line
    binds the store uses)."""
    aliases: Dict[str, str] = {}
    for sub in ast.walk(fn):
        if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1):
            continue
        tgt = sub.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        root = _container_root(sub.value, aliases)
        if root is not None:
            aliases[tgt.id] = root
    return aliases


def _own_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Every node of ``fn`` except those inside nested function defs —
    a nested def is its own audit scope."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


@rule(
    "digest-maintenance",
    "object mutation in the store module set (store/store.py, "
    "store/partition.py) inside a function that never touches `_digest` "
    "— the incremental state digest (volcano_tpu/vtaudit.py) silently "
    "drifts from reality and `vtctl audit` flags a healthy store; update "
    "the digest under the same lock hold (set_obj/apply_fields/remove), "
    "or suppress with the digest-neutrality argument on the line",
)
def check_digest_maintenance(ctx: FileContext) -> Iterable[Finding]:
    if not any(ctx.relpath.endswith(s) for s in _SCOPED_SUFFIXES):
        return
    funcs = [
        fn for fn in ast.walk(ctx.tree)
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for fn in funcs:
        if _is_exempt(fn) or _touches_digest(fn):
            continue
        aliases = _collect_aliases(fn)
        seen: Set[int] = set()

        def hit(node: ast.AST, what: str):
            if id(node) in seen:
                return None
            seen.add(id(node))
            return ctx.finding(
                "digest-maintenance",
                node,
                f"{what} in `{fn.name}` without a `_digest` update — "
                "the maintained state digest drifts from the stored "
                "objects (vtaudit divergence on a healthy store); "
                "route the mutation through the digest helper under "
                "the same lock hold",
            )

        for node in _own_nodes(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in targets:
                    if isinstance(tgt, ast.Subscript):
                        root = _container_root(tgt.value, aliases)
                        if root is not None:
                            f = hit(node, f"subscript write into `{root}`")
                            if f:
                                yield f
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript):
                        root = _container_root(tgt.value, aliases)
                        if root is not None:
                            f = hit(node, f"`del` from `{root}`")
                            if f:
                                yield f
            elif isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                if fname == "setattr":
                    f = hit(node, "in-place `setattr` on a live object")
                    if f:
                        yield f
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATOR_METHODS
                ):
                    root = _container_root(node.func.value, aliases)
                    if root is not None:
                        f = hit(
                            node,
                            f"`.{node.func.attr}()` on `{root}`",
                        )
                        if f:
                            yield f
