"""Hot-path purity + jit-boundary hygiene rules.

The sub-second 100k x 10k cycle exists because the kernel-twin modules
(`fastpath.py`, `kernels.py`, `victim_kernels.py`, `fast_victims.py`,
`tensor_actions.py`) never run O(tasks x nodes) Python and never sync the
device mid-solve (ANALYSIS.md; BASELINE.md config 5).  These rules make
that reviewers'-heads discipline machine-checked:

* ``hotpath-python-loop`` — nested Python loops where both levels iterate
  hot collections (tasks/nodes/pods/jobs/victims): the O(T x N) signature
  the array mirror exists to avoid (PARITY.md "Scheduler cache" row).
* ``hotpath-host-sync`` — ``.item()`` anywhere in a kernel twin, and
  ``.item()``/``device_get``/``np.asarray``/``float(name)`` inside a jit
  body: each is a device->host sync that serializes the solve against the
  tunnel's ~0.1 s RTT floor (BASELINE.md cfg4 methodology note).
* ``hotpath-wallclock`` — ``time.time()``/``time.monotonic()``/
  ``datetime.now()``/stdlib ``random`` in a kernel twin module
  (``time.perf_counter`` is allowed outside jit: phase timing).  Inside a
  jit body ANY ``time.*`` call is flagged — it would burn the trace-time
  clock into the compiled program.
* ``jit-state-mutation`` — ``global``/``nonlocal`` declarations or
  mutation of captured (closure/module) state inside a jit-traced body:
  the mutation runs once at trace time, not per execution.
* ``jit-unkeyed-random`` — host randomness (``random.*``/``np.random.*``)
  or a constant-seeded ``jax.random.PRNGKey`` inside a jit body: the
  "random" draw is frozen into the compiled artifact.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from volcano_tpu.analysis.core import (
    FileContext,
    Finding,
    dotted_name,
    jit_roots,
    ctx_nodes_in_jit,
    rule,
)

#: the kernel-twin modules: host mirrors of device programs, where Python
#: cost is the product the paper optimizes away
KERNEL_TWIN_BASENAMES = {
    # the fastpath package (PR 11 split of the old fastpath.py monolith;
    # the old basename stays for the rule's own test fixtures)
    "fastpath.py",
    "mirror.py",
    "snapshot_build.py",
    "cycle.py",
    "publish.py",
    "kernels.py",
    "victim_kernels.py",
    "fast_victims.py",
    "tensor_actions.py",
}

_HOT_TOKENS = ("task", "node", "pod", "job", "victim", "preemptor")


def _is_kernel_twin(ctx: FileContext) -> bool:
    return ctx.basename in KERNEL_TWIN_BASENAMES


def _mentions_hot_collection(expr: ast.AST) -> bool:
    for sub in ast.walk(expr):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name is None:
            continue
        low = name.lower()
        if any(tok in low for tok in _HOT_TOKENS):
            return True
    return False


def _target_names(target: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for sub in ast.walk(target):
        if isinstance(sub, ast.Name):
            names.add(sub.id)
    return names


@rule(
    "hotpath-python-loop",
    "nested Python loops over hot collections (tasks x nodes) in a "
    "kernel-twin module — the O(T x N) interpreter cost the array mirror "
    "exists to eliminate",
)
def check_hot_loops(ctx: FileContext) -> Iterable[Finding]:
    if not _is_kernel_twin(ctx):
        return
    for outer in ast.walk(ctx.tree):
        if not isinstance(outer, ast.For) or not _mentions_hot_collection(outer.iter):
            continue
        outer_targets = _target_names(outer.target)
        for sub in ast.walk(outer):
            if sub is outer or not isinstance(sub, ast.For):
                continue
            if not _mentions_hot_collection(sub.iter):
                continue
            # hierarchical iteration (a job's OWN tasks, a node's OWN
            # residents) is linear in the total element count, not a
            # product: skip inner loops whose iterable derives from the
            # outer loop variable
            inner_names = {
                n.id for n in ast.walk(sub.iter) if isinstance(n, ast.Name)
            }
            if inner_names & outer_targets:
                continue
            yield ctx.finding(
                "hotpath-python-loop",
                sub,
                "nested Python loop over independent hot collections "
                f"(outer loop at line {outer.lineno}): this is the "
                "O(tasks x nodes) shape — vectorize it or move it to "
                "the host residue sub-cycle",
            )


_SYNC_NP_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                  "jax.device_get", "device_get"}


@rule(
    "hotpath-host-sync",
    ".item()/device_get/np.asarray host syncs in kernel twins or inside "
    "jit bodies — each blocks on the device and pays the tunnel RTT floor",
)
def check_host_sync(ctx: FileContext) -> Iterable[Finding]:
    twin = _is_kernel_twin(ctx)
    if not twin:
        # outside the twins we still police jit bodies (any module)
        in_jit = ctx_nodes_in_jit(ctx)
        if not in_jit:
            return
    else:
        in_jit = ctx_nodes_in_jit(ctx)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        jit_ctx = id(node) in in_jit
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item" \
                and not node.args and not node.keywords:
            if twin or jit_ctx:
                yield ctx.finding(
                    "hotpath-host-sync",
                    node,
                    ".item() is a device->host sync; fetch results packed, "
                    "once, after the solve",
                )
            continue
        name = dotted_name(node.func)
        if jit_ctx and name in _SYNC_NP_CALLS:
            yield ctx.finding(
                "hotpath-host-sync",
                node,
                f"{name}() inside a jit body materializes the traced value "
                "on host — keep the computation in jnp",
            )
        elif jit_ctx and name in ("float", "int", "bool") and len(node.args) == 1 \
                and isinstance(node.args[0], ast.Name):
            yield ctx.finding(
                "hotpath-host-sync",
                node,
                f"{name}() on a (possibly traced) value inside a jit body "
                "forces concretization; use jnp casts",
            )


_WALLCLOCK = {"time.time", "time.monotonic", "datetime.now",
              "datetime.datetime.now", "datetime.utcnow"}


@rule(
    "hotpath-wallclock",
    "wall-clock or stdlib randomness in a kernel-twin module (or any "
    "time.* call inside a jit body) — nondeterminism the parity suites "
    "cannot replay",
)
def check_wallclock(ctx: FileContext) -> Iterable[Finding]:
    twin = _is_kernel_twin(ctx)
    in_jit = ctx_nodes_in_jit(ctx)
    if not twin and not in_jit:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        jit_ctx = id(node) in in_jit
        if jit_ctx and name.startswith("time."):
            yield ctx.finding(
                "hotpath-wallclock",
                node,
                f"{name}() inside a jit body runs at trace time only — the "
                "compiled program keeps the frozen value",
            )
        elif twin and name in _WALLCLOCK:
            yield ctx.finding(
                "hotpath-wallclock",
                node,
                f"{name}() in a kernel-twin module: inject clocks from the "
                "caller (time.perf_counter is allowed for phase timing)",
            )
        elif twin and name.startswith("random."):
            yield ctx.finding(
                "hotpath-wallclock",
                node,
                f"stdlib {name}() in a kernel-twin module breaks bit-for-bit "
                "replay; thread explicit seeds/keys instead",
            )


_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "remove", "discard", "clear", "write",
             "appendleft"}


def _local_names(fn: ast.AST) -> Set[str]:
    """Names bound within one function scope (params + assignments +
    loop/with/comprehension targets + nested defs), NOT including names from
    enclosing scopes."""
    names: Set[str] = set()
    args = fn.args
    for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
        names.add(a.arg)
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)

    def collect_target(t):
        if isinstance(t, ast.Name):
            names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                collect_target(e)
        elif isinstance(t, ast.Starred):
            collect_target(t.value)

    for node in ast.walk(fn):
        if node is fn:
            continue
        if isinstance(node, ast.Assign):
            for t in node.targets:
                collect_target(t)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            collect_target(node.target)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            collect_target(node.target)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.withitem) and node.optional_vars is not None:
            collect_target(node.optional_vars)
        elif isinstance(node, ast.comprehension):
            collect_target(node.target)
        elif isinstance(node, ast.NamedExpr):
            collect_target(node.target)
    return names


def _root_name(node: ast.AST):
    cur = node
    while isinstance(cur, (ast.Attribute, ast.Subscript)):
        cur = cur.value
    if isinstance(cur, ast.Name):
        return cur.id
    return None


@rule(
    "jit-state-mutation",
    "mutation of captured Python state (or global/nonlocal) inside a "
    "jit/lax body — runs once at trace time, silently absent from the "
    "compiled program",
)
def check_jit_mutation(ctx: FileContext) -> Iterable[Finding]:
    roots = jit_roots(ctx.tree)
    if not roots:
        return
    # process every function scope contained in a jit root separately, so
    # a nested body fn mutating ITS enclosing (trace-time) scope is caught
    for root in roots:
        scopes: List[ast.AST] = [
            fn for fn in ast.walk(root)
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            locals_ = _local_names(scope)
            nested = [
                f for f in ast.walk(scope)
                if f is not scope
                and isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            nested_ids = set()
            for f in nested:
                for sub in ast.walk(f):
                    if sub is not f:
                        nested_ids.add(id(sub))
            for node in ast.walk(scope):
                if node is scope or id(node) in nested_ids:
                    continue
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    yield ctx.finding(
                        "jit-state-mutation",
                        node,
                        f"{'global' if isinstance(node, ast.Global) else 'nonlocal'} "
                        "declaration inside a jit-traced body",
                    )
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                    for t in targets:
                        if isinstance(t, (ast.Attribute, ast.Subscript)):
                            root_n = _root_name(t)
                            if root_n and root_n not in locals_:
                                yield ctx.finding(
                                    "jit-state-mutation",
                                    node,
                                    f"assignment into captured {root_n!r} inside a "
                                    "jit-traced body mutates trace-time state",
                                )
                elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                    if node.func.attr in _MUTATORS:
                        root_n = _root_name(node.func.value)
                        if root_n and root_n not in locals_:
                            yield ctx.finding(
                                "jit-state-mutation",
                                node,
                                f"{root_n}.{node.func.attr}(...) inside a jit-traced "
                                "body mutates captured trace-time state",
                            )


@rule(
    "jit-unkeyed-random",
    "host randomness (random./np.random.) or constant-seeded PRNGKey "
    "inside a jit body — the draw is frozen into the compiled program",
)
def check_jit_random(ctx: FileContext) -> Iterable[Finding]:
    in_jit = ctx_nodes_in_jit(ctx)
    if not in_jit:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or id(node) not in in_jit:
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        if name.startswith("random.") or name.startswith("np.random.") \
                or name.startswith("numpy.random."):
            yield ctx.finding(
                "jit-unkeyed-random",
                node,
                f"{name}() inside a jit body draws once at trace time; "
                "thread a jax.random key through the kernel instead",
            )
        elif name.endswith("PRNGKey") and node.args \
                and isinstance(node.args[0], ast.Constant):
            yield ctx.finding(
                "jit-unkeyed-random",
                node,
                "constant-seeded PRNGKey inside a jit body yields the same "
                "stream every call; take the key as an argument",
            )
