"""device-sync-discipline: no stray device syncs in fastpath-hot modules.

The vtprof critical-path attribution (volcano_tpu/vtprof.py) is only as
honest as the fetch discipline: every device→host synchronization in the
fastpath-hot modules must go through the sanctioned boundaries —
``vtprof.fetch`` for the packed solve outputs and ``vtprof.device_get``
for the whole-pass contention fetches.  A stray ``.block_until_ready()``,
``jax.device_get``, ``np.asarray(<device array>)`` or an implicit-sync
``float(...)`` / ``int(...)`` / ``bool(...)`` coercion of a device value:

* serializes dispatch (the host blocks mid-phase where the profiler
  expects async submission), and
* books device wait time into the ``host`` segment, corrupting exactly
  the attribution ROADMAP item 1's sharding work will be judged with.

Recognition is deliberately conservative (near-misses must stay quiet):

* ``.block_until_ready()`` and ``device_get`` (other than
  ``vtprof.device_get``) fire anywhere in the module set;
* ``np.asarray`` / ``float`` / ``int`` / ``bool`` fire only on a bare
  name whose most recent assignment in the same function came from a
  known device-solve call (``victim_step`` / ``preempt_solve`` /
  ``reclaim_solve`` / ``preempt_rounds`` / ``allocate_solve[_batch]`` /
  ``water_fill``) or from a jit wrapper created in that function
  (``jax.jit(...)`` / ``_packed_solve(...)`` /
  ``_PACKED_SOLVES.get(...)``).  Reassigning the name from a sanctioned
  fetch clears it.

The sanctioned startup syncs (Scheduler.prewarm's device handshake and
warm-task blocks — they run before the first timed cycle, where blocking
is the point) carry justified line suppressions.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from volcano_tpu.analysis.core import (
    FileContext,
    Finding,
    dotted_name,
    rule,
    walk_functions,
)

#: fastpath-hot modules (by basename, like the other loop-shape rules)
_HOT_MODULES = {
    # the fastpath package (PR 11 split of the old fastpath.py monolith;
    # the old basename stays for the rule's own test fixtures)
    "fastpath.py",
    "mirror.py", "snapshot_build.py", "cycle.py", "publish.py",
    "tensor_actions.py", "fast_victims.py", "volsolve.py",
    "kernels.py", "victim_kernels.py", "snapshot.py", "scheduler.py",
    # the sharded-cycle module: its fetch boundaries are vtprof-sanctioned
    "sharded.py",
}

#: calls whose results are device arrays (the dispatch entries)
_DEVICE_SOLVES = {
    "victim_step", "preempt_solve", "reclaim_solve", "preempt_rounds",
    "allocate_solve", "allocate_solve_batch", "water_fill",
}

#: calls that CREATE a jit wrapper; names bound to them are dispatchers
_JIT_MAKERS = {"jit", "_packed_solve"}

#: coercions that implicitly synchronize a device value
_COERCIONS = {"float", "int", "bool"}


def _call_tail(call: ast.Call) -> str:
    name = dotted_name(call.func) or ""
    return name.split(".")[-1]


def _target_names(target: ast.AST) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in target.elts:
            if isinstance(elt, ast.Name):
                out.append(elt.id)
        return out
    return []


def _collect_wrappers(fn: ast.AST) -> Set[str]:
    """Names bound (anywhere in the function) to a jit-wrapper factory."""
    wrappers: Set[str] = set()
    for sub in ast.walk(fn):
        if not (isinstance(sub, ast.Assign)
                and isinstance(sub.value, ast.Call)):
            continue
        name = dotted_name(sub.value.func) or ""
        tail = name.split(".")[-1]
        if tail in _JIT_MAKERS or name.endswith("_PACKED_SOLVES.get"):
            for t in sub.targets:
                wrappers.update(_target_names(t))
    return wrappers


def _device_assignments(fn: ast.AST,
                        wrappers: Set[str]) -> Dict[str, List[Tuple[int, bool]]]:
    """name -> [(lineno, is_device)] for every bare-name assignment in
    the function, so a use can resolve its most recent producer."""
    history: Dict[str, List[Tuple[int, bool]]] = {}
    for sub in ast.walk(fn):
        if not isinstance(sub, ast.Assign):
            continue
        is_device = False
        if isinstance(sub.value, ast.Call):
            name = dotted_name(sub.value.func) or ""
            tail = name.split(".")[-1]
            is_device = (
                tail in _DEVICE_SOLVES
                or (name in wrappers)
            ) and not name.startswith("vtprof.")
        for t in sub.targets:
            for n in _target_names(t):
                history.setdefault(n, []).append((sub.lineno, is_device))
    for entries in history.values():
        entries.sort()
    return history


def _is_device_at(history, name: str, lineno: int) -> bool:
    entries = history.get(name)
    if not entries:
        return False
    latest = None
    for ln, is_dev in entries:
        if ln <= lineno:
            latest = is_dev
        else:
            break
    return bool(latest)


@rule(
    "device-sync-discipline",
    "fastpath-hot modules must not synchronize with the device outside "
    "the sanctioned vtprof boundaries: no .block_until_ready(), no "
    "jax.device_get (use vtprof.device_get), and no np.asarray / "
    "float / int / bool of a device-solve result (use vtprof.fetch) — "
    "hidden syncs serialize dispatch and corrupt the critical-path "
    "attribution; startup warm-up blocks carry justified suppressions",
)
def check_device_sync_discipline(ctx: FileContext) -> Iterable[Finding]:
    if ctx.basename not in _HOT_MODULES:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "block_until_ready":
            yield ctx.finding(
                "device-sync-discipline", node,
                ".block_until_ready() outside the sanctioned vtprof "
                "fetch boundaries: route the fetch through vtprof.fetch "
                "/ vtprof.device_get so the wait is attributed, not "
                "hidden in a host phase",
            )
            continue
        name = dotted_name(node.func) or ""
        if name.split(".")[-1] == "device_get" \
                and not name.startswith("vtprof."):
            yield ctx.finding(
                "device-sync-discipline", node,
                f"{name}() is an unattributed device sync: use "
                "vtprof.device_get (disarmed it IS jax.device_get)",
            )
    for fn in walk_functions(ctx.tree):
        wrappers = _collect_wrappers(fn)
        history = _device_assignments(fn, wrappers)
        if not history:
            continue
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            name = dotted_name(sub.func) or ""
            tail = name.split(".")[-1]
            coercion = name in _COERCIONS
            asarray = tail == "asarray" and name.split(".")[0] in (
                "np", "numpy",
            )
            if not (coercion or asarray):
                continue
            if len(sub.args) != 1 or not isinstance(sub.args[0], ast.Name):
                continue
            arg = sub.args[0]
            if _is_device_at(history, arg.id, sub.lineno):
                what = "np.asarray" if asarray else f"{name}(...)"
                yield ctx.finding(
                    "device-sync-discipline", sub,
                    f"{what} of device-solve result {arg.id!r} is an "
                    "implicit sync outside the sanctioned boundaries: "
                    "fetch once through vtprof.fetch / vtprof.device_get "
                    "and branch on host values",
                )
