"""metric-discipline: naming and clock hygiene for the metrics layer.

The bounded-histogram metrics core (scheduler/metrics.py, r8) makes the
series operators scrape the contract surface; this rule fences the three
regressions that silently corrupt it:

1. **Counter naming** — a series recorded with ``inc()`` is a Prometheus
   counter and must end ``_total`` (the exposition stamps ``# TYPE ...
   counter``; scrape-side rate()/increase() tooling keys on the suffix).
   The reference-parity names that predate the convention
   (``volcano_total_preemption_attempts``, ``volcano_job_retry_counts``)
   carry justified line suppressions — new counters don't get to.
2. **Duration units** — a histogram whose name says it measures time
   (``latency`` / ``duration``) must carry an explicit unit suffix
   (``_seconds`` / ``_milliseconds`` / ``_microseconds``): a unitless
   duration series is unreadable on a dashboard and unfixable once
   scraped.
3. **Monotonic clocks** — a metric value derived from ``time.time()`` in
   the emitting expression measures wall-clock, which steps under NTP
   and skews latency tails; measurement sites must use
   ``time.monotonic()`` / ``time.perf_counter()``.  The one sanctioned
   exception (the cross-process first-seen→bind series, whose start edge
   is an epoch creation timestamp) carries a justified suppression.

Scope: the whole package — metric calls are recognized by shape
(``metrics.inc`` / ``metrics.observe`` / ``metrics.update_*`` /
``metrics.register_*`` / ``metrics.set_gauge``, or the bare helpers
inside a module that defines them) with a ``volcano``-prefixed literal
name where naming is checked.

4. **HELP coverage** (scoped to the fleet-observability modules) — a
   series recorded by vtfleet.py lands on the FEDERATED exposition the
   ShardRouter serves, where a missing ``# HELP`` line is filled with a
   placeholder the operator's dashboards then display; every literal
   family name those modules record must be registered in the ``_HELP``
   table of scheduler/metrics.py.  Scoped because the general package
   rule would fire on every reference-parity family that predates the
   table.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, Optional

from volcano_tpu.analysis.core import (
    FileContext,
    Finding,
    dotted_name,
    rule,
)

_UNIT_SUFFIXES = ("_seconds", "_milliseconds", "_microseconds")
_DURATION_MARKERS = ("latency", "duration")

#: modules whose recorded families must be HELP'd in scheduler/metrics.py
#: (they feed the router's merged /metrics, where an un-HELP'd family
#: gets a placeholder description on every operator dashboard)
_HELP_SCOPED = ("vtfleet.py",)

_HELP_CACHE: list = []  # [frozenset] once parsed; [None] on parse failure


def _help_names() -> Optional[frozenset]:
    """The literal keys of scheduler/metrics.py's ``_HELP`` table, read
    by AST (importing the package from a lint pass would execute it).
    Returns None — sub-check skipped — when the file cannot be parsed."""
    if _HELP_CACHE:
        return _HELP_CACHE[0]
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "scheduler", "metrics.py")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
        names = None
        for node in ast.walk(tree):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target]
                       if isinstance(node, ast.AnnAssign) else [])
            if any(isinstance(t, ast.Name) and t.id == "_HELP"
                   for t in targets) and isinstance(node.value, ast.Dict):
                names = frozenset(
                    k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                )
        _HELP_CACHE.append(names)
    except (OSError, SyntaxError, ValueError):
        _HELP_CACHE.append(None)
    return _HELP_CACHE[0]


def _metric_call(call: ast.Call) -> Optional[str]:
    """The metric-layer verb this call invokes (``inc`` / ``observe`` /
    ``set_gauge`` / ``update_*`` / ``register_*`` / ``observe_*``), or
    None.  Bare names count too — metrics.py itself calls its own
    module-level ``inc``/``observe``."""
    name = dotted_name(call.func)
    if not name:
        return None
    tail = name.split(".")[-1]
    if tail in ("inc", "observe", "set_gauge"):
        return tail
    if "metrics" in name.split(".")[:-1] and (
        tail.startswith("update_") or tail.startswith("register_")
        or tail.startswith("observe_")
    ):
        return tail
    return None


def _literal_metric_name(call: ast.Call) -> Optional[str]:
    """First-arg string literal when it names a volcano series."""
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        name = call.args[0].value
        if name.startswith("volcano"):
            return name
    return None


def _uses_wall_clock(call: ast.Call) -> bool:
    """Any ``time.time()`` call inside the metric call's argument
    subtree — the value being recorded was derived from wall clock."""
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Call):
                name = dotted_name(sub.func) or ""
                parts = name.split(".")
                if parts[-1] == "time" and len(parts) > 1 \
                        and parts[-2] in ("time", "_time"):
                    return True
    return False


@rule(
    "metric-discipline",
    "metrics hygiene: counters recorded with inc() must end _total, "
    "duration histograms must carry a unit suffix "
    "(_seconds/_milliseconds/_microseconds), and metric values must not "
    "be derived from wall-clock time.time() — use time.monotonic() / "
    "time.perf_counter(); reference-parity names and cross-process epoch "
    "edges carry justified line suppressions",
)
def check_metric_discipline(ctx: FileContext) -> Iterable[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        verb = _metric_call(node)
        if verb is None:
            continue
        if _uses_wall_clock(node):
            yield ctx.finding(
                "metric-discipline",
                node,
                f"metric value for {verb}() derived from wall-clock "
                "time.time(): latency/duration measurement must use "
                "time.monotonic() or time.perf_counter() (wall clock "
                "steps under NTP and skews the recorded tail)",
            )
        name = _literal_metric_name(node)
        if name is None:
            continue
        if verb == "inc" and not name.endswith("_total"):
            yield ctx.finding(
                "metric-discipline",
                node,
                f"counter {name!r} recorded with inc() must end "
                "'_total' (Prometheus counter naming; the exposition "
                "stamps TYPE counter)",
            )
        if verb == "observe" and any(
            m in name for m in _DURATION_MARKERS
        ) and not name.endswith(_UNIT_SUFFIXES):
            yield ctx.finding(
                "metric-discipline",
                node,
                f"duration histogram {name!r} must carry a unit suffix "
                "(_seconds/_milliseconds/_microseconds)",
            )
        if ctx.basename in _HELP_SCOPED:
            helped = _help_names()
            if helped is not None and name not in helped:
                yield ctx.finding(
                    "metric-discipline",
                    node,
                    f"family {name!r} recorded by {ctx.basename} is "
                    "missing from the _HELP table in "
                    "scheduler/metrics.py: it lands on the router's "
                    "federated /metrics with a placeholder HELP line "
                    "(register a description beside the other families)",
                )
