"""shard-spec-complete: every sharded-cycle argument has a declared placement.

The mesh-sharded fast cycle (parallel/sharded.py) jits one cycle function
with explicit ``NamedSharding`` in_shardings derived from the ``_SPECS``
PartitionSpec table; anything absent from the table silently replicates
via the ``P()`` default.  That default is exactly how a sharding bug
ships: a new node-shaped array added to the cycle without a ``_SPECS``
entry quietly replicates whole across the mesh — correctness holds (GSPMD
inserts resharding collectives), so no test fails, but the scale axis the
mesh exists for (node-plane memory and bandwidth dividing by shard count)
silently stops applying to that array.

This rule makes the placement decision explicit and total: in the module
set (``sharded.py`` and the multi-controller ``multihost.py``, whose
host-axis ``_SPECS`` extends the same contract) every string key read
from the cycle-argument dict (``args["name"]`` inside the jitted cycle
body ``_cycle``) must appear in the ``_SPECS`` PartitionSpec table OR in
the explicit ``_REPLICATED`` set.  A name in neither is a finding — add it to ``_SPECS`` with its node
axis, or to ``_REPLICATED`` with the reason it replicates (a conscious
placement, reviewable in the diff, instead of a silent default).

Recognition is conservative: only constant-string subscripts of the
``args`` parameter inside functions named ``_cycle``/``cycle``/
``sharded_cycle`` are checked, so helper dicts and wire payloads
elsewhere in the module never fire.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Set

from volcano_tpu.analysis.core import FileContext, Finding, rule

_SCOPED_BASENAMES = {"sharded.py", "multihost.py"}

#: cycle-body function names whose ``args[...]`` reads are checked
_CYCLE_FNS = {"_cycle", "cycle", "sharded_cycle"}

#: module-level names holding the placement tables
_SPEC_TABLE = "_SPECS"
_REPL_TABLE = "_REPLICATED"


def _assigned_value(tree: ast.AST, name: str) -> Optional[ast.AST]:
    """The module-level value bound to ``name`` (Assign or AnnAssign)."""
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return node.value
        elif isinstance(node, ast.AnnAssign):
            if isinstance(node.target, ast.Name) and node.target.id == name:
                return node.value
    return None


def _string_keys(value: Optional[ast.AST]) -> Optional[Set[str]]:
    """String keys of a dict literal / elements of a set literal, looking
    through ``frozenset({...})``/``set({...})`` wrappers; None when the
    table is absent or not a literal the rule can read."""
    if value is None:
        return None
    if isinstance(value, ast.Call):
        fn = value.func
        if (
            isinstance(fn, ast.Name)
            and fn.id in ("frozenset", "set")
            and value.args
        ):
            value = value.args[0]
    out: Set[str] = set()
    if isinstance(value, ast.Dict):
        for k in value.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out.add(k.value)
        return out
    if isinstance(value, (ast.Set, ast.List, ast.Tuple)):
        for e in value.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
        return out
    return None


@rule(
    "shard-spec-complete",
    "an array argument enters the jitted sharded cycle with no entry in "
    "the PartitionSpec table (_SPECS) and no explicit replicated "
    "declaration (_REPLICATED): it silently replicates across the mesh — "
    "declare its node-axis spec or its reason to replicate",
)
def check_shard_spec_complete(ctx: FileContext) -> Iterable[Finding]:
    if ctx.basename not in _SCOPED_BASENAMES:
        return
    specs = _string_keys(_assigned_value(ctx.tree, _SPEC_TABLE))
    repl = _string_keys(_assigned_value(ctx.tree, _REPL_TABLE))
    declared = (specs or set()) | (repl or set())
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name not in _CYCLE_FNS:
            continue
        arg_names = {a.arg for a in fn.args.args} | {
            a.arg for a in fn.args.kwonlyargs
        }
        if "args" not in arg_names:
            continue
        if specs is None:
            yield ctx.finding(
                "shard-spec-complete",
                fn,
                f"module defines a sharded cycle ({fn.name!r}) but no "
                f"{_SPEC_TABLE} PartitionSpec table — every argument "
                "placement is a silent default",
            )
            return
        seen: Set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Subscript):
                continue
            if not (
                isinstance(node.value, ast.Name)
                and node.value.id == "args"
            ):
                continue
            sl = node.slice
            if not (
                isinstance(sl, ast.Constant) and isinstance(sl.value, str)
            ):
                continue
            name = sl.value
            if name in declared or name in seen:
                continue
            seen.add(name)
            yield ctx.finding(
                "shard-spec-complete",
                node,
                f"cycle argument {name!r} has no PartitionSpec "
                f"({_SPEC_TABLE}) and no explicit replicated declaration "
                f"({_REPL_TABLE}): it silently replicates across the "
                "mesh — declare its placement",
            )
