"""Parity-citation coverage for action/plugin modules.

Every behavior in `scheduler/actions/` and `scheduler/plugins/` is a
line-for-line reproduction of a reference component (PARITY.md maps them
all); the project convention is that each module carries a
``Parity: reference ...<file>.go:<lines>`` citation in its module
docstring, and every Action/Plugin entrypoint is covered by a citation in
its own, its class's, or its module's docstring.  A new action or plugin
without a citation is unreviewable against the reference — exactly the
drift the parity suites exist to catch late; this rule catches it at lint
time.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from volcano_tpu.analysis.core import FileContext, Finding, rule

#: "<something>.go:123" or "<something>.go:123-456"
CITATION_RE = re.compile(r"[\w./-]+\.go:\d+(?:-\d+)?")

_ENTRYPOINTS = {"execute", "on_session_open"}
_BASES = {"Action", "Plugin"}


def _in_scope(ctx: FileContext) -> bool:
    if ctx.basename == "__init__.py":
        return False
    return any(part in ("actions", "plugins") for part in ctx.dir_parts)


def _has_citation(doc: Optional[str]) -> bool:
    return bool(doc and CITATION_RE.search(doc))


@rule(
    "parity-citation",
    "action/plugin modules and their entrypoints must carry a reference "
    "file:line citation (the PARITY.md convention)",
)
def check_parity_citation(ctx: FileContext) -> Iterable[Finding]:
    if not _in_scope(ctx):
        return
    module_doc = ast.get_docstring(ctx.tree)
    module_cited = _has_citation(module_doc)
    if not module_cited:
        yield ctx.finding(
            "parity-citation",
            1,
            "module docstring lacks a reference citation "
            "('Parity: reference <file>.go:<lines>'); every action/plugin "
            "module must name the reference code it reproduces",
        )
    for node in ctx.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        bases = {
            b.attr if isinstance(b, ast.Attribute) else getattr(b, "id", "")
            for b in node.bases
        }
        if not bases & _BASES:
            continue
        class_cited = module_cited or _has_citation(ast.get_docstring(node))
        for item in node.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name not in _ENTRYPOINTS:
                continue
            if class_cited or _has_citation(ast.get_docstring(item)):
                continue
            yield ctx.finding(
                "parity-citation",
                item,
                f"entrypoint {node.name}.{item.name} has no reference "
                "citation in its own, its class's, or its module's "
                "docstring",
            )
