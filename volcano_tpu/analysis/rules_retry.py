"""Retry pacing discipline for daemon code: no fixed-sleep transient retries.

The control-plane daemons (cli/daemons.py), the elastic autoscaler
(volcano_tpu/elastic/), the leader elector, and the store client all run
retry-on-transient loops against the store bus.  The
shared pacing primitive is ``volcano_tpu/backoff.py`` (decorrelated-jitter
exponential backoff): a fixed ``time.sleep(period)`` on the retry path
synchronizes every replica in a deployment onto the same beat — after an
apiserver restart the whole fleet reconnects simultaneously, the
thundering herd the reference avoids with client-go's wait.Backoff.

The ``retry-backoff`` rule flags, in daemon modules only:

* a ``time.sleep(<fixed>)`` inside an except handler that catches
  transient store errors (OSError/RemoteStoreError/StaleWatch/…) within a
  retry loop — the sleep must derive from a backoff (``retry.sleep()`` or
  ``time.sleep(bo.next())``);
* a transient handler that falls through (no continue/break/return/raise)
  to a fixed loop-level ``time.sleep`` — the pre-backoff daemons.py shape,
  where the healthy-path pump sleep silently doubled as the retry delay.

A fixed sleep on the HEALTHY path (reached only after the handler
``continue``\\ s) stays legal: the pump period is deliberately fixed.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from volcano_tpu.analysis.core import FileContext, Finding, dotted_name, rule

#: exception names that mark a handler as catching store-bus transients
_TRANSIENT_NAMES = {
    "OSError", "ConnectionError", "ConnectionResetError",
    "ConnectionRefusedError", "BrokenPipeError", "TimeoutError",
    "URLError", "HTTPException", "RemoteStoreError", "StaleWatch",
}

#: daemon modules the discipline applies to (replica.py: the follower
#: pump retries the leader's feed across outages and elections — the
#: exact reconnect-storm shape the jitter discipline exists for)
_SCOPED_BASENAMES = {"daemons.py", "leader.py", "client.py", "replica.py"}

#: daemon PACKAGES the discipline applies to wholesale: every module under
#: cli/ (the daemon entrypoints) and elastic/ (elasticd's reconciler —
#: its pump loops retry against the store bus exactly like the daemons)
_SCOPED_DIRS = {"cli", "elastic"}


def _in_scope(ctx: FileContext) -> bool:
    return bool(_SCOPED_DIRS.intersection(ctx.dir_parts)) \
        or ctx.basename in _SCOPED_BASENAMES


def _exc_names(node: Optional[ast.AST]) -> List[str]:
    """Leaf exception names of an except clause's type expression."""
    if node is None:
        return []
    if isinstance(node, ast.Tuple):
        out: List[str] = []
        for elt in node.elts:
            out.extend(_exc_names(elt))
        return out
    name = dotted_name(node)
    if name is not None:
        return [name.split(".")[-1]]
    return []


def _is_transient_handler(handler: ast.ExceptHandler) -> bool:
    for name in _exc_names(handler.type):
        if name in _TRANSIENT_NAMES or "transient" in name.lower():
            return True
    return False


def _is_fixed_sleep(call: ast.Call) -> bool:
    """``time.sleep(X)`` where X does not derive from a backoff — no
    ``.next()``/``.sleep()`` call anywhere in the argument expression."""
    if dotted_name(call.func) != "time.sleep" or not call.args:
        return False
    for sub in ast.walk(call.args[0]):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in ("next", "sleep"):
            return False
    return True


def _escapes(handler: ast.ExceptHandler) -> bool:
    """Does the handler body leave the loop iteration (continue/break/
    return/raise) rather than falling through to the loop tail?"""
    for sub in ast.walk(handler):
        if isinstance(sub, (ast.Continue, ast.Break, ast.Return, ast.Raise)):
            return True
    return False


def _loop_level_nodes(loop: ast.AST) -> Iterable[ast.AST]:
    """The loop's subtree, excluding nested loops and function defs (their
    sleeps belong to their own construct)."""

    def walk(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.While, ast.For, ast.AsyncFor,
                                  ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            yield child
            yield from walk(child)

    yield from walk(loop)


@rule(
    "retry-backoff",
    "fixed-sleep retry of a transient store error in daemon code — pace "
    "retries through volcano_tpu.backoff (decorrelated jitter), not "
    "time.sleep(period)",
)
def check_retry_backoff(ctx: FileContext) -> Iterable[Finding]:
    if not _in_scope(ctx):
        return
    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, (ast.While, ast.For)):
            continue
        fallthrough = False
        handler_spans: List[tuple] = []
        for node in _loop_level_nodes(loop):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                # record EVERY handler's span: a fixed sleep inside a
                # non-transient handler (e.g. `except Conflict:`) is that
                # handler's business, not the loop-tail retry delay the
                # fall-through pass below is hunting
                handler_spans.append(
                    (handler.lineno, handler.end_lineno or handler.lineno)
                )
                if not _is_transient_handler(handler):
                    continue
                for sub in ast.walk(handler):
                    if isinstance(sub, ast.Call) and _is_fixed_sleep(sub):
                        yield ctx.finding(
                            "retry-backoff",
                            sub,
                            "transient-error handler retries on a fixed "
                            "time.sleep — use a Backoff "
                            "(volcano_tpu/backoff.py): retry.sleep() / "
                            "time.sleep(retry.next())",
                        )
                if not _escapes(handler):
                    fallthrough = True
        if not fallthrough:
            continue
        # a transient handler falls through: the loop-tail sleep IS the
        # retry delay, and it must not be fixed
        for node in _loop_level_nodes(loop):
            if isinstance(node, ast.Call) and _is_fixed_sleep(node):
                line = node.lineno
                if any(a <= line <= b for a, b in handler_spans):
                    continue  # already reported above
                yield ctx.finding(
                    "retry-backoff",
                    node,
                    "a transient-error handler in this loop falls through "
                    "to this fixed time.sleep, making it the retry delay — "
                    "back off with jitter in the handler "
                    "(volcano_tpu/backoff.py) and `continue`, keeping the "
                    "healthy-path period fixed",
                )
