"""vtlint: project-native static analysis for volcano-tpu.

Enforces the disciplines the kernels depend on — hot-path purity,
jit-boundary hygiene, ε-tolerant Resource comparison, parity-citation
coverage, Session-registry completeness, lock ordering, Statement
commit/discard totality, and no silent exception swallowing — as
machine-checked rules that run before every PR (`make lint`, and as the
preamble of `make test`; `tests/test_vtlint.py` keeps the tree at zero
findings).  `ANALYSIS.md` documents every rule.

CLI:  python -m volcano_tpu.analysis [--json] [--select RULES] [paths...]

The package is pure stdlib (ast/re/tokenize) — it runs anywhere the
package installs, jax or not.  The runtime half (the env-gated lock-order
sanitizer the static `lock-order` rule is cross-checked against) lives in
`volcano_tpu.analysis.locksan`.
"""

from volcano_tpu.analysis.core import (  # noqa: F401
    Finding,
    all_rules,
    run_paths,
)

__all__ = ["Finding", "all_rules", "run_paths"]
