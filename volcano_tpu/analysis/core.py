"""vtlint core: rule registry, suppression handling, file walking, output.

The analyzer is pure stdlib (ast + re) so it can run in any environment the
package installs into — including CI images without jax.  Rules live in the
sibling modules and register themselves through :func:`rule`; each rule is a
function ``(ctx: FileContext) -> Iterable[Finding]`` plus metadata.

Suppression contract (per-file, the only sanctioned escape hatch):

* a comment line ``# vtlint: disable=rule-a,rule-b`` anywhere in a file
  disables those rules for the whole file;
* a trailing ``# vtlint: disable=rule-a`` on a code line disables the rule
  for that line only;
* unknown rule names in a disable comment are themselves findings (rule
  ``vtlint-usage``) — a typoed suppression must not silently disable
  nothing.
"""

from __future__ import annotations

import ast
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set

#: rule id -> (description, fn)
_REGISTRY: Dict[str, "Rule"] = {}

#: pseudo-rule for analyzer-usage errors (bad suppressions); never
#: suppressible and always active.
USAGE_RULE = "vtlint-usage"

_DISABLE_RE = re.compile(r"#\s*vtlint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # relative to the analysis root
    line: int
    message: str

    def human(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"

    def as_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


@dataclass
class Rule:
    id: str
    description: str
    fn: Callable[["FileContext"], Iterable[Finding]]


def rule(id: str, description: str):
    """Decorator registering a rule function in the global registry."""

    def deco(fn):
        if id in _REGISTRY:
            raise ValueError(f"duplicate vtlint rule id {id!r}")
        _REGISTRY[id] = Rule(id, description, fn)
        return fn

    return deco


def all_rules() -> Dict[str, Rule]:
    _load_rule_modules()
    return dict(_REGISTRY)


_LOADED = False


def _load_rule_modules() -> None:
    """Import the rule modules exactly once (they self-register)."""
    global _LOADED
    if _LOADED:
        return
    _LOADED = True
    from volcano_tpu.analysis import (  # noqa: F401  (import = registration)
        rules_audit,
        rules_concurrency,
        rules_delta,
        rules_device,
        rules_epsilon,
        rules_excepts,
        rules_hotpath,
        rules_io,
        rules_metrics,
        rules_parity,
        rules_registry,
        rules_residue,
        rules_retry,
        rules_shard,
        rules_statement,
        rules_trace,
        rules_wire,
    )


@dataclass
class FileContext:
    """Everything a rule needs to know about one file under analysis."""

    path: str  # absolute
    relpath: str  # relative to the root, forward slashes
    source: str
    tree: ast.AST
    #: rules disabled for the whole file
    file_disabled: Set[str] = field(default_factory=set)
    #: line -> rules disabled on that line
    line_disabled: Dict[int, Set[str]] = field(default_factory=dict)
    #: findings raised by suppression parsing itself (unknown rule names)
    usage_findings: List[Finding] = field(default_factory=list)
    #: per-file memo shared across rules (jit-node sets, lock graphs, ...)
    cache: Dict[str, object] = field(default_factory=dict)

    @property
    def basename(self) -> str:
        return os.path.basename(self.relpath)

    @property
    def dir_parts(self) -> Sequence[str]:
        return tuple(self.relpath.split("/")[:-1])

    def finding(self, rule_id: str, node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(rule_id, self.relpath, int(line), message)


def _parse_suppressions(ctx: FileContext, known: Set[str]) -> None:
    """Populate file/line disable sets from ``# vtlint: disable=`` comments.

    Comment-only lines disable file-wide; trailing comments disable that
    line.  Comments are found with the tokenizer, not a regex over raw
    lines, so a disable marker inside a string literal is inert.
    """
    import io

    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(ctx.source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return
    # lines that contain any non-comment, non-whitespace token
    code_lines: Set[int] = set()
    for tok in tokens:
        if tok.type in (
            tokenize.COMMENT,
            tokenize.NL,
            tokenize.NEWLINE,
            tokenize.INDENT,
            tokenize.DEDENT,
            tokenize.ENCODING,
            tokenize.ENDMARKER,
        ):
            continue
        for ln in range(tok.start[0], tok.end[0] + 1):
            code_lines.add(ln)
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _DISABLE_RE.search(tok.string)
        if not m:
            continue
        names = [n.strip() for n in m.group(1).split(",") if n.strip()]
        line = tok.start[0]
        for name in names:
            if name not in known:
                ctx.usage_findings.append(
                    ctx.finding(
                        USAGE_RULE,
                        line,
                        f"unknown rule {name!r} in vtlint disable comment "
                        f"(known: {', '.join(sorted(known))})",
                    )
                )
                continue
            if line in code_lines:
                ctx.line_disabled.setdefault(line, set()).add(name)
            else:
                ctx.file_disabled.add(name)


def load_file(path: str, root: str) -> Optional[FileContext]:
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        ctx = FileContext(path=path, relpath=_rel(path, root), source=source,
                          tree=ast.Module(body=[], type_ignores=[]))
        ctx.usage_findings.append(
            ctx.finding(USAGE_RULE, e.lineno or 1, f"syntax error: {e.msg}")
        )
        return ctx
    ctx = FileContext(path=path, relpath=_rel(path, root), source=source, tree=tree)
    return ctx


def _rel(path: str, root: str) -> str:
    try:
        rel = os.path.relpath(path, root)
    except ValueError:  # different drive (windows)
        rel = path
    return rel.replace(os.sep, "/")


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def run_paths(
    paths: Sequence[str],
    root: Optional[str] = None,
    select: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Analyze ``paths`` (files or directories) and return sorted findings.

    ``root`` anchors relative paths in findings (defaults to the common
    parent).  ``select`` limits the run to the given rule ids; unknown ids
    raise ValueError (a CI target selecting a typoed rule must fail loudly,
    not pass vacuously).
    """
    rules = all_rules()
    if select is not None:
        unknown = [s for s in select if s not in rules]
        if unknown:
            raise ValueError(
                f"unknown rule id(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(rules))})"
            )
        rules = {k: v for k, v in rules.items() if k in set(select)}
    if root is None:
        root = os.path.commonpath([os.path.abspath(p) for p in paths]) if paths else "."
        if os.path.isfile(root):
            root = os.path.dirname(root)
    known_ids = set(all_rules())
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        ctx = load_file(path, root)
        if ctx is None:
            continue
        _parse_suppressions(ctx, known_ids)
        findings.extend(ctx.usage_findings)
        for r in rules.values():
            if r.id in ctx.file_disabled:
                continue
            for f in r.fn(ctx):
                if r.id in ctx.line_disabled.get(f.line, ()):
                    continue
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# --- shared AST helpers used by several rules --------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested Name/Attribute chains, else None."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def resolve_iterable(
    expr: ast.AST,
    names: Set[str],
    wrappers: Set[str],
    call_suffixes: Sequence[str] = (),
) -> Optional[str]:
    """The collection spelling an iterable expression resolves to, or
    None.  Sees through wrapper calls (``enumerate``/``list``/``zip``/…,
    every positional argument considered) and ``.items()``/``.values()``/
    ``.keys()`` methods; matches bare names and attribute tails against
    ``names``, and calls whose last dotted segment is in
    ``call_suffixes``.  Shared by the loop-shape rules
    (``residue-vectorized``, ``columnar-publish``) so the wrapper-peeling
    logic cannot drift between them."""
    stack = [expr]
    while stack:
        cur = stack.pop()
        while isinstance(cur, ast.Call):
            fname = dotted_name(cur.func)
            if fname in wrappers and cur.args:
                stack.extend(cur.args[1:])
                cur = cur.args[0]
                continue
            if fname is not None and fname.split(".")[-1] in call_suffixes:
                return fname
            if isinstance(cur.func, ast.Attribute) and cur.func.attr in (
                "items", "values", "keys",
            ):
                cur = cur.func.value
                continue
            cur = None
            break
        if isinstance(cur, ast.Name) and cur.id in names:
            return cur.id
        if isinstance(cur, ast.Attribute) and cur.attr in names:
            return dotted_name(cur) or cur.attr
    return None


def walk_functions(tree: ast.AST):
    """Yield every FunctionDef/AsyncFunctionDef in the tree."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def is_jit_decorated(fn: ast.AST) -> bool:
    """True for @jax.jit / @jit / @functools.partial(jax.jit, ...) etc."""
    for dec in getattr(fn, "decorator_list", []):
        name = dotted_name(dec)
        if name in ("jit", "jax.jit"):
            return True
        if isinstance(dec, ast.Call):
            cname = dotted_name(dec.func)
            if cname in ("jit", "jax.jit"):
                return True
            if cname in ("partial", "functools.partial") and dec.args:
                inner = dotted_name(dec.args[0])
                if inner in ("jit", "jax.jit"):
                    return True
    return False


_LAX_HOF = {"while_loop", "cond", "scan", "fori_loop", "switch", "map"}


def jit_roots(tree: ast.AST) -> List[ast.AST]:
    """Function defs that execute under a jax trace: jit-decorated
    functions, plus any top-level function passed by name into a
    ``lax.while_loop``/``cond``/``scan``-style higher-order call when the
    call site itself is not already inside a jit root (nested defs inside a
    jit root are covered by containment)."""
    roots = [fn for fn in walk_functions(tree) if is_jit_decorated(fn)]
    root_set = set(id(r) for r in roots)
    # functions referenced by name in lax higher-order calls
    referenced: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname and fname.split(".")[-1] in _LAX_HOF and (
                fname.startswith("lax.") or fname.startswith("jax.lax.")
                or fname.split(".")[-2:-1] == ["lax"]
            ):
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        referenced.add(arg.id)
    if referenced:
        contained = set()
        for r in roots:
            for sub in ast.walk(r):
                contained.add(id(sub))
        for fn in walk_functions(tree):
            if fn.name in referenced and id(fn) not in contained and id(fn) not in root_set:
                roots.append(fn)
                root_set.add(id(fn))
    return roots


def nodes_in_jit(tree: ast.AST) -> Set[int]:
    """id()s of every AST node that executes under a jax trace."""
    out: Set[int] = set()
    for root in jit_roots(tree):
        for sub in ast.walk(root):
            out.add(id(sub))
    return out


def ctx_nodes_in_jit(ctx: "FileContext") -> Set[int]:
    """`nodes_in_jit(ctx.tree)`, computed once per file (several rules
    need it)."""
    if "nodes_in_jit" not in ctx.cache:
        ctx.cache["nodes_in_jit"] = nodes_in_jit(ctx.tree)
    return ctx.cache["nodes_in_jit"]  # type: ignore[return-value]
