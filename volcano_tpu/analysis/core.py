"""vtlint core: rule registry, suppression handling, file walking, output.

The analyzer is pure stdlib (ast + re) so it can run in any environment the
package installs into — including CI images without jax.  Rules live in the
sibling modules and register themselves through :func:`rule`; each rule is a
function ``(ctx: FileContext) -> Iterable[Finding]`` plus metadata.

Suppression contract (per-file, the only sanctioned escape hatch):

* a comment line ``# vtlint: disable=rule-a,rule-b`` anywhere in a file
  disables those rules for the whole file;
* a trailing ``# vtlint: disable=rule-a`` on a code line disables the rule
  for that line only;
* unknown rule names in a disable comment are themselves findings (rule
  ``vtlint-usage``) — a typoed suppression must not silently disable
  nothing.
"""

from __future__ import annotations

import ast
import os
import re
import time
import tokenize
from dataclasses import dataclass, field, replace as _dc_replace
from typing import (
    Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple,
)

#: rule id -> (description, fn)
_REGISTRY: Dict[str, "Rule"] = {}

#: pseudo-rule for analyzer-usage errors (bad suppressions); never
#: suppressible and always active.
USAGE_RULE = "vtlint-usage"

_DISABLE_RE = re.compile(r"#\s*vtlint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # relative to the analysis root
    line: int
    message: str
    #: set only in ``--worklist`` mode: the finding was suppressed in the
    #: source; ``justification`` carries the suppressing comment's text so
    #: the machine-readable inventory keeps the human reasoning attached
    suppressed: bool = False
    justification: str = ""

    def human(self) -> str:
        tag = " [suppressed]" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.rule}{tag}: {self.message}"

    def as_dict(self) -> Dict[str, object]:
        d: Dict[str, object] = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }
        if self.suppressed:
            d["suppressed"] = True
            d["justification"] = self.justification
        return d


@dataclass
class Rule:
    id: str
    description: str
    fn: Callable[..., Iterable[Finding]]
    #: "file" rules see one FileContext; "project" rules see the whole
    #: package as a ProjectContext (the vtflow interprocedural core)
    scope: str = "file"


def rule(id: str, description: str, scope: str = "file"):
    """Decorator registering a rule function in the global registry."""

    def deco(fn):
        if id in _REGISTRY:
            raise ValueError(f"duplicate vtlint rule id {id!r}")
        _REGISTRY[id] = Rule(id, description, fn, scope)
        return fn

    return deco


def all_rules() -> Dict[str, Rule]:
    _load_rule_modules()
    return dict(_REGISTRY)


_LOADED = False


def _load_rule_modules() -> None:
    """Import the rule modules exactly once (they self-register)."""
    global _LOADED
    if _LOADED:
        return
    _LOADED = True
    from volcano_tpu.analysis import (  # noqa: F401  (import = registration)
        rules_audit,
        rules_concurrency,
        rules_delta,
        rules_device,
        rules_digestreach,
        rules_effectorder,
        rules_epsilon,
        rules_excepts,
        rules_hotpath,
        rules_io,
        rules_latebind,
        rules_metrics,
        rules_parity,
        rules_procisolation,
        rules_registry,
        rules_residue,
        rules_retry,
        rules_shard,
        rules_statement,
        rules_trace,
        rules_wire,
    )


@dataclass
class FileContext:
    """Everything a rule needs to know about one file under analysis."""

    path: str  # absolute
    relpath: str  # relative to the root, forward slashes
    source: str
    tree: ast.AST
    #: rules disabled for the whole file
    file_disabled: Set[str] = field(default_factory=set)
    #: line -> rules disabled on that line
    line_disabled: Dict[int, Set[str]] = field(default_factory=dict)
    #: findings raised by suppression parsing itself (unknown rule names)
    usage_findings: List[Finding] = field(default_factory=list)
    #: per-file memo shared across rules (jit-node sets, lock graphs, ...)
    cache: Dict[str, object] = field(default_factory=dict)

    @property
    def basename(self) -> str:
        return os.path.basename(self.relpath)

    @property
    def dir_parts(self) -> Sequence[str]:
        return tuple(self.relpath.split("/")[:-1])

    def finding(self, rule_id: str, node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(rule_id, self.relpath, int(line), message)

    def suppression_note(self, rule_id: str, line: int) -> str:
        """The text of the disable comment covering (rule_id, line) — the
        human justification a ``--worklist`` report keeps attached."""
        lines = self.source.splitlines()

        def comment_of(ln: int) -> str:
            if 1 <= ln <= len(lines) and "#" in lines[ln - 1]:
                return lines[ln - 1][lines[ln - 1].index("#"):].strip()
            return ""

        if rule_id in self.line_disabled.get(line, ()):
            # the disable may sit on any line of the logical statement;
            # scan the lines that share this line's disable set
            for ln, rules in sorted(self.line_disabled.items()):
                if rule_id in rules and abs(ln - line) <= 50:
                    note = comment_of(ln)
                    if rule_id in note:
                        return note
            return comment_of(line)
        if rule_id in self.file_disabled:
            for i, text in enumerate(lines, 1):
                m = _DISABLE_RE.search(text)
                if m and rule_id in m.group(1):
                    return comment_of(i)
        return ""


def _parse_suppressions(ctx: FileContext, known: Set[str]) -> None:
    """Populate file/line disable sets from ``# vtlint: disable=`` comments.

    Scoping follows LOGICAL lines: a disable comment lexically inside a
    multi-line statement (trailing the code, or on its own continuation
    line) disables the rules for every physical line the statement spans —
    findings anchor at a statement's first line, so a trailing disable on
    the closing-paren line still covers them.  A comment outside any
    logical line disables file-wide.  Comments are found with the
    tokenizer, not a regex over raw lines, so a disable marker inside a
    string literal is inert.
    """
    import io

    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(ctx.source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return
    # logical-line intervals: first code-token line .. NEWLINE line
    intervals: List[Tuple[int, int]] = []
    start: Optional[int] = None
    last_code_end = 0
    for tok in tokens:
        if tok.type in (
            tokenize.COMMENT,
            tokenize.NL,
            tokenize.INDENT,
            tokenize.DEDENT,
            tokenize.ENCODING,
            tokenize.ENDMARKER,
        ):
            continue
        if tok.type == tokenize.NEWLINE:
            if start is not None:
                intervals.append((start, max(tok.start[0], last_code_end)))
                start = None
            continue
        if start is None:
            start = tok.start[0]
        last_code_end = max(last_code_end, tok.end[0])
    if start is not None:  # unterminated final logical line
        intervals.append((start, last_code_end))

    def interval_of(line: int) -> Optional[Tuple[int, int]]:
        for s, e in intervals:
            if s <= line <= e:
                return (s, e)
        return None

    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _DISABLE_RE.search(tok.string)
        if not m:
            continue
        names = [n.strip() for n in m.group(1).split(",") if n.strip()]
        line = tok.start[0]
        span = interval_of(line)
        for name in names:
            if name not in known:
                ctx.usage_findings.append(
                    ctx.finding(
                        USAGE_RULE,
                        line,
                        f"unknown rule {name!r} in vtlint disable comment "
                        f"(known: {', '.join(sorted(known))})",
                    )
                )
                continue
            if span is not None:
                for ln in range(span[0], span[1] + 1):
                    ctx.line_disabled.setdefault(ln, set()).add(name)
            else:
                ctx.file_disabled.add(name)


def load_file(path: str, root: str) -> Optional[FileContext]:
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        ctx = FileContext(path=path, relpath=_rel(path, root), source=source,
                          tree=ast.Module(body=[], type_ignores=[]))
        ctx.usage_findings.append(
            ctx.finding(USAGE_RULE, e.lineno or 1, f"syntax error: {e.msg}")
        )
        return ctx
    ctx = FileContext(path=path, relpath=_rel(path, root), source=source, tree=tree)
    return ctx


def _rel(path: str, root: str) -> str:
    try:
        rel = os.path.relpath(path, root)
    except ValueError:  # different drive (windows)
        rel = path
    return rel.replace(os.sep, "/")


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def run_paths(
    paths: Sequence[str],
    root: Optional[str] = None,
    select: Optional[Sequence[str]] = None,
    worklist: bool = False,
    stats: Optional[Dict[str, object]] = None,
) -> List[Finding]:
    """Analyze ``paths`` (files or directories) and return sorted findings.

    ``root`` anchors relative paths in findings (defaults to the common
    parent).  ``select`` limits the run to the given rule ids; unknown ids
    raise ValueError (a CI target selecting a typoed rule must fail loudly,
    not pass vacuously).  ``worklist`` keeps suppressed findings in the
    output (marked ``suppressed`` with the justifying comment attached) —
    the machine-checked inventory mode ``--worklist`` exposes.  Pass a
    dict as ``stats`` to collect per-rule finding counts and wall time.
    """
    rules = all_rules()
    if select is not None:
        unknown = [s for s in select if s not in rules]
        if unknown:
            raise ValueError(
                f"unknown rule id(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(rules))})"
            )
        rules = {k: v for k, v in rules.items() if k in set(select)}
    if root is None:
        root = os.path.commonpath([os.path.abspath(p) for p in paths]) if paths else "."
        if os.path.isfile(root):
            root = os.path.dirname(root)
    known_ids = set(all_rules())
    file_rules = [r for r in rules.values() if r.scope == "file"]
    project_rules = [r for r in rules.values() if r.scope == "project"]
    rule_stats: Dict[str, Dict[str, float]] = {
        r.id: {"findings": 0, "time_s": 0.0} for r in rules.values()
    }
    t_start = time.perf_counter()
    findings: List[Finding] = []
    contexts: List[FileContext] = []

    def emit(r: Rule, ctx: Optional[FileContext], f: Finding) -> None:
        suppressed = ctx is not None and (
            r.id in ctx.file_disabled or r.id in ctx.line_disabled.get(f.line, ())
        )
        if suppressed:
            if not worklist:
                return
            f = _dc_replace(
                f, suppressed=True,
                justification=ctx.suppression_note(r.id, f.line),
            )
        rule_stats[r.id]["findings"] += 1
        findings.append(f)

    for path in iter_python_files(paths):
        ctx = load_file(path, root)
        if ctx is None:
            continue
        _parse_suppressions(ctx, known_ids)
        findings.extend(ctx.usage_findings)
        contexts.append(ctx)
        for r in file_rules:
            t0 = time.perf_counter()
            for f in r.fn(ctx):
                emit(r, ctx, f)
            rule_stats[r.id]["time_s"] += time.perf_counter() - t0

    build_s = 0.0
    if project_rules:
        t0 = time.perf_counter()
        pctx = ProjectContext(contexts)
        build_s = time.perf_counter() - t0
        by_rel = {c.relpath: c for c in contexts}
        for r in project_rules:
            t0 = time.perf_counter()
            for f in r.fn(pctx):
                emit(r, by_rel.get(f.path), f)
            rule_stats[r.id]["time_s"] += time.perf_counter() - t0

    # fully deterministic order: message breaks ties between two findings
    # of one rule on one line, so --json output is diff-stable
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    if stats is not None:
        stats["files"] = len(contexts)
        stats["total_s"] = time.perf_counter() - t_start
        stats["project_build_s"] = build_s
        stats["rules"] = rule_stats
    return findings


# --- shared AST helpers used by several rules --------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested Name/Attribute chains, else None."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def resolve_iterable(
    expr: ast.AST,
    names: Set[str],
    wrappers: Set[str],
    call_suffixes: Sequence[str] = (),
) -> Optional[str]:
    """The collection spelling an iterable expression resolves to, or
    None.  Sees through wrapper calls (``enumerate``/``list``/``zip``/…,
    every positional argument considered) and ``.items()``/``.values()``/
    ``.keys()`` methods; matches bare names and attribute tails against
    ``names``, and calls whose last dotted segment is in
    ``call_suffixes``.  Shared by the loop-shape rules
    (``residue-vectorized``, ``columnar-publish``) so the wrapper-peeling
    logic cannot drift between them."""
    stack = [expr]
    while stack:
        cur = stack.pop()
        while isinstance(cur, ast.Call):
            fname = dotted_name(cur.func)
            if fname in wrappers and cur.args:
                stack.extend(cur.args[1:])
                cur = cur.args[0]
                continue
            if fname is not None and fname.split(".")[-1] in call_suffixes:
                return fname
            if isinstance(cur.func, ast.Attribute) and cur.func.attr in (
                "items", "values", "keys",
            ):
                cur = cur.func.value
                continue
            cur = None
            break
        if isinstance(cur, ast.Name) and cur.id in names:
            return cur.id
        if isinstance(cur, ast.Attribute) and cur.attr in names:
            return dotted_name(cur) or cur.attr
    return None


def walk_functions(tree: ast.AST):
    """Yield every FunctionDef/AsyncFunctionDef in the tree."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def is_jit_decorated(fn: ast.AST) -> bool:
    """True for @jax.jit / @jit / @functools.partial(jax.jit, ...) etc."""
    for dec in getattr(fn, "decorator_list", []):
        name = dotted_name(dec)
        if name in ("jit", "jax.jit"):
            return True
        if isinstance(dec, ast.Call):
            cname = dotted_name(dec.func)
            if cname in ("jit", "jax.jit"):
                return True
            if cname in ("partial", "functools.partial") and dec.args:
                inner = dotted_name(dec.args[0])
                if inner in ("jit", "jax.jit"):
                    return True
    return False


_LAX_HOF = {"while_loop", "cond", "scan", "fori_loop", "switch", "map"}


def jit_roots(tree: ast.AST) -> List[ast.AST]:
    """Function defs that execute under a jax trace: jit-decorated
    functions, plus any top-level function passed by name into a
    ``lax.while_loop``/``cond``/``scan``-style higher-order call when the
    call site itself is not already inside a jit root (nested defs inside a
    jit root are covered by containment)."""
    roots = [fn for fn in walk_functions(tree) if is_jit_decorated(fn)]
    root_set = set(id(r) for r in roots)
    # functions referenced by name in lax higher-order calls
    referenced: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname and fname.split(".")[-1] in _LAX_HOF and (
                fname.startswith("lax.") or fname.startswith("jax.lax.")
                or fname.split(".")[-2:-1] == ["lax"]
            ):
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        referenced.add(arg.id)
    if referenced:
        contained = set()
        for r in roots:
            for sub in ast.walk(r):
                contained.add(id(sub))
        for fn in walk_functions(tree):
            if fn.name in referenced and id(fn) not in contained and id(fn) not in root_set:
                roots.append(fn)
                root_set.add(id(fn))
    return roots


def nodes_in_jit(tree: ast.AST) -> Set[int]:
    """id()s of every AST node that executes under a jax trace."""
    out: Set[int] = set()
    for root in jit_roots(tree):
        for sub in ast.walk(root):
            out.add(id(sub))
    return out


def ctx_nodes_in_jit(ctx: "FileContext") -> Set[int]:
    """`nodes_in_jit(ctx.tree)`, computed once per file (several rules
    need it)."""
    if "nodes_in_jit" not in ctx.cache:
        ctx.cache["nodes_in_jit"] = nodes_in_jit(ctx.tree)
    return ctx.cache["nodes_in_jit"]  # type: ignore[return-value]


# --- vtflow: the interprocedural effect core ---------------------------------
#
# A ProjectContext is the whole-package view the interprocedural rules
# consume: a module/class-resolved call graph plus per-function *effect
# summaries* computed to a fixpoint — the same propagation shape
# rules_concurrency.py uses for lock acquisitions, hoisted here so any
# rule can consume it.
#
# The effect lattice (ANALYSIS.md "vtflow interprocedural core"):
#
#   mutate   in-memory columnar/mirror store mutation (store verb call or
#            a direct write into a digested container)
#   digest   state-digest fold (any `_digest` touch)
#   append   WAL append (`.wal.append(...)` / `_wal_append`)
#   beacon   digest-beacon enqueue (`_maybe_beacon`/`stamp_beacon`/`log_beacon`)
#   ship     replication ship (`repl.log_append` — the feed queue)
#   ack      durability ack (`_commit_ack`, or a literal-2xx `_reply`)
#   lock     lock acquisition (informational; the lock rules own this)
#   global-write  mutation of a module-level mutable global
#
# Beyond the may-effect set, each summary carries the ORDER quadruple the
# wal-effect-order rule composes across calls:
#
#   mutates        the function (transitively) mutates store state
#   clears         on every non-raising path the function reaches a WAL
#                  append — a caller's pending mutation is covered
#   ends_unlogged  on some path the function returns with a mutation not
#                  yet covered by an append
#   leading_obs    (kind, line) of an observable effect (beacon/ship/ack)
#                  the function can perform BEFORE any append — calling it
#                  with a pending mutation composes an ordering violation
#
# Two structural guard exemptions keep the live tree honest without
# suppressions: a branch whose test mentions `.wal` is a CONFIGURATION
# guard (wal-less servers have no append obligation), joined
# optimistically; and a beacon under a `repl is None` test is local-only
# (it can never ship), so it is not an observable effect.
#
# Calls are atomic at the caller's granularity: a callee's internal
# exception windows are the callee's own analysis obligation.  Exception
# handlers inherit the maximum caller-level pending state of their try
# body, which is how "no exception path may ack without the append" is
# checked.
#
# Cross-function findings anchor at the line that COMPOSES the violation
# (the call site in the caller for composed findings, the effect line for
# in-function findings).  Suppression follows the anchor: a disable at
# the caller's call-site line (or its file) suppresses the composed
# finding; a disable inside the callee does not — the callee is innocent
# alone, the composition is the bug.

#: store verbs whose call on a store-ish receiver is an in-memory mutation
MUTATE_VERBS = {
    "create", "update", "update_cas", "patch", "delete",
    "apply_segment_lazy", "bulk",
}
#: digest-beacon enqueue points
BEACON_CALLS = {"_maybe_beacon", "stamp_beacon", "log_beacon"}
#: observable (externally visible) effect kinds
OBSERVABLE_EFFECTS = ("beacon", "ship", "ack")
#: containers whose content the state digest covers
DIGESTED_CONTAINERS = {"_objects", "_lazy_patch"}


def classify_call(dotted: Optional[str]) -> Optional[str]:
    """Effect kind of a call by its dotted spelling, or None."""
    if not dotted:
        return None
    parts = dotted.split(".")
    last, prefix = parts[-1], parts[:-1]
    if last == "_wal_append":
        return "append"
    if last == "append" and any("wal" in p for p in prefix):
        return "append"
    if last in BEACON_CALLS:
        return "beacon"
    if last == "log_append":
        return "ship"
    if last == "_commit_ack":
        return "ack"
    if last in MUTATE_VERBS and any(
        p in ("store", "_store") or p.endswith("store") for p in prefix
    ):
        return "mutate"
    return None


class FunctionSummary:
    """Per-function effect summary (one fixpoint variable)."""

    __slots__ = (
        "fqn", "relpath", "qualname", "name", "cls", "node",
        "effects", "mutates", "clears", "ends_unlogged", "leading_obs",
        "violations", "calls",
    )

    def __init__(self, fqn: str, relpath: str, qualname: str,
                 cls: Optional[str], node: ast.AST):
        self.fqn = fqn
        self.relpath = relpath
        self.qualname = qualname
        self.name = qualname.split(".")[-1]
        self.cls = cls  # enclosing class name or None
        self.node = node
        self.effects: Set[str] = set()
        self.mutates = False
        self.clears = False
        self.ends_unlogged = False
        self.leading_obs: Optional[Tuple[str, int]] = None
        #: (line, message) order violations found in THIS function
        self.violations: List[Tuple[int, str]] = []
        #: resolved call edges: (line, callee fqn)
        self.calls: List[Tuple[int, str]] = []

    def _key(self):
        return (frozenset(self.effects), self.mutates, self.clears,
                self.ends_unlogged, self.leading_obs)


def _mentions_wal(test: ast.AST) -> bool:
    for sub in ast.walk(test):
        if isinstance(sub, ast.Attribute) and "wal" in sub.attr:
            return True
        if isinstance(sub, ast.Name) and "wal" in sub.id:
            return True
    return False


def _repl_none_guard(test: ast.AST) -> bool:
    """True for tests of the shape ``<x>.repl is None`` (possibly inside
    a BoolOp) — a beacon under it is local-only and can never ship."""
    for sub in ast.walk(test):
        if (
            isinstance(sub, ast.Compare)
            and len(sub.ops) == 1
            and isinstance(sub.ops[0], ast.Is)
            and isinstance(sub.comparators[0], ast.Constant)
            and sub.comparators[0].value is None
        ):
            name = dotted_name(sub.left)
            if name and name.split(".")[-1] == "repl":
                return True
    return False


class _OState:
    __slots__ = ("pending", "appended", "dead")

    def __init__(self, pending=False, appended=False, dead=False):
        self.pending = pending
        self.appended = appended
        self.dead = dead

    def copy(self) -> "_OState":
        return _OState(self.pending, self.appended, self.dead)

    def join(self, other: "_OState", optimistic: bool = False) -> None:
        if other.dead:
            return
        if self.dead:
            self.pending, self.appended, self.dead = (
                other.pending, other.appended, other.dead)
            return
        if optimistic:
            self.pending = self.pending and other.pending
            self.appended = self.appended or other.appended
        else:
            self.pending = self.pending or other.pending
            self.appended = self.appended and other.appended


class _OrderWalk:
    """One pass over a function body with the current callee summaries:
    computes the order quadruple and records violations."""

    def __init__(self, summary: FunctionSummary, project: "ProjectContext"):
        self.s = summary
        self.p = project
        self.effects: Set[str] = set()
        self.mutates = False
        self.clears = True
        self.ends_unlogged = False
        self.leading_obs: Optional[Tuple[str, int]] = None
        self.violations: List[Tuple[int, str]] = []
        self.calls: List[Tuple[int, str]] = []
        #: one flag per enclosing try body: set when a statement boundary
        #: inside it passed with a pending (un-appended) mutation — the
        #: state an exception from a LATER statement would expose to the
        #: handler
        self._try_pending_flags: List[bool] = []
        #: materialization folds values the staging path already logged
        #: and digested — its container writes are representation changes,
        #: not logical mutations (same structural exemption as
        #: rules_audit)
        self._mutate_exempt = (
            summary.name.lstrip("_").startswith("materialize")
        )

    def run(self) -> None:
        st = _OState()
        self.visit_stmts(self.s.node.body, st, False)
        self.end_path(st)

    # -- path accounting ---------------------------------------------------

    def end_path(self, st: _OState) -> None:
        if st.dead:
            return
        self.ends_unlogged = self.ends_unlogged or st.pending
        self.clears = self.clears and st.appended
        st.dead = True

    def event(self, kind: str, line: int, st: _OState, exempt: bool,
              detail: str = "") -> None:
        if st.dead:
            return
        if kind == "mutate" and self._mutate_exempt:
            return
        self.effects.add(kind)
        if kind == "mutate":
            self.mutates = True
            st.pending = True
        elif kind == "append":
            st.pending = False
            st.appended = True
        elif kind in OBSERVABLE_EFFECTS:
            if kind == "beacon" and exempt:
                return
            if st.pending:
                self.violations.append((line, (
                    f"{detail or kind} effect reaches the outside world "
                    "before the WAL append covering the pending in-memory "
                    "mutation — a crash here acks/ships state the log "
                    "cannot replay (the PR-15 beacon-ordering bug class); "
                    "move the effect after the append"
                )))
            elif not st.appended and self.leading_obs is None:
                self.leading_obs = (kind, line)

    def call_event(self, line: int, summaries: List[FunctionSummary],
                   st: _OState, exempt: bool) -> None:
        if st.dead or not summaries:
            return
        leading = next((s.leading_obs for s in summaries
                        if s.leading_obs is not None), None)
        clears = all(s.clears for s in summaries)
        ends_unlogged = any(s.ends_unlogged for s in summaries)
        names = "/".join(sorted({s.qualname for s in summaries}))
        for s in summaries:
            self.effects |= s.effects
            self.calls.append((line, s.fqn))
        if leading is not None and not (leading[0] == "beacon" and exempt):
            if st.pending:
                self.violations.append((line, (
                    f"call into `{names}` performs a {leading[0]} effect "
                    "before any WAL append while this caller holds an "
                    "un-appended mutation — the composed path acks/ships "
                    "ahead of the log (cross-function effect order); "
                    "append first or hoist the effect past it"
                )))
            elif not st.appended and self.leading_obs is None:
                self.leading_obs = (leading[0], line)
        if clears and summaries:
            st.pending = False
            st.appended = True
        if ends_unlogged:
            self.mutates = True
            st.pending = True

    # -- expression walk (eval order, calls post-order) --------------------

    def visit_expr(self, node: ast.AST, st: _OState, exempt: bool) -> None:
        if node is None or st.dead:
            return
        if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # closures run later; their effects are not this path's
        if isinstance(node, ast.Attribute) and node.attr == "_digest":
            self.effects.add("digest")
        if isinstance(node, ast.Constant) and node.value == "_digest":
            self.effects.add("digest")
        if isinstance(node, ast.IfExp):
            self.visit_expr(node.test, st, exempt)
            ex_body = exempt or _repl_none_guard(node.test)
            opt = _mentions_wal(node.test)
            b, o = st.copy(), st.copy()
            self.visit_expr(node.body, b, ex_body)
            self.visit_expr(node.orelse, o, exempt)
            b.join(o, optimistic=opt)
            st.pending, st.appended, st.dead = b.pending, b.appended, b.dead
            return
        if isinstance(node, ast.Call):
            for sub in ast.iter_child_nodes(node):
                if sub is not node.func:
                    self.visit_expr(sub, st, exempt)
            # receiver expression itself may contain nested calls
            if isinstance(node.func, ast.Attribute):
                self.visit_expr(node.func.value, st, exempt)
            dotted = dotted_name(node.func)
            kind = classify_call(dotted)
            if kind is None and dotted is None and isinstance(
                    node.func, ast.Attribute):
                kind = classify_call(node.func.attr)
            if kind is None:
                kind = self._reply_ack(node)
            if kind is not None:
                detail = f"`{dotted or '?'}` ({kind})"
                self.event(kind, node.lineno, st, exempt, detail)
                # still merge callee effect SETS for reachability rules
                for s in self.p.resolve_call(self.s, node):
                    self.effects |= s.effects
                    self.calls.append((node.lineno, s.fqn))
            else:
                self.call_event(node.lineno,
                                self.p.resolve_call(self.s, node),
                                st, exempt)
            return
        for sub in ast.iter_child_nodes(node):
            self.visit_expr(sub, st, exempt)

    @staticmethod
    def _reply_ack(node: ast.Call) -> Optional[str]:
        """``self._reply(200, ...)`` / ``send_response(201)`` with a
        literal success code is an ack effect; non-literal codes are
        handled where the code is computed."""
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name in ("_reply", "send_response") and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, int) \
                    and arg.value < 400:
                return "ack"
        return None

    # -- statement walk ----------------------------------------------------

    def visit_stmts(self, body, st: _OState, exempt: bool) -> None:
        last = len(body) - 1
        for i, stmt in enumerate(body):
            if st.dead:
                return
            self.visit_stmt(stmt, st, exempt)
            # a boundary BETWEEN statements with a pending mutation is
            # what an exception from a later statement exposes to the
            # enclosing handler; a boundary after the LAST statement
            # exposes nothing new (an exception from the statement itself
            # means its mutation never happened — calls are atomic at
            # this caller's granularity)
            if i < last and st.pending and not st.dead \
                    and self._try_pending_flags:
                for j in range(len(self._try_pending_flags)):
                    self._try_pending_flags[j] = True

    def visit_stmt(self, node: ast.AST, st: _OState, exempt: bool) -> None:
        if st.dead:
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs are their own summaries
        if isinstance(node, ast.If):
            self.visit_expr(node.test, st, exempt)
            opt = _mentions_wal(node.test)
            ex_body = exempt or _repl_none_guard(node.test)
            b, o = st.copy(), st.copy()
            self.visit_stmts(node.body, b, ex_body)
            self.visit_stmts(node.orelse, o, exempt)
            b.join(o, optimistic=opt)
            st.pending, st.appended, st.dead = b.pending, b.appended, b.dead
            return
        if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            if isinstance(node, ast.While):
                self.visit_expr(node.test, st, exempt)
            else:
                self.visit_expr(node.iter, st, exempt)
            b = st.copy()
            self.visit_stmts(node.body, b, exempt)
            self.visit_stmts(node.orelse, b, exempt)
            st.join(b)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                ctx_name = dotted_name(item.context_expr)
                if ctx_name and any(
                    k in ctx_name.split(".")[-1]
                    for k in ("lock", "_mu", "cond", "_cv")
                ):
                    self.effects.add("lock")
                self.visit_expr(item.context_expr, st, exempt)
            self.visit_stmts(node.body, st, exempt)
            return
        if isinstance(node, ast.Try):
            self._try_pending_flags.append(False)
            b = st.copy()
            self.visit_stmts(node.body, b, exempt)
            self.visit_stmts(node.orelse, b, exempt)
            body_pending = self._try_pending_flags.pop()
            joined = b
            for handler in node.handlers:
                h = st.copy()
                h.pending = st.pending or body_pending
                h.appended = st.appended
                self.visit_stmts(handler.body, h, exempt)
                joined.join(h)
            self.visit_stmts(node.finalbody, joined, exempt)
            st.pending, st.appended, st.dead = (
                joined.pending, joined.appended, joined.dead)
            return
        if isinstance(node, ast.Return):
            self.visit_expr(node.value, st, exempt)
            self.end_path(st)
            return
        if isinstance(node, ast.Raise):
            self.visit_expr(node.exc, st, exempt)
            st.dead = True  # exceptional exit: the caller's handler owns it
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            # direct writes into digested containers are mutations
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            value = getattr(node, "value", None)
            if value is not None:
                self.visit_expr(value, st, exempt)
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Attribute) \
                            and sub.attr == "_digest":
                        self.effects.add("digest")
                base = t
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    if isinstance(base, ast.Attribute) \
                            and base.attr in DIGESTED_CONTAINERS:
                        if isinstance(t, ast.Subscript):
                            self.event("mutate", node.lineno, st, exempt,
                                       f"write into `{base.attr}`")
                        break
                    base = base.value
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                base = t
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    if isinstance(base, ast.Attribute) \
                            and base.attr in DIGESTED_CONTAINERS:
                        self.event("mutate", node.lineno, st, exempt,
                                   f"del from `{base.attr}`")
                        break
                    base = base.value
            return
        if isinstance(node, ast.Expr):
            self.visit_expr(node.value, st, exempt)
            return
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, ast.stmt):
                self.visit_stmt(sub, st, exempt)
            else:
                self.visit_expr(sub, st, exempt)


class ProjectContext:
    """The whole-package view: every FileContext, a class-resolved call
    graph, and per-function effect summaries computed to a fixpoint."""

    MAX_ITERATIONS = 40

    def __init__(self, contexts: Sequence[FileContext]):
        self.contexts: Dict[str, FileContext] = {
            c.relpath: c for c in contexts
        }
        #: class name -> fully-qualified "relpath::Class" (merged on dup)
        self.classes: Dict[str, Set[str]] = {}
        #: "relpath::Class" -> {method name -> fqn}
        self.methods: Dict[str, Dict[str, str]] = {}
        #: relpath -> {function name -> fqn} (module level)
        self.module_fns: Dict[str, Dict[str, str]] = {}
        #: relpath -> {imported name -> source module dotted path}
        self.imports: Dict[str, Dict[str, str]] = {}
        #: attr/bare name -> candidate class names ("store" -> {"Store"})
        self.attr_types: Dict[str, Set[str]] = {}
        #: fqn -> summary
        self.summaries: Dict[str, FunctionSummary] = {}
        #: fqn -> {param name -> class name} from annotations
        self._param_types: Dict[str, Dict[str, str]] = {}
        self._index()
        self._infer_attr_types()
        self._fixpoint()

    # -- indexing ----------------------------------------------------------

    def _index(self) -> None:
        for rel, ctx in self.contexts.items():
            tree = ctx.tree
            self.module_fns[rel] = {}
            self.imports[rel] = {}
            for node in tree.body if isinstance(tree, ast.Module) else []:
                if isinstance(node, (ast.Import, ast.ImportFrom)):
                    self._record_import(rel, node)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fqn = f"{rel}::{node.name}"
                    self.module_fns[rel][node.name] = fqn
                    self._add_summary(fqn, rel, node.name, None, node)
            # classes anywhere in the module — the request-handler class
            # defined inside StoreServer.__init__ is part of the seam
            for node in ast.walk(tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                fqcn = f"{rel}::{node.name}"
                self.classes.setdefault(node.name, set()).add(fqcn)
                self.methods.setdefault(fqcn, {})
                for item in node.body:  # direct methods only; a def
                    # nested inside a method is a closure, not a method
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        qual = f"{node.name}.{item.name}"
                        fqn = f"{rel}::{qual}"
                        if item.name not in self.methods[fqcn]:
                            self.methods[fqcn][item.name] = fqn
                            self._add_summary(fqn, rel, qual,
                                              node.name, item)

    def _add_summary(self, fqn, rel, qual, cls, node) -> None:
        s = FunctionSummary(fqn, rel, qual, cls, node)
        self.summaries[fqn] = s
        ptypes: Dict[str, str] = {}
        args = node.args
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            ann = a.annotation
            cname = None
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                cname = ann.value.strip("'\" ")
            elif ann is not None:
                cname = dotted_name(ann)
            if cname:
                # keep even names not yet indexed — forward refs resolve
                # against the finished class map at query time
                ptypes[a.arg] = cname.split(".")[-1].split("[")[0]
        self._param_types[fqn] = ptypes

    def _record_import(self, rel: str, node: ast.AST) -> None:
        if isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                self.imports[rel][alias.asname or alias.name] = node.module

    def _infer_attr_types(self) -> None:
        """attr/name -> candidate classes, from `x.attr = Class(...)`,
        `name = Class(...)`, `name = self` (handler-closure pattern), and
        `self.attr = <annotated param>`."""
        for rel, ctx in self.contexts.items():
            cls_stack: List[Optional[str]] = []

            def walk(node, cls, fn_fqn):
                for sub in ast.iter_child_nodes(node):
                    sub_cls, sub_fqn = cls, fn_fqn
                    if isinstance(sub, ast.ClassDef):
                        sub_cls = sub.name
                    elif isinstance(sub, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        qual = f"{cls}.{sub.name}" if cls else sub.name
                        sub_fqn = f"{rel}::{qual}"
                    if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                        self._note_assign(sub, cls, sub_fqn)
                    walk(sub, sub_cls, sub_fqn)

            walk(ctx.tree, None, None)

    def _note_assign(self, node: ast.Assign, cls: Optional[str],
                     fn_fqn: Optional[str]) -> None:
        tgt = node.targets[0]
        name = None
        if isinstance(tgt, ast.Attribute):
            name = tgt.attr
        elif isinstance(tgt, ast.Name):
            name = tgt.id
        if name is None:
            return

        def note(cname: Optional[str]):
            if cname and cname in self.classes:
                self.attr_types.setdefault(name, set()).add(cname)

        # peel `a or b` — `self.store = store or Store()`
        values = [node.value]
        if isinstance(node.value, ast.BoolOp):
            values = list(node.value.values)
        for v in values:
            if isinstance(v, ast.Call):
                cname = dotted_name(v.func)
                note(cname.split(".")[-1] if cname else None)
            elif isinstance(v, ast.Name):
                if v.id == "self" and cls is not None:
                    note(cls)
                elif fn_fqn is not None:
                    note(self._param_types.get(fn_fqn, {}).get(v.id))

    # -- call resolution ---------------------------------------------------

    def resolve_call(self, caller: FunctionSummary,
                     node: ast.Call) -> List[FunctionSummary]:
        """Candidate callee summaries for a call site (empty when the
        callee is outside the project or unresolvable)."""
        f = node.func
        rel = caller.relpath
        out: List[str] = []
        if isinstance(f, ast.Name):
            fqn = self.module_fns.get(rel, {}).get(f.id)
            if fqn:
                out.append(fqn)
            elif f.id in self.imports.get(rel, {}):
                out.extend(self._imported(rel, f.id))
            elif f.id in self.classes:
                for fqcn in self.classes[f.id]:
                    init = self.methods.get(fqcn, {}).get("__init__")
                    if init:
                        out.append(init)
        elif isinstance(f, ast.Attribute):
            meth = f.attr
            base = f.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and caller.cls is not None:
                fqcn = f"{caller.relpath}::{caller.cls}"
                fqn = self.methods.get(fqcn, {}).get(meth)
                if fqn:
                    out.append(fqn)
                elif meth in self.module_fns.get(rel, {}):
                    pass  # self.x never resolves to a module function
            else:
                tail = None
                dn = dotted_name(base)
                if dn is not None:
                    tail = dn.split(".")[-1]
                cands: Set[str] = set()
                if tail is not None:
                    ptype = self._param_types.get(caller.fqn, {}).get(tail)
                    if ptype and ptype in self.classes:
                        cands |= {c for c in self.classes[ptype]}
                    for cname in self.attr_types.get(tail, ()):
                        cands |= self.classes.get(cname, set())
                for fqcn in cands:
                    fqn = self.methods.get(fqcn, {}).get(meth)
                    if fqn:
                        out.append(fqn)
        seen: Set[str] = set()
        res = []
        for fqn in out:
            if fqn not in seen and fqn in self.summaries:
                seen.add(fqn)
                res.append(self.summaries[fqn])
        return res

    def _imported(self, rel: str, name: str) -> List[str]:
        module = self.imports[rel][name]
        suffix = module.replace(".", "/") + ".py"
        for other_rel in self.contexts:
            trimmed = other_rel[:-3] if other_rel.endswith(".py") else other_rel
            if suffix.endswith(trimmed + ".py") or suffix == other_rel \
                    or module.replace(".", "/").endswith(trimmed):
                fqn = self.module_fns.get(other_rel, {}).get(name)
                if fqn:
                    return [fqn]
        return []

    # -- the summary fixpoint ----------------------------------------------

    def _fixpoint(self) -> None:
        for _ in range(self.MAX_ITERATIONS):
            changed = False
            for s in self.summaries.values():
                walk = _OrderWalk(s, self)
                walk.run()
                key_before = s._key()
                s.effects = walk.effects
                s.mutates = walk.mutates
                s.clears = walk.clears and "append" in walk.effects
                s.ends_unlogged = walk.ends_unlogged
                s.leading_obs = walk.leading_obs
                s.violations = walk.violations
                s.calls = walk.calls
                if s._key() != key_before:
                    changed = True
            if not changed:
                break

    # -- graph queries for rules -------------------------------------------

    def reachable_from(self, roots: Iterable[str]) -> Set[str]:
        """fqns reachable from the given root fqns over resolved calls."""
        seen: Set[str] = set()
        frontier = [r for r in roots if r in self.summaries]
        while frontier:
            cur = frontier.pop()
            if cur in seen:
                continue
            seen.add(cur)
            for _line, callee in self.summaries[cur].calls:
                if callee not in seen:
                    frontier.append(callee)
        return seen

    def functions_in(self, relpath: str) -> List[FunctionSummary]:
        return sorted(
            (s for s in self.summaries.values() if s.relpath == relpath),
            key=lambda s: s.node.lineno,
        )

    def finding(self, rule_id: str, summary: FunctionSummary, line: int,
                message: str) -> Finding:
        return Finding(rule_id, summary.relpath, int(line), message)
