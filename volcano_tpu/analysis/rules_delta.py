"""delta-discipline: snapshot columns are patched, never poked.

The vtdelta micro-cycle contract (scheduler/delta/, ANALYSIS.md) hangs
on one invariant: a snapshot leaving the delta engine is bit-for-bit
what a fresh full build would have produced on the same mirror state,
modulo the admission filter — and the ONLY sanctioned way the delta
modules rewrite snapshot columns is the ``patch_*`` API
(``incremental.patch_task_planes``), which keeps the jit shape buckets
pinned and the aux row maps coherent.  An ad-hoc ``snap.task_req[...] =
...`` elsewhere in the package silently breaks the snapshot-incremental
oracle's coverage (the oracle compares builds, not later mutations) and
can re-bucket a plane shape mid-steady-state, tripping the vtprof
recompile sentinel.

The rule fences the package set (``scheduler/delta/``): any assignment
— plain, augmented, or in-place subscript — whose target drills into an
attribute of a snapshot-named binding (``snap``, ``snapshot``,
``*_snap``, ``snap_*``) fires unless it happens inside a ``patch_*``
function (the sanctioned API's own body).  Reads never fire.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from volcano_tpu.analysis.core import FileContext, Finding, rule

_SCOPED_FRAGMENT = "scheduler/delta/"


def _snapshot_root(expr: ast.AST) -> Optional[str]:
    """The snapshot-named binding an assignment target drills into, or
    None.  Peels subscripts: ``snap.task_req[:5]`` -> attribute
    ``task_req`` on name ``snap``."""
    cur = expr
    while isinstance(cur, ast.Subscript):
        cur = cur.value
    if not isinstance(cur, ast.Attribute):
        return None
    base = cur.value
    if not isinstance(base, ast.Name):
        return None
    n = base.id
    if (
        n in ("snap", "snapshot")
        or n.startswith("snap_")
        or n.endswith("_snap")
        or n.endswith("snapshot")
    ):
        return f"{n}.{cur.attr}"
    return None


def _enclosing_patch_fn(stack) -> bool:
    return any(
        isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
        and f.name.startswith("patch_")
        for f in stack
    )


@rule(
    "delta-discipline",
    "snapshot-column write in a scheduler/delta/ module outside the "
    "sanctioned patch API (`patch_*`, incremental.patch_task_planes) — "
    "mutations after the build escape the snapshot-incremental parity "
    "oracle and can re-bucket a jit plane shape mid-steady-state "
    "(vtprof recompile sentinel); route the write through the patch "
    "API, or name the invariant that makes it build-equivalent in a "
    "suppression",
)
def check_delta_discipline(ctx: FileContext) -> Iterable[Finding]:
    if _SCOPED_FRAGMENT not in ctx.relpath:
        return

    def walk(node: ast.AST, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from walk(child, stack + [child])
                continue
            if isinstance(child, (ast.Assign, ast.AugAssign)):
                targets = (
                    child.targets if isinstance(child, ast.Assign)
                    else [child.target]
                )
                for tgt in targets:
                    root = _snapshot_root(tgt)
                    if root is not None and not _enclosing_patch_fn(stack):
                        yield ctx.finding(
                            "delta-discipline",
                            child,
                            f"direct snapshot-column write `{root}` "
                            "outside the sanctioned patch API — the "
                            "snapshot-incremental oracle compares "
                            "BUILDS, so a post-build poke silently "
                            "escapes parity coverage; route it through "
                            "`patch_task_planes` (or a `patch_*` "
                            "helper beside it)",
                        )
            yield from walk(child, stack)

    yield from walk(ctx.tree, [])
