"""Span discipline for the vtrace runtime (volcano_tpu/trace.py).

Two invariants keep the tracing layer placement-neutral and crash-safe:

* **Spans are scoped, not paired.**  A span opened with ``with
  span(...):`` is recorded even when the body raises, and the ambient
  context always unwinds.  A manual begin/end pair (calling ``span(...)``
  outside a ``with`` item, entering it by hand, or calling a
  ``begin_span``/``end_span`` method) leaks the context on any exception
  — every later span in the thread silently joins the wrong trace.
* **No clock reads under a jax trace.**  ``time.*`` inside a jit-traced
  body executes once at trace time and bakes a constant into the
  compiled kernel — the span would "measure" compilation, not execution,
  and the timing call itself can force a host sync.  Trace-aware modules
  (anything importing ``volcano_tpu.trace``) must time device work only
  at block-until-ready boundaries, outside jit roots.  The generic
  hot-path rules stay the enforcers for kernels; this rule closes the
  gap for instrumentation added to modules they don't scope.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from volcano_tpu.analysis.core import (
    FileContext,
    Finding,
    ctx_nodes_in_jit,
    dotted_name,
    rule,
)

#: call names that open a span (the factory and its qualified forms)
_SPAN_CALLS = {"span", "trace.span", "volcano_tpu.trace.span"}
#: manual pairing methods — must not exist, with or without a with
_MANUAL_ATTRS = {"begin_span", "end_span"}


def _imports_trace(ctx: FileContext) -> bool:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            if any(a.name == "volcano_tpu.trace" for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module == "volcano_tpu.trace":
                return True
            if node.module == "volcano_tpu" and any(
                a.name == "trace" for a in node.names
            ):
                return True
    return False


def _with_context_calls(tree: ast.AST) -> Set[int]:
    """id()s of Call nodes that are directly a with-item's context
    expression (``with span(...):`` / ``with span(...) as s:``)."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    out.add(id(item.context_expr))
    return out


@rule(
    "trace-span-discipline",
    "spans must be opened via `with span(...)` (no manual begin/end "
    "pairs) and trace-aware modules may not read time.* or open spans "
    "inside jit-traced bodies",
)
def check_trace_span_discipline(ctx: FileContext) -> Iterable[Finding]:
    with_calls = _with_context_calls(ctx.tree)
    in_jit = ctx_nodes_in_jit(ctx)
    trace_module = _imports_trace(ctx) or ctx.basename == "trace.py"
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        leaf = name.split(".")[-1]
        if leaf in _MANUAL_ATTRS:
            yield ctx.finding(
                "trace-span-discipline",
                node,
                f"manual span pairing via {leaf}() leaks the trace "
                "context on exceptions — open spans with `with "
                "span(...)` only",
            )
            continue
        if name in _SPAN_CALLS:
            if id(node) in in_jit:
                yield ctx.finding(
                    "trace-span-discipline",
                    node,
                    "span opened inside a jit-traced body: it would time "
                    "trace-time, not execution — instrument at the "
                    "block-until-ready boundary outside the jit root",
                )
            elif id(node) not in with_calls:
                yield ctx.finding(
                    "trace-span-discipline",
                    node,
                    "span(...) result not used as a `with` context: a "
                    "raised exception would leak the span and its "
                    "ambient context — write `with span(...):`",
                )
            continue
        if (
            trace_module
            and name.startswith("time.")
            and id(node) in in_jit
        ):
            yield ctx.finding(
                "trace-span-discipline",
                node,
                f"{name}() inside a jit-traced body of a trace-aware "
                "module: the read happens once at trace time (and can "
                "force a host sync) — time device work only at "
                "block-until-ready boundaries",
            )
