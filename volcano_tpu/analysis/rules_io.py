"""crash-safe-io: state-file writes in the store must be crash-atomic.

The store's durability layer (PR 7) promises that a process killed at ANY
instant leaves either the old state file or the new one — never a torn
half-written JSON that recovery chokes on.  The protocol is the standard
one: write to a temp path, ``os.fsync`` the descriptor, then
``os.replace`` onto the real path (the WAL's own segment files are
append-only with per-record CRCs, a different protocol, and are exempt by
mode).  This rule fences the regression in the store persistence modules
(``volcano_tpu/store/``): a bare ``open(path, "w")`` in a function that
never fsyncs or never atomically renames is a silent crash-consistency
hole — exactly the shape ``flush_state`` had before the WAL PR fixed it.

Scope is the enclosing function: the write, its fsync, and its rename
belong together (that is the protocol), so a helper that only opens is
flagged until it carries the whole discipline or a justified line
suppression.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from volcano_tpu.analysis.core import (
    FileContext,
    Finding,
    dotted_name,
    rule,
    walk_functions,
)


def _open_write_mode(call: ast.Call) -> Optional[str]:
    """The literal mode string when ``call`` is a truncating file write
    (``open(..., "w"/"wb"/...)``), else None.  Append/read modes and
    non-literal modes stay quiet — the rule targets bare state rewrites."""
    if not (isinstance(call.func, ast.Name) and call.func.id == "open"):
        return None
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if isinstance(mode, str) and mode.startswith("w"):
        return mode
    return None


def _call_tails(node: ast.AST, exclude=None) -> set:
    """Last dotted segments of every call in ``node``'s subtree.
    ``exclude`` (node-id set) drops subtrees — the module scope must not
    be excused by an fsync/replace living inside some function's body."""
    tails = set()
    for sub in ast.walk(node):
        if exclude is not None and id(sub) in exclude:
            continue
        if isinstance(sub, ast.Call):
            name = dotted_name(sub.func)
            if name:
                tails.add(name.split(".")[-1])
    return tails


@rule(
    "crash-safe-io",
    "bare open(..., 'w') state write in a store persistence module without "
    "the write-temp -> os.fsync -> os.replace protocol in the same "
    "function — a crash mid-write leaves a torn state file recovery "
    "cannot parse; fsync+atomically-rename (flush_state is the model), or "
    "suppress with the justification on the line",
)
def check_crash_safe_io(ctx: FileContext) -> Iterable[Finding]:
    if "store" not in ctx.dir_parts:
        return
    fns = list(walk_functions(ctx.tree))
    in_fn = set()
    for fn in fns:
        for sub in ast.walk(fn):
            in_fn.add(id(sub))
    for scope in fns + [ctx.tree]:
        tails = None
        for sub in ast.walk(scope):
            if scope is ctx.tree and id(sub) in in_fn:
                continue  # module scope covers only top-level statements
            if not isinstance(sub, ast.Call):
                continue
            mode = _open_write_mode(sub)
            if mode is None:
                continue
            if tails is None:
                tails = _call_tails(
                    scope, exclude=in_fn if scope is ctx.tree else None)
            missing = []
            if "fsync" not in tails:
                missing.append("os.fsync")
            if not ({"replace", "rename"} & tails):
                missing.append("os.replace")
            if not missing:
                continue
            human = " and ".join(missing)
            yield ctx.finding(
                "crash-safe-io",
                sub,
                f"open(..., {mode!r}) state write without {human} in the "
                "same function: a crash mid-write tears the file — use "
                "write-temp -> fsync -> atomic-rename",
            )
