"""Silent-fallback discipline: no `except Exception: pass` on the hot path.

The scheduler/store/api layers are the scheduling hot path: a swallowed
exception there silently converts a correctness bug into a scheduling
anomaly (a task that never binds, a queue that never drains) with no
err_log entry, no event, no metric.  The project convention for the few
legitimate broad catches (wire boundaries, per-op isolation in bulk verbs)
is to HANDLE the error — record it, return it, count it — and tag the
handler `# noqa: BLE001`; a body of just `pass`/`continue`/`...` is never
acceptable in these trees.
"""

from __future__ import annotations

import ast
from typing import Iterable

from volcano_tpu.analysis.core import FileContext, Finding, rule

#: directory prefixes under the package root that count as hot path
_HOT_PREFIXES = ("scheduler", "store", "api", "parallel")


def _in_scope(ctx: FileContext) -> bool:
    parts = ctx.relpath.split("/")
    if "volcano_tpu" in parts:
        parts = parts[parts.index("volcano_tpu") + 1:]
    return bool(parts) and parts[0] in _HOT_PREFIXES


def _is_silent(body) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass) or isinstance(stmt, ast.Continue):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / Ellipsis
        return False
    return True


@rule(
    "bare-except",
    "`except [Exception]: pass` on the scheduling hot path swallows "
    "correctness bugs silently — record, return, or count the error",
)
def check_bare_except(ctx: FileContext) -> Iterable[Finding]:
    if not _in_scope(ctx):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is not None:
            tname = node.type.attr if isinstance(node.type, ast.Attribute) \
                else getattr(node.type, "id", None)
            if tname not in ("Exception", "BaseException"):
                continue
        if _is_silent(node.body):
            what = "bare except" if node.type is None else "except Exception"
            yield ctx.finding(
                "bare-except",
                node,
                f"{what} with a silent body on the scheduling hot path — "
                "at minimum record to the cache err_log or an Event",
            )
