"""Compatibility surface: the lock-order sanitizer is documented with the
analysis toolkit, but it is RUNTIME code (pure os/threading) imported by
the store/server/applier/native modules — so it lives at
``volcano_tpu.locksan``, outside the lint framework's import graph (a
broken rule module must never take down the production daemons).  This
shim keeps the ``volcano_tpu.analysis.locksan`` name working."""

from volcano_tpu.locksan import (  # noqa: F401
    ENV_FLAG,
    LockOrderError,
    enabled,
    make_condition,
    make_lock,
    make_rlock,
    reset_graph,
)
