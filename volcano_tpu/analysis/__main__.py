"""CLI entry: ``python -m volcano_tpu.analysis``.

Exit status: 0 when the analyzed tree is clean, 1 when findings exist,
2 on usage errors.  ``--json`` emits a machine-readable report (used by
``make lint`` and the tier-1 test); the default output is one
``path:line: rule: message`` line per finding, grep/editor friendly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from volcano_tpu.analysis.core import all_rules, run_paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m volcano_tpu.analysis",
        description="vtlint: project-native static analysis for volcano-tpu",
    )
    ap.add_argument("paths", nargs="*",
                    help="files or directories to analyze "
                         "(default: ./volcano_tpu)")
    ap.add_argument("--root", default=None,
                    help="root for relative paths in findings "
                         "(default: common parent of the inputs)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="JSON report on stdout")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--stats", action="store_true",
                    help="per-rule finding counts and wall time (stderr "
                         "table, or a \"stats\" key with --json)")
    ap.add_argument("--worklist", action="store_true",
                    help="keep suppressed findings in the output, marked "
                         "suppressed with the justifying comment attached "
                         "— the machine-checked deferred-work inventory "
                         "(suppressed-only findings do not fail the run)")
    ns = ap.parse_args(argv)

    rules = all_rules()
    if ns.list_rules:
        if ns.as_json:
            print(json.dumps(
                {rid: r.description for rid, r in sorted(rules.items())},
                indent=2))
        else:
            for rid in sorted(rules):
                print(f"{rid}: {rules[rid].description}")
        return 0

    paths = ns.paths or [os.path.join(os.getcwd(), "volcano_tpu")]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"vtlint: no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2
    select = [s.strip() for s in ns.select.split(",")] if ns.select else None
    stats = {} if ns.stats else None
    try:
        findings = run_paths(paths, root=ns.root, select=select,
                             worklist=ns.worklist, stats=stats)
    except ValueError as e:
        print(f"vtlint: {e}", file=sys.stderr)
        return 2

    live = [f for f in findings if not f.suppressed]
    if ns.as_json:
        report = {
            "findings": [f.as_dict() for f in findings],
            "count": len(findings),
            "rules": sorted(rules if select is None else select),
        }
        if ns.worklist:
            report["live_count"] = len(live)
            report["suppressed_count"] = len(findings) - len(live)
        if stats is not None:
            report["stats"] = stats
        print(json.dumps(report, indent=2))
    else:
        for f in findings:
            print(f.human())
        n_rules = len(rules if select is None else select)
        print(f"vtlint: {len(findings)} finding(s) "
              f"({n_rules} rule(s) active)",
              file=sys.stderr)
        if stats is not None:
            print(f"vtlint: {stats['files']} file(s) in "
                  f"{stats['total_s']:.2f}s (project context: "
                  f"{stats['project_build_s']:.2f}s)", file=sys.stderr)
            rows = sorted(
                stats["rules"].items(),
                key=lambda kv: (-kv[1]["time_s"], kv[0]),
            )
            for rid, row in rows:
                print(f"vtlint:   {rid:<24} {row['findings']:>4} "
                      f"finding(s)  {row['time_s']*1000:8.1f} ms",
                      file=sys.stderr)
    # suppressed findings are inventory, not failures: --worklist on a
    # tree whose only findings are justified suppressions still exits 0
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())
