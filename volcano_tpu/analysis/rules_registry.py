"""Session registry completeness for plugins.

`Session` (scheduler/session.py) holds 11 callback registries plus
`add_event_handler`/`add_tensor_fn`; tier dispatch looks callbacks up BY
PLUGIN NAME from the conf tiers (session.py `_ordered`).  Two silent
failure modes follow:

* a typoed registration method (``ssn.add_job_oder_fn``) raises only when
  the plugin first opens a session — or never, if the path is cold;
* a registration under a name other than the plugin's own ``name`` is
  dead: ``_ordered`` will never find it for this plugin's tier entry.

This rule validates every ``ssn.add_*``/``session.add_*`` call against the
real `Session` class (parsed from source, so the rule can never drift from
the code), and checks that registrations made inside a Plugin class pass
``self.name`` (or the literal class ``name``) as the registration name.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, Optional, Set

from volcano_tpu.analysis.core import FileContext, Finding, rule

_RECEIVERS = {"ssn", "session"}

_session_names_cache: Optional[Set[str]] = None


def _session_registration_names() -> Set[str]:
    """The `add_*` method names defined on the real Session class, parsed
    from its SOURCE — located relative to this package, never imported, so
    the analyzer executes no scheduler code and the set cannot drift from
    the file on disk."""
    global _session_names_cache
    if _session_names_cache is not None:
        return _session_names_cache
    names: Set[str] = set()
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scheduler", "session.py",
    )
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read())
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name == "Session":
                for item in node.body:
                    if isinstance(item, ast.FunctionDef) and item.name.startswith("add_"):
                        names.add(item.name)
    except (OSError, SyntaxError):
        pass
    if not names:
        # source not on disk (zip/bundled install): fall back to the known
        # registry set rather than accepting everything or flooding
        # findings against nothing
        names = {
            "add_job_order_fn", "add_queue_order_fn", "add_task_order_fn",
            "add_predicate_fn", "add_node_order_fn", "add_preemptable_fn",
            "add_reclaimable_fn", "add_overused_fn", "add_job_ready_fn",
            "add_job_pipelined_fn", "add_job_valid_fn",
            "add_event_handler", "add_tensor_fn",
        }
    _session_names_cache = names
    return names


def _class_name_attr(cls: ast.ClassDef) -> Optional[str]:
    for item in cls.body:
        if isinstance(item, ast.Assign) and len(item.targets) == 1 \
                and isinstance(item.targets[0], ast.Name) \
                and item.targets[0].id == "name" \
                and isinstance(item.value, ast.Constant):
            return item.value.value
    return None


def _is_plugin_class(cls: ast.ClassDef) -> bool:
    for b in cls.bases:
        base = b.attr if isinstance(b, ast.Attribute) else getattr(b, "id", "")
        if base == "Plugin":
            return True
    return False


def _name_arg_ok(arg: ast.AST, class_name_value: Optional[str]) -> bool:
    if isinstance(arg, ast.Attribute) and arg.attr == "name" \
            and isinstance(arg.value, ast.Name) and arg.value.id == "self":
        return True
    if isinstance(arg, ast.Constant) and class_name_value is not None \
            and arg.value == class_name_value:
        return True
    return False


@rule(
    "session-registry",
    "plugin registrations must target real Session registries and "
    "register under the plugin's own name (tier dispatch is name-keyed)",
)
def check_session_registry(ctx: FileContext) -> Iterable[Finding]:
    valid = _session_registration_names()

    # the class each node belongs to (for the self.name check)
    plugin_classes = [
        node for node in ast.walk(ctx.tree)
        if isinstance(node, ast.ClassDef) and _is_plugin_class(node)
    ]
    in_plugin = {}
    for cls in plugin_classes:
        cname = _class_name_attr(cls)
        for sub in ast.walk(cls):
            in_plugin[id(sub)] = (cls.name, cname)
        if cname is None:
            yield ctx.finding(
                "session-registry",
                cls,
                f"Plugin subclass {cls.name} has no literal `name` class "
                "attribute — conf tiers cannot enable it and registrations "
                "cannot be dispatched",
            )

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Attribute):
            continue
        recv = node.func.value
        if not (isinstance(recv, ast.Name) and recv.id in _RECEIVERS):
            continue
        method = node.func.attr
        if not method.startswith("add_"):
            continue
        if method not in valid:
            yield ctx.finding(
                "session-registry",
                node,
                f"{recv.id}.{method}(...) does not match any Session "
                f"registry (known: {', '.join(sorted(valid))}) — the "
                "registration would raise AttributeError at session open",
            )
            continue
        cls_info = in_plugin.get(id(node))
        if cls_info is None:
            continue  # registrations outside Plugin classes: name check n/a
        cls_name, cname = cls_info
        # which positional argument carries the registration name
        name_idx = None
        if method == "add_tensor_fn":
            name_idx = 1  # (kind, name, fn)
        elif method.endswith("_fn"):
            name_idx = 0  # (name, fn)
        if name_idx is None or len(node.args) <= name_idx:
            continue
        if not _name_arg_ok(node.args[name_idx], cname):
            yield ctx.finding(
                "session-registry",
                node,
                f"{cls_name} registers {method} under a name other than "
                "self.name — tier dispatch is keyed by the plugin's conf "
                "name, so this callback would never fire for this plugin",
            )
