"""wal-effect-order: mutation reaches the WAL before the world hears of it.

The PR-15 bug class, made a permanent invariant: on every path from a
store verb or a replica apply, the in-memory mutation must reach the WAL
append **before** any observable effect — a digest beacon enqueue, a
replication ship, or an HTTP durability ack.  A beacon shipped (or a 200
acked) while the covering WAL record does not exist yet is a promise the
log cannot replay after a crash: followers verify a digest the leader
never durably had, clients retry a write the store already acked.

The check is interprocedural (the vtflow core in ``core.py``): per-
function effect summaries composed across resolved calls, so both
in-function reorders (beacon stamped between the store verb and
``_wal_append``) and composed ones (a verb path calling into a helper
whose first observable effect precedes any append) are caught.  Two
structural exemptions keep the live tree clean without suppressions:

* a branch guarded on ``.wal`` is configuration, not ordering — a
  wal-less server has no append obligation, so the join across that
  branch is optimistic;
* a beacon under a ``repl is None`` guard is local-only — it can never
  ship, so it is not an observable effect (this is exactly the PR-15
  *fix* shape, which must stay legal).

Exception paths are covered by try-handler accounting: a handler
inherits the maximum caller-level pending state of its try body, so "no
exception path may ack without the append" falls out of the same walk.
Calls are atomic at the caller's granularity — a callee's internal
exception windows are the callee's own obligation (its summary is
computed from its own body).

Composed findings anchor at the CALL SITE in the caller — the line that
composes the violation — and suppression follows the anchor: a disable
at the callee's effect line does not suppress the caller-site finding.
"""

from __future__ import annotations

from typing import Iterable, List

from volcano_tpu.analysis.core import (
    Finding,
    ProjectContext,
    rule,
)

#: the write-path seam: store verbs, replica apply, scheduler apply
_SCOPED_BASENAMES = {
    "server.py", "store.py", "replica.py", "partition.py", "apply.py",
}
_SCOPED_DIRS = {"store", "scheduler"}


def _in_scope(relpath: str) -> bool:
    parts = relpath.split("/")
    return parts[-1] in _SCOPED_BASENAMES and any(
        p in _SCOPED_DIRS for p in parts[:-1]
    )


@rule(
    "wal-effect-order",
    "observable effect (beacon enqueue / replication ship / HTTP ack) "
    "reachable before the WAL append covering a pending in-memory "
    "mutation, on some path from a store verb or replica apply — a crash "
    "in the window acks or ships state the log cannot replay (the PR-15 "
    "beacon-ordering bug class); move the effect after `_wal_append`, or "
    "guard it on `repl is None` if it is genuinely local-only",
    scope="project",
)
def check_wal_effect_order(pctx: ProjectContext) -> Iterable[Finding]:
    out: List[Finding] = []
    for rel in sorted(pctx.contexts):
        if not _in_scope(rel):
            continue
        for summary in pctx.functions_in(rel):
            for line, message in summary.violations:
                out.append(pctx.finding(
                    "wal-effect-order", summary, line,
                    f"in `{summary.qualname}`: {message}",
                ))
    return out
