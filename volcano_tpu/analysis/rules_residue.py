"""residue-vectorized: the host-residue cliff must not regress.

BASELINE.md r5 measured the old residue sub-cycle at ~0.13 s/task — a
per-task Python scan over every node (64.6 s for 500 volume-constrained
tasks at 10k nodes).  r6 replaced it with the vectorized engine
(scheduler/residue.py: one batched numpy step per task) and the device
volume solve; this rule keeps the cliff from silently coming back.

In the residue module set (``residue.py``, ``tensor_actions.py``) a
``for`` loop over a node collection (``nodes``/``all_nodes``/
``node_list``/``feasible``/``ssn.nodes``/``get_node_list(...)`` —
including through ``enumerate``/``list``/``sorted`` wrappers) may appear
only at loop-nesting depth zero: a single O(N) sweep (mask building,
array assembly) is the vectorized engine's amortized setup, but the same
loop nested inside ANY enclosing ``for``/``while`` is the per-task node
scan — O(tasks x nodes) interpreter time on the path whose entire reason
to exist is not paying it.  The oracle per-task loop lives in
``actions/allocate.py``, deliberately outside this set: parity suites
need an unvectorized reference to measure against.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from volcano_tpu.analysis.core import (
    FileContext,
    Finding,
    resolve_iterable,
    rule,
)

_SCOPED_BASENAMES = {"residue.py", "tensor_actions.py"}

_NODEISH_NAMES = {"nodes", "all_nodes", "node_list", "feasible",
                  "feasible_nodes"}
_WRAPPERS = {"enumerate", "list", "sorted", "reversed", "tuple"}


def _nodeish(expr: ast.AST) -> Optional[str]:
    """The node-collection spelling an iterable expression resolves to,
    or None (core.resolve_iterable with this rule's name/wrapper sets;
    ``get_node_list(...)`` calls match by suffix)."""
    return resolve_iterable(expr, _NODEISH_NAMES, _WRAPPERS,
                            ("get_node_list",))


@rule(
    "residue-vectorized",
    "per-task `for ... in nodes` Python loop in the residue/tensor-action "
    "module set — the O(tasks x nodes) host-residue cliff (0.13 s/task at "
    "10k nodes, BASELINE.md r5) these modules exist to eliminate; "
    "vectorize over the node axis or hoist the sweep to depth zero",
)
def check_residue_vectorized(ctx: FileContext) -> Iterable[Finding]:
    if ctx.basename not in _SCOPED_BASENAMES:
        return
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # walk this function's own statements (nested defs get their own
        # visit), tracking loop depth: a node-ish For at depth > 0 is the
        # per-task scan
        nested = {
            id(sub)
            for f in ast.walk(fn)
            if f is not fn
            and isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
            for sub in ast.walk(f)
        }

        def visit(node: ast.AST, depth: int):
            for child in ast.iter_child_nodes(node):
                if id(child) in nested:
                    continue
                if isinstance(child, (ast.For, ast.AsyncFor)):
                    spelled = _nodeish(child.iter)
                    if spelled is not None and depth > 0:
                        yield ctx.finding(
                            "residue-vectorized",
                            child,
                            f"loop over {spelled!r} nested inside another "
                            "loop: this is the per-task node scan the "
                            "vectorized residue engine replaces — batch "
                            "the node axis with numpy instead",
                        )
                    yield from visit(child, depth + 1)
                elif isinstance(child, ast.While):
                    yield from visit(child, depth + 1)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                else:
                    yield from visit(child, depth)

        yield from visit(fn, 0)
