"""columnar-publish: the publish/drain path must stay columnar.

r6 made the cycle's output ONE columnar segment end to end
(store/segment.py): binds/evicts/Events ride parallel columns over
interned string tables, the server applies them lazily under one lock,
and the watch log holds block references instead of per-object
encodings.  That deleted the 14.9 s cfg7 drain (BASELINE.md r5) whose
cost was per-object ``encode(...)`` dict loops.  This rule fences the
regression: in the wire module set (``scheduler/apply.py``,
``store/client.py``, ``store/server.py``, ``store/segment.py``) a call
to ``encode``/``encode_fields``/``encode_object``/``json.dumps`` may
not sit inside a loop or comprehension over a decision/op collection
(``ops``/``binds``/``evicts``/``events``/``keys``/``items``/...) —
that is the per-object wire encode the columnar path exists to avoid.

The generic per-op verbs that legitimately survive for NON-decision
traffic (client ``bulk``'s object encode, the state-flush fallback)
carry explicit line suppressions with their justification — new
per-object encode loops must either go columnar or argue their case in
review the same way.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from volcano_tpu.analysis.core import (
    FileContext,
    Finding,
    dotted_name,
    resolve_iterable,
    rule,
)

_SCOPED_SUFFIXES = (
    "scheduler/apply.py",
    "store/client.py",
    "store/server.py",
    "store/segment.py",
)

#: iterable spellings that mean "one element per decision/op/object"
_PLURAL_NAMES = {
    "ops", "wire", "binds", "evicts", "events", "ev_ops", "batch",
    "items", "keys", "rows", "decisions", "objs", "pods",
}
_WRAPPERS = {"enumerate", "list", "sorted", "reversed", "tuple", "zip"}
_ENCODERS = {"encode", "encode_fields", "encode_object", "json.dumps",
             "dumps"}


def _pluralish(expr: ast.AST) -> Optional[str]:
    """The decision-plural spelling an iterable resolves to, or None
    (core.resolve_iterable with the wire rule's name/wrapper sets)."""
    return resolve_iterable(expr, _PLURAL_NAMES, _WRAPPERS)


def _encoder_calls(node: ast.AST) -> Iterable[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fname = dotted_name(sub.func)
            if fname is not None and (
                fname in _ENCODERS or fname.split(".")[-1] in _ENCODERS
            ):
                yield sub


@rule(
    "columnar-publish",
    "per-object encode()/json.dumps loop over a decision/op collection in "
    "the wire module set — the per-object publish/drain cost the columnar "
    "segment path (store/segment.py) deleted (14.9 s cfg7 drain, "
    "BASELINE.md r5); ship a segment, or suppress with the justification "
    "on the line",
)
def check_columnar_publish(ctx: FileContext) -> Iterable[Finding]:
    if not any(ctx.relpath.endswith(s) for s in _SCOPED_SUFFIXES):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            spelled = _pluralish(node.iter)
            if spelled is None:
                continue
            # the loop body only — a same-line else/orelse is not the loop
            for stmt in node.body:
                for call in _encoder_calls(stmt):
                    yield ctx.finding(
                        "columnar-publish",
                        call,
                        f"per-object encode inside `for ... in {spelled}`: "
                        "this re-grows the per-object wire the columnar "
                        "segment path replaced — carry the run as segment "
                        "columns instead",
                    )
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            spelled = None
            for gen in node.generators:
                spelled = _pluralish(gen.iter)
                if spelled is not None:
                    break
            if spelled is None:
                continue
            for call in _encoder_calls(node):
                yield ctx.finding(
                    "columnar-publish",
                    call,
                    f"per-object encode in a comprehension over "
                    f"{spelled!r} — carry the run as segment columns "
                    "instead",
                )
