"""proc-isolation: what survives today only because of the GIL.

ROADMAP item 1 — multi-process shard servers behind one logical store —
needs a machine-checked inventory of every place the current
single-process implementation shares state in ways a process boundary
breaks.  This rule produces that inventory for the shard-seam module set
(``store/server.py``, ``store/partition.py``, ``store/replica.py``,
``store/store.py``, ``scheduler/apply.py``), in three classes:

1. **module-global mutation from a verb path** — a module-level mutable
   (dict/list/set) written by a function reachable from an HTTP verb,
   a store verb, or the replica apply.  In one process that is shared
   state "for free"; across processes each worker silently gets its own
   copy and the aggregate lies.

2. **cross-shard object references** — a write that fans out across the
   per-shard index space from one shard's apply path (``for s in
   range(self.shards): self._shard_seq[s] = ...``).  In-process this is
   a cheap broadcast; across processes it is a cross-shard write that
   needs a protocol.

3. **unlocked read-modify-write** — ``x.attr += 1`` on a shared
   attribute of a lock-owning class, outside any ``with <lock>`` hold.
   The GIL makes the single bytecode races merely unlikely; a
   multi-process (or free-threaded) build makes them lost updates.

Findings are designed to be consumed via ``--worklist`` (suppressed
findings stay in the JSON output, marked, with the justifying comment
attached) so the multi-process PR starts from a complete inventory, and
every deferred item is ALSO listed in ROADMAP item 1's acceptance notes.

Structural exemptions: ``__init__``-family and recovery/replay entry
points (``_load*``, ``_recover*``, ``reset*``, ``_replay*``,
``_absorb*``) are single-threaded by contract and exempt from the RMW
check; thread-local state (an attribute chain through ``_tl``) is
per-thread by construction.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from volcano_tpu.analysis.core import (
    Finding,
    FunctionSummary,
    MUTATE_VERBS,
    ProjectContext,
    dotted_name,
    rule,
)
from volcano_tpu.analysis.rules_concurrency import class_lock_context

_SEAM_SUFFIXES = (
    "store/server.py",
    "store/partition.py",
    "store/replica.py",
    "store/store.py",
    "scheduler/apply.py",
)

_MUTATOR_METHODS = {
    "append", "add", "pop", "clear", "update", "setdefault", "popitem",
    "extend", "remove", "discard", "insert",
}

_INIT_METHODS = {
    "__init__", "__setstate__", "__getstate__", "__new__", "__post_init__",
}

_RECOVERY_PREFIXES = ("_load", "_recover", "reset", "_replay", "_absorb")

#: lock-ish context-manager name tails: `with self._mu:`, `with srv.lock:`
_LOCKISH = ("lock", "_mu", "_cv", "cond")


def _in_seam(relpath: str) -> bool:
    return any(relpath.endswith(s) for s in _SEAM_SUFFIXES)


def _is_recovery(name: str) -> bool:
    return name in _INIT_METHODS or any(
        name.startswith(p) for p in _RECOVERY_PREFIXES
    )


def _verb_roots(pctx: ProjectContext) -> List[str]:
    """HTTP verbs, seam-class store verbs, and the replica apply."""
    roots = []
    for s in pctx.summaries.values():
        if not _in_seam(s.relpath):
            continue
        if s.name.startswith("do_") and s.cls is not None:
            roots.append(s.fqn)
        elif s.cls is not None and s.name in MUTATE_VERBS:
            roots.append(s.fqn)
        elif s.name in ("apply_record", "apply"):
            roots.append(s.fqn)
    return roots


def _module_globals(tree: ast.AST) -> Dict[str, int]:
    """Module-level names bound to mutable literals/constructors."""
    out: Dict[str, int] = {}
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        value = node.value
        mutable = isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                                     ast.DictComp, ast.SetComp))
        if isinstance(value, ast.Call):
            ctor = dotted_name(value.func) or ""
            mutable = ctor.split(".")[-1] in (
                "dict", "list", "set", "defaultdict", "OrderedDict",
                "Counter", "deque",
            )
        if not mutable:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and not t.id.isupper():
                # SCREAMING_CASE module constants that are never written
                # are config tables; they are caught below only if a
                # verb path actually mutates them
                out[t.id] = node.lineno
            elif isinstance(t, ast.Name):
                out[t.id] = node.lineno
    return out


def _lock_attrs(pctx: ProjectContext, rel: str) -> Set[str]:
    """Attribute names assigned from lock factories/ctors in this file."""
    ctx = pctx.contexts[rel]
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Attribute):
            continue
        val = node.value
        calls = [val]
        if isinstance(val, ast.ListComp):
            calls = [val.elt]
        for c in calls:
            if isinstance(c, ast.Call):
                ctor = (dotted_name(c.func) or "").split(".")[-1]
                if ctor in ("make_lock", "make_rlock", "make_condition",
                            "Lock", "RLock", "Condition", "Semaphore"):
                    out.add(tgt.attr)
    return out


def _effectively_locked(pctx: ProjectContext, rel: str) -> Set[str]:
    """Qualnames ("Class.method") that are construction-only or
    called-locked per rules_concurrency's per-class fixpoint — an RMW
    inside them holds the caller's lock even without a lexical `with`."""
    ctx = pctx.contexts[rel]
    memo = ctx.cache.get("procisolation_locked")
    if memo is not None:
        return memo
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        lc = class_lock_context(ctx, node)
        if lc is None:
            continue
        for m in lc.init_reach | lc.locked_methods:
            out.add(f"{node.name}.{m}")
    ctx.cache["procisolation_locked"] = out
    return out


def _under_lock(fn: ast.AST, target: ast.AST) -> bool:
    """True when ``target`` sits lexically inside a ``with`` whose
    context expression names a lock-ish attribute."""

    def contains(node: ast.AST) -> bool:
        return any(sub is target for sub in ast.walk(node))

    stack = [fn]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.With, ast.AsyncWith)):
            locked = False
            for item in node.items:
                name = dotted_name(item.context_expr)
                tail = (name or "").split(".")[-1]
                if any(k in tail for k in _LOCKISH) or (
                    isinstance(item.context_expr, ast.Call)
                    and any(k in (dotted_name(item.context_expr.func) or "")
                            for k in _LOCKISH)
                ):
                    locked = True
            if locked and contains(node):
                return True
        for sub in ast.iter_child_nodes(node):
            if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or sub is fn:
                stack.extend([sub])
    return False


def _own_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _global_mutations(
    fn: ast.AST, globals_: Dict[str, int],
) -> Iterable[Tuple[int, str, str]]:
    """(line, name, how) for mutations of module globals in ``fn``."""
    declared = {
        n for node in _own_nodes(fn) if isinstance(node, ast.Global)
        for n in node.names
    }
    for node in _own_nodes(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in globals_:
                    yield (node.lineno, t.value.id, "subscript write")
                elif isinstance(t, ast.Name) and t.id in declared \
                        and t.id in globals_:
                    yield (node.lineno, t.id, "rebind via `global`")
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in globals_:
                    yield (node.lineno, t.value.id, "`del`")
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATOR_METHODS \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in globals_:
            yield (node.lineno, node.func.value.id,
                   f"`.{node.func.attr}()`")


def _cross_shard_writes(fn: ast.AST) -> Iterable[Tuple[int, str]]:
    """Writes fanning out across the per-shard index space: a subscript
    write ``<x>._shard*[i] = ...`` where ``i`` is the variable of an
    enclosing ``for i in range(...shard...)`` loop."""
    for node in _own_nodes(fn):
        if not isinstance(node, (ast.For, ast.AsyncFor)):
            continue
        if not isinstance(node.target, ast.Name):
            continue
        loopvar = node.target.id
        it = node.iter
        spans_shards = False
        if isinstance(it, ast.Call) \
                and (dotted_name(it.func) or "") == "range":
            for sub in ast.walk(it):
                if isinstance(sub, ast.Attribute) and "shard" in sub.attr:
                    spans_shards = True
                if isinstance(sub, ast.Name) and "shard" in sub.id:
                    spans_shards = True
        if not spans_shards:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Assign, ast.AugAssign)):
                targets = (
                    sub.targets if isinstance(sub, ast.Assign)
                    else [sub.target]
                )
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.slice, ast.Name) \
                            and t.slice.id == loopvar:
                        name = dotted_name(t.value) or "?"
                        if "_shard" in name.split(".")[-1]:
                            yield (sub.lineno, name)


def _unlocked_rmw(
    fn: ast.AST, lock_attrs: Set[str],
) -> Iterable[Tuple[int, str]]:
    for node in _own_nodes(fn):
        if not isinstance(node, ast.AugAssign):
            continue
        t = node.target
        if not isinstance(t, ast.Attribute):
            continue
        name = dotted_name(t) or t.attr
        parts = name.split(".")
        if "_tl" in parts:
            continue  # thread-local by construction
        if t.attr in lock_attrs:
            continue
        if not _under_lock(fn, node):
            yield (node.lineno, name)


@rule(
    "proc-isolation",
    "state in the shard-seam module set that survives only by GIL "
    "atomicity or single-process memory sharing: a module-level mutable "
    "global mutated from a verb path, a cross-shard fan-out write, or an "
    "unlocked read-modify-write on a shared attribute — each one breaks "
    "when the shards become processes (ROADMAP item 1); fix it now or "
    "defer it with a justified suppression that `--worklist` keeps "
    "visible",
    scope="project",
)
def check_proc_isolation(pctx: ProjectContext) -> Iterable[Finding]:
    findings: List[Finding] = []
    reachable = pctx.reachable_from(_verb_roots(pctx))
    for rel in sorted(pctx.contexts):
        if not _in_seam(rel):
            continue
        globals_ = _module_globals(pctx.contexts[rel].tree)
        lock_attrs = _lock_attrs(pctx, rel)
        for summary in pctx.functions_in(rel):
            fn = summary.node
            on_verb_path = summary.fqn in reachable
            if globals_ and on_verb_path:
                for line, gname, how in _global_mutations(fn, globals_):
                    findings.append(pctx.finding(
                        "proc-isolation", summary, line,
                        f"{how} on module global `{gname}` from the verb "
                        f"path `{summary.qualname}` — per-process copies "
                        "diverge silently once shards are processes; move "
                        "the state onto the store/server object or behind "
                        "an explicit shared channel",
                    ))
            for line, name in _cross_shard_writes(fn):
                findings.append(pctx.finding(
                    "proc-isolation", summary, line,
                    f"cross-shard fan-out write to `{name}` in "
                    f"`{summary.qualname}` — one shard's apply path "
                    "writes every shard's slot; across processes this "
                    "needs a broadcast protocol, not a loop",
                ))
            if _is_recovery(summary.name):
                continue  # single-threaded by contract
            if not lock_attrs:
                continue
            if summary.qualname in _effectively_locked(pctx, rel):
                continue  # construction-only or called-locked helper
            for line, name in _unlocked_rmw(fn, lock_attrs):
                findings.append(pctx.finding(
                    "proc-isolation", summary, line,
                    f"unlocked read-modify-write `{name} += ...` in "
                    f"`{summary.qualname}` of a lock-owning class — only "
                    "GIL atomicity makes this a non-race today; take the "
                    "owning lock (or make the counter explicitly "
                    "single-writer)",
                ))
    return findings
