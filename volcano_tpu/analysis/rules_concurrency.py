"""Lock discipline: static lock-order graph + guarded-state writes.

The multi-process daemons (store server, store, leader elector, event
recorder, scheduler cache/applier) serve concurrent HTTP handler threads,
a saver thread, and the async applier thread.  Two invariants keep them
deadlock- and race-free:

* **acyclic acquisition order** — e.g. `StoreServer.flush_state` takes
  `_flush_lock` BEFORE `lock` (server.py documents the ABBA hazard); any
  path acquiring them in the opposite order is a latent deadlock.  This
  rule builds a per-module lock-order graph from `with <lock>:` nesting,
  propagates acquisitions through same-class/same-module calls to a
  fixpoint, and flags cycles.  Nested acquisition of a NON-reentrant
  `threading.Lock` (self-cycle) is flagged too — it self-deadlocks.
* **guarded writes** — an attribute that is ever written under a class's
  lock is shared daemon state; writing it in another method without the
  lock is a data race.  Methods whose every intra-module call site holds
  the lock count as locked (`_pump_log` style "called-locked" helpers);
  `__init__`-reachable methods are construction-time and exempt.

The same graph is cross-checked at runtime by the env-gated lock-order
sanitizer (`volcano_tpu/analysis/locksan.py`, `make sanitize`).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from volcano_tpu.analysis.core import FileContext, Finding, dotted_name, rule

_LOCK_CTORS = {
    "threading.Lock": False,    # reentrant?
    "threading.RLock": True,
    "threading.Condition": True,  # condition shares/wraps a (re-entrant ok) lock
    "Lock": False,
    "RLock": True,
    "Condition": True,
    "make_lock": False,
    "make_rlock": True,
    "make_condition": True,
    "locksan.make_lock": False,
    "locksan.make_rlock": True,
    "locksan.make_condition": True,
}


class _LockDef:
    def __init__(self, key: str, reentrant: bool, line: int):
        self.key = key          # "ClassName.attr" or "module:name"
        self.reentrant = reentrant
        self.line = line
        self.alias_of: Optional[str] = None  # Condition(self.lock) aliases


class _FnInfo:
    """Per-function summary from the syntactic walk."""

    def __init__(self, qualname: str):
        self.qualname = qualname
        # (held tuple at acquisition, lock key, line)
        self.acquisitions: List[Tuple[Tuple[str, ...], str, int]] = []
        # (held tuple at call, callee simple name, receiver is self/module)
        self.calls: List[Tuple[Tuple[str, ...], str, int]] = []


def _collect_lock_defs(tree: ast.AST) -> Dict[str, _LockDef]:
    """Map attr/global name -> _LockDef, keyed by bare name (qualified key
    stored inside).  Bare-name keying matches `with self.X` / `with X`
    sites; collisions across classes are merged conservatively."""
    defs: Dict[str, _LockDef] = {}

    def record(bare: str, qual: str, ctor: str, node: ast.Call):
        reentrant = _LOCK_CTORS[ctor]
        d = _LockDef(qual, reentrant, node.lineno)
        # Condition(self.other_lock) is an alias for that lock
        if ctor.endswith("Condition") and node.args:
            target = node.args[0]
            if isinstance(target, ast.Attribute):
                d.alias_of = target.attr
            elif isinstance(target, ast.Name):
                d.alias_of = target.id
        if bare in defs:
            # same bare name in two classes: keep first, both treated as one
            return
        defs[bare] = d

    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        ctor = dotted_name(node.value.func)
        if ctor not in _LOCK_CTORS:
            continue
        for t in node.targets:
            if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                record(t.attr, f"self.{t.attr}", ctor, node.value)
            elif isinstance(t, ast.Name):
                record(t.id, f"module:{t.id}", ctor, node.value)
    return defs


def _resolve(defs: Dict[str, _LockDef], bare: str) -> Optional[str]:
    d = defs.get(bare)
    if d is None:
        return None
    seen = set()
    while d.alias_of is not None and d.alias_of in defs and d.alias_of not in seen:
        seen.add(d.alias_of)
        bare = d.alias_of
        d = defs[bare]
    return bare


def _with_lock_name(item: ast.withitem, defs: Dict[str, _LockDef]) -> Optional[str]:
    expr = item.context_expr
    # `with self.lock:` / `with server.lock:` / `with _lock:`
    if isinstance(expr, ast.Attribute):
        return _resolve(defs, expr.attr)
    if isinstance(expr, ast.Name):
        return _resolve(defs, expr.id)
    return None


def _callee_name(call: ast.Call) -> Optional[str]:
    """Simple name of an intra-module callee: `self.f(...)`, `f(...)`, or
    `<var>.f(...)` where the attr matches a module function/method — the
    receiver form `<var>.<attr>.f(...)` is treated as external."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.attr
    return None


def _walk_fn(fn: ast.AST, qualname: str, defs: Dict[str, _LockDef]) -> _FnInfo:
    info = _FnInfo(qualname)

    def visit(node: ast.AST, held: Tuple[str, ...]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
            # nested defs analyzed separately (closures run later; a held
            # lock at definition time is not held at call time)
            return
        if isinstance(node, ast.With):
            new_held = held
            for item in node.items:
                lock = _with_lock_name(item, defs)
                if lock is not None:
                    info.acquisitions.append((new_held, lock, node.lineno))
                    new_held = new_held + (lock,)
            for child in node.body:
                visit(child, new_held)
            return
        if isinstance(node, ast.Call):
            callee = _callee_name(node)
            if callee is not None:
                info.calls.append((held, callee, node.lineno))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.body:
        visit(stmt, ())
    return info


def _function_index(tree: ast.AST) -> Dict[str, List[Tuple[str, ast.AST]]]:
    """simple name -> [(qualname, fn node)] for module functions and
    methods (any class)."""
    index: Dict[str, List[Tuple[str, ast.AST]]] = {}
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            index.setdefault(node.name, []).append((node.name, node))
        elif isinstance(node, ast.ClassDef):
            for item in ast.walk(node):
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{node.name}.{item.name}"
                    index.setdefault(item.name, []).append((qual, item))
    return index


def _analyze_module(ctx: FileContext):
    """Shared walk for both concurrency rules (computed once per file,
    memoized on the FileContext).  Returns
    (defs, infos by qualname, edges, edge_sites, acq_closure)."""
    cached = ctx.cache.get("lock_analysis")
    if cached is not None:
        return cached
    result = _analyze_module_uncached(ctx)
    ctx.cache["lock_analysis"] = result
    return result


def _analyze_module_uncached(ctx: FileContext):
    defs = _collect_lock_defs(ctx.tree)
    if not defs:
        return defs, {}, {}, {}, {}
    index = _function_index(ctx.tree)
    infos: Dict[str, _FnInfo] = {}
    for name, entries in index.items():
        for qual, fn in entries:
            if qual not in infos:
                infos[qual] = _walk_fn(fn, qual, defs)

    # transitive lock-acquisition closure per function (fixpoint)
    acq: Dict[str, Set[str]] = {q: set(l for _, l, _ in i.acquisitions)
                                for q, i in infos.items()}
    changed = True
    while changed:
        changed = False
        for q, i in infos.items():
            for _, callee, _ in i.calls:
                for cq, _fn in index.get(callee, []):
                    extra = acq.get(cq, set()) - acq[q]
                    if extra:
                        acq[q] |= extra
                        changed = True

    # order edges: held -> newly acquired (direct + via calls)
    edges: Dict[str, Set[str]] = {}
    edge_sites: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def add_edge(a: str, b: str, qual: str, line: int):
        if a == b:
            return
        edges.setdefault(a, set()).add(b)
        edge_sites.setdefault((a, b), (qual, line))

    for q, i in infos.items():
        for held, lock, line in i.acquisitions:
            for h in held:
                add_edge(h, lock, q, line)
        for held, callee, line in i.calls:
            if not held:
                continue
            for cq, _fn in index.get(callee, []):
                for lock in acq.get(cq, ()):  # locks callee may acquire
                    for h in held:
                        add_edge(h, lock, q, line)
    return defs, infos, edges, edge_sites, acq


@rule(
    "lock-order",
    "cycle in the static lock-acquisition-order graph (ABBA deadlock) or "
    "nested acquisition of a non-reentrant lock",
)
def check_lock_order(ctx: FileContext) -> Iterable[Finding]:
    defs, infos, edges, edge_sites, _acq = _analyze_module(ctx)
    if not defs:
        return

    # non-reentrant self-nesting: direct or via calls
    for q, i in infos.items():
        for held, lock, line in i.acquisitions:
            if lock in held and not defs[lock].reentrant:
                yield ctx.finding(
                    "lock-order",
                    line,
                    f"{q} re-acquires non-reentrant lock "
                    f"{defs[lock].key!r} while already holding it — "
                    "self-deadlock (use RLock or restructure)",
                )

    # cycles via DFS
    color: Dict[str, int] = {}
    stack: List[str] = []
    reported: Set[frozenset] = set()

    def dfs(n: str):
        color[n] = 1
        stack.append(n)
        for m in sorted(edges.get(n, ())):
            if color.get(m, 0) == 0:
                yield from dfs(m)
            elif color.get(m) == 1:
                cycle = stack[stack.index(m):] + [m]
                key = frozenset(cycle)
                if key not in reported:
                    reported.add(key)
                    hops = []
                    for a, b in zip(cycle, cycle[1:]):
                        qual, line = edge_sites.get((a, b), ("?", 0))
                        hops.append(f"{a}->{b} ({qual}:{line})")
                    site = edge_sites.get((cycle[0], cycle[1]), ("?", 1))
                    yield ctx.finding(
                        "lock-order",
                        site[1],
                        "lock-order cycle (ABBA deadlock): "
                        + "; ".join(hops)
                        + " — pick one global order and stick to it",
                    )
        stack.pop()
        color[n] = 2

    for n in sorted(edges):
        if color.get(n, 0) == 0:
            yield from dfs(n)


_INIT_METHODS = {"__init__", "__setstate__", "__getstate__", "__new__",
                 "__post_init__"}


def _assigned_self_attrs(fn: ast.AST, locked_only: bool,
                         defs, infos: Dict[str, _FnInfo],
                         qual: str) -> Set[Tuple[str, int]]:
    """(attr, line) for writes to self.X (incl. self.X[...] / self.X.y)
    in fn, filtered by whether the write site is under a with-lock."""
    out: Set[Tuple[str, int]] = set()

    def visit(node: ast.AST, held: bool):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
            return
        if isinstance(node, ast.With):
            new_held = held or any(
                _with_lock_name(item, defs) is not None for item in node.items
            )
            for child in node.body:
                visit(child, new_held)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                base = t
                while isinstance(base, (ast.Subscript, ast.Attribute)) and not (
                    isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                ):
                    base = base.value
                if isinstance(base, ast.Attribute) and isinstance(base.value, ast.Name) \
                        and base.value.id == "self":
                    if held == locked_only:
                        out.add((base.attr, node.lineno))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.body:
        visit(stmt, False)
    return out


class ClassLockContext:
    """Per-class locking context shared by lock-guard and the
    interprocedural proc-isolation rule: which methods run only during
    construction, and which are "effectively locked" (every non-init
    call site holds a lock — `_pump_log`-style called-locked helpers)."""

    def __init__(self, cls: ast.ClassDef, methods, init_reach,
                 locked_methods, defs, infos):
        self.cls = cls
        self.methods: Dict[str, ast.AST] = methods
        self.init_reach: Set[str] = init_reach
        self.locked_methods: Set[str] = locked_methods
        self.defs = defs
        self.infos = infos


def class_lock_context(ctx: FileContext,
                       cls: ast.ClassDef) -> Optional[ClassLockContext]:
    """The locking context of one class, or None when the class owns no
    lock (then there is no discipline to check)."""
    defs, infos, _edges, _sites, _acq = _analyze_module(ctx)
    if not defs:
        return None
    methods = {
        item.name: item
        for item in cls.body
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    if not methods:
        return None
    has_lock = any(d.key == f"self.{bare}" for bare, d in defs.items())
    if not has_lock:
        return None

    # call sites within the class: method -> [(caller, held?)]
    call_sites: Dict[str, List[Tuple[str, bool]]] = {}
    for mname in methods:
        qual = f"{cls.name}.{mname}"
        info = infos.get(qual)
        if info is None:
            continue
        for held, callee, _line in info.calls:
            if callee in methods:
                call_sites.setdefault(callee, []).append((mname, bool(held)))

    # init-reachable methods (construction context, single-threaded)
    init_reach: Set[str] = set(m for m in methods if m in _INIT_METHODS)
    frontier = list(init_reach)
    while frontier:
        cur = frontier.pop()
        info = infos.get(f"{cls.name}.{cur}")
        if info is None:
            continue
        for _held, callee, _line in info.calls:
            if callee in methods and callee not in init_reach:
                # only counts if ALL its call sites are init-reachable
                sites = call_sites.get(callee, [])
                if sites and all(c in init_reach for c, _h in sites):
                    init_reach.add(callee)
                    frontier.append(callee)

    # fixpoint: a method is "effectively locked" if it has >=1 call
    # site and every non-init call site holds a lock or is itself
    # effectively locked
    locked_methods: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for mname in methods:
            if mname in locked_methods or mname in init_reach:
                continue
            sites = [s for s in call_sites.get(mname, [])
                     if s[0] not in init_reach]
            if sites and all(h or c in locked_methods for c, h in sites):
                locked_methods.add(mname)
                changed = True

    return ClassLockContext(cls, methods, init_reach, locked_methods,
                            defs, infos)


@rule(
    "lock-guard",
    "write to lock-guarded shared state outside the lock — attributes "
    "ever written under a class's lock must always be written under it",
)
def check_lock_guard(ctx: FileContext) -> Iterable[Finding]:
    defs, infos, _edges, _sites, _acq = _analyze_module(ctx)
    if not defs:
        return

    # per class: find methods, call sites, locked-effective methods
    for cls in (n for n in ast.walk(ctx.tree) if isinstance(n, ast.ClassDef)):
        lc = class_lock_context(ctx, cls)
        if lc is None:
            continue
        methods = lc.methods
        init_reach = lc.init_reach
        locked_methods = lc.locked_methods

        # guarded attrs: written under lock in any non-init context
        guarded: Set[str] = set()
        for mname, fn in methods.items():
            if mname in init_reach:
                continue
            qual = f"{cls.name}.{mname}"
            under = _assigned_self_attrs(fn, True, defs, infos, qual)
            guarded |= {a for a, _ in under}
            if mname in locked_methods:
                # everything it writes is effectively under lock
                outside = _assigned_self_attrs(fn, False, defs, infos, qual)
                guarded |= {a for a, _ in outside}
        # the lock attributes themselves are not data
        guarded -= set(defs.keys())
        if not guarded:
            continue

        for mname, fn in methods.items():
            if mname in init_reach or mname in locked_methods:
                continue
            qual = f"{cls.name}.{mname}"
            for attr, line in _assigned_self_attrs(fn, False, defs, infos, qual):
                if attr in guarded:
                    yield ctx.finding(
                        "lock-guard",
                        line,
                        f"{qual} writes self.{attr} outside the lock, but "
                        f"self.{attr} is lock-guarded shared state elsewhere "
                        "in this class — take the lock or move the write to "
                        "construction",
                    )


# --- lock-factory: daemon locks must be sanitizer-visible --------------------

#: the sanitizer-scoped module set: daemon modules whose locks must be
#: created through the locksan factories so `make sanitize` sees them.
#: PR 16 extends the set to the elastic/admission/loadgen daemons — they
#: are lock-free today, and this rule keeps any lock they GROW visible.
_FACTORY_DIRS = {"store", "elastic", "admission", "loadgen"}
_FACTORY_BASENAMES = {"apply.py", "daemons.py", "leader.py", "client.py"}

_RAW_CTORS = {
    "threading.Lock": "make_lock",
    "threading.RLock": "make_rlock",
    "Lock": "make_lock",
    "RLock": "make_rlock",
}


def _factory_scoped(ctx: FileContext) -> bool:
    parts = ctx.relpath.split("/")
    return any(p in _FACTORY_DIRS for p in parts[:-1]) \
        or parts[-1] in _FACTORY_BASENAMES


@rule(
    "lock-factory",
    "raw threading.Lock/RLock/Condition constructed in a sanitizer-scoped "
    "daemon module (store/, elastic/, admission/, loadgen/, apply.py, "
    "daemons.py, leader.py, client.py) — the lock-order sanitizer "
    "(`make sanitize`) only watches locks built through the locksan "
    "factories (make_lock/make_rlock/make_condition), so a raw lock is "
    "invisible to the runtime deadlock check; use the factory",
)
def check_lock_factory(ctx: FileContext) -> Iterable[Finding]:
    if not _factory_scoped(ctx):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        ctor = dotted_name(node.func)
        if ctor in _RAW_CTORS:
            yield ctx.finding(
                "lock-factory",
                node,
                f"raw `{ctor}()` in a sanitizer-scoped daemon module — "
                "invisible to the lock-order sanitizer; use "
                f"`{_RAW_CTORS[ctor]}(...)` from volcano_tpu.locksan "
                "(names the lock and keeps `make sanitize` honest)",
            )
        elif ctor in ("threading.Condition", "Condition") and not node.args:
            # Condition() with NO lock argument creates its own hidden
            # RLock; Condition(existing_lock) wraps an already-visible
            # lock and is fine
            yield ctx.finding(
                "lock-factory",
                node,
                "bare `Condition()` creates a hidden RLock the sanitizer "
                "cannot see — pass an existing factory-made lock "
                "(`Condition(self.lock)`) or use make_condition(...)",
            )
