"""digest-reachability: every verb-reachable mutation keeps the digest.

The interprocedural upgrade of the per-file ``digest-maintenance`` rule
(rules_audit.py).  That rule fences direct container mutation inside the
store module set; this one walks the resolved call graph (the vtflow
core) from the HTTP verbs — ``do_*`` handlers, the server store verbs,
and the replica ``apply_record`` — and checks every *reachable* function
in the whole package: if it directly mutates a digested container
(``_objects`` / ``_lazy_patch``) its transitive effect set must include
a ``_digest`` touch — its own, or one folded in from a callee it invokes
(the maintenance hook may live one call away).

Why reachability matters: a helper OUTSIDE store/store.py that a verb
path calls — a migration shim, a compaction pass, a debug endpoint that
"just fixes up" an object — mutates exactly the same audited state, and
the per-file rule never sees it.  Conversely a function nobody can reach
from a verb (dead scaffolding, test fixtures shipped in-package) is not
a divergence risk and stays out of the report.

Exemptions mirror rules_audit: ``materialize*`` functions fold values
the staging path already digested (digest-neutral by design), and
construction/recovery entry points rebuild the digest wholesale.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from volcano_tpu.analysis.core import (
    Finding,
    ProjectContext,
    rule,
)
from volcano_tpu.analysis.rules_audit import (
    _collect_aliases,
    _container_root,
    _is_exempt,
    _MUTATOR_METHODS,
    _own_nodes,
)
from volcano_tpu.analysis.rules_procisolation import (
    _is_recovery,
    _verb_roots,
)


def _direct_mutations(fn: ast.AST) -> Iterable[tuple]:
    """(line, what) for direct digested-container mutations in ``fn`` —
    the same detection rules_audit applies, minus the setattr heuristic
    (object-field rewrites are the per-file rule's concern)."""
    aliases = _collect_aliases(fn)
    for node in _own_nodes(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for tgt in targets:
                if isinstance(tgt, ast.Subscript):
                    root = _container_root(tgt.value, aliases)
                    if root is not None:
                        yield (node.lineno, f"subscript write into `{root}`")
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    root = _container_root(tgt.value, aliases)
                    if root is not None:
                        yield (node.lineno, f"`del` from `{root}`")
        elif isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATOR_METHODS
            ):
                root = _container_root(node.func.value, aliases)
                if root is not None:
                    yield (node.lineno, f"`.{node.func.attr}()` on `{root}`")


@rule(
    "digest-reachability",
    "a function reachable from an HTTP verb (do_* handler, server store "
    "verb, replica apply) directly mutates a digested container without "
    "a `_digest` update anywhere in its transitive effect set — the "
    "incremental state digest drifts on a live write path wherever the "
    "helper happens to live (interprocedural upgrade of "
    "digest-maintenance); fold the digest under the same lock hold",
    scope="project",
)
def check_digest_reachability(pctx: ProjectContext) -> Iterable[Finding]:
    findings: List[Finding] = []
    reachable: Set[str] = pctx.reachable_from(_verb_roots(pctx))
    for fqn in sorted(reachable):
        summary = pctx.summaries[fqn]
        fn = summary.node
        if _is_exempt(fn) or _is_recovery(summary.name):
            continue
        if "digest" in summary.effects:
            continue  # its own body or a callee folds the digest
        for line, what in _direct_mutations(fn):
            findings.append(pctx.finding(
                "digest-reachability", summary, line,
                f"{what} in `{summary.qualname}` (reachable from an HTTP "
                "verb) with no `_digest` touch in its transitive effects "
                "— the maintained digest drifts from the stored objects "
                "on a live write path; update the digest in the same "
                "verb or in a helper this function calls",
            ))
    return findings
