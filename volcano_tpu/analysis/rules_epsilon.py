"""ε-compare discipline: `Resource` values never compare with raw operators.

`api/resource.py` defines the epsilon-tolerant comparison semantics the
whole scheduler depends on (MIN_MILLI_CPU / MIN_MEMORY / MIN_SCALAR,
reference resource_info.go:70-72): `less_equal`, `less`, `approx_equal`,
`fit_delta`.  A raw `==`/`<`/`<=` between Resource values silently
reintroduces exact float comparison and breaks parity with both the
reference and the device kernels (which carry the same epsilons as `eps`
tensors).  This rule flags comparisons whose operand is

* an attribute known (by project-wide naming convention, see
  ``RESOURCE_ATTRS``) to hold a ``Resource`` — ``task.resreq``,
  ``node.idle``, ``attr.deserved``, ... — or
* a local name assigned from ``Resource(...)`` / ``.clone()`` /
  ``Resource.min(...)`` / ``Resource.from_resource_list(...)`` in the same
  function,

everywhere except ``api/resource.py`` itself (the single place allowed to
define the semantics).  Comparisons inside jit-traced bodies are exempt:
device code compares float arrays with explicit ``eps`` terms by design.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Set

from volcano_tpu.analysis.core import (
    FileContext,
    Finding,
    dotted_name,
    ctx_nodes_in_jit,
    rule,
    walk_functions,
)

#: attribute names that hold Resource values across the model
#: (api/resource.py, scheduler/model.py, plugins/proportion.py)
RESOURCE_ATTRS = {
    "resreq",
    "init_resreq",
    "total_request",
    "allocated",
    "idle",
    "used",
    "releasing",
    "allocatable",
    "capability",
    "idle_deficit",
    "releasing_deficit",
    "min_resources",
    "deserved",
}

_CONSTRUCTORS = {
    "Resource",
    "Resource.min",
    "Resource.from_resource_list",
    "resource.Resource",
}

_ALLOWED_FILES = ("api/resource.py",)

_OP_NAMES = {
    ast.Eq: "==",
    ast.NotEq: "!=",
    ast.Lt: "<",
    ast.LtE: "<=",
    ast.Gt: ">",
    ast.GtE: ">=",
}


def _is_resource_expr(node: ast.AST, tainted: Set[str]) -> bool:
    if isinstance(node, ast.Attribute) and node.attr in RESOURCE_ATTRS:
        return True
    if isinstance(node, ast.Name) and node.id in tainted:
        return True
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name in _CONSTRUCTORS:
            return True
        # fluent chain: x.resreq.clone().add(y) stays a Resource
        if isinstance(node.func, ast.Attribute) and node.func.attr in (
            "clone", "add", "sub", "multi", "set_max", "fit_delta"
        ):
            return _is_resource_expr(node.func.value, tainted)
    return False


def _tainted_names(fn: ast.AST) -> Set[str]:
    """Local names assigned from Resource constructors/clones within fn."""
    tainted: Set[str] = set()
    # two passes so `a = Resource(); b = a.clone()` taints b
    for _ in range(2):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                if _is_resource_expr(node.value, tainted):
                    tainted.add(node.targets[0].id)
    return tainted


@rule(
    "resource-raw-compare",
    "raw ==/!=/</<= between Resource values outside api/resource.py — "
    "use less/less_equal/approx_equal (epsilon-tolerant) instead",
)
def check_resource_compare(ctx: FileContext) -> Iterable[Finding]:
    if ctx.relpath.endswith(_ALLOWED_FILES):
        return
    in_jit = ctx_nodes_in_jit(ctx)

    scopes = [ctx.tree] + list(walk_functions(ctx.tree))
    seen: Set[int] = set()
    for scope in scopes:
        tainted = _tainted_names(scope) if scope is not ctx.tree else set()
        for node in ast.walk(scope):
            if not isinstance(node, ast.Compare) or id(node) in seen:
                continue
            if id(node) in in_jit:
                seen.add(id(node))
                continue
            operands = [node.left] + list(node.comparators)
            for op, (l, r) in zip(node.ops, zip(operands, operands[1:])):
                if type(op) not in _OP_NAMES:
                    continue
                for side in (l, r):
                    if _is_resource_expr(side, tainted):
                        seen.add(id(node))
                        desc = ast.unparse(side) if hasattr(ast, "unparse") else "operand"
                        yield ctx.finding(
                            "resource-raw-compare",
                            node,
                            f"raw {_OP_NAMES[type(op)]} comparison on Resource "
                            f"value {desc!r}; use the epsilon-tolerant API "
                            "(less/less_equal/approx_equal) from api/resource.py",
                        )
                        break
                if id(node) in seen:
                    break
