import sys

from volcano_tpu.cli.vtctl import main

sys.exit(main())
