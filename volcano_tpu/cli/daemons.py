"""Daemon entrypoints: run each control-plane component as its own process.

The reference ships three binaries — vk-scheduler (kube-batch),
vk-controllers, vk-admission — plus vkctl, all meeting at the API server
(SURVEY.md §1). Here the API server is the store server
(volcano_tpu/store/server.py, admission runs inline on Job writes as the
webhook does), and the scheduler/controller/kubelet run against it through
RemoteStore:

  python -m volcano_tpu.cli apiserver  --port 8443
  python -m volcano_tpu.cli controller --server http://127.0.0.1:8443
  python -m volcano_tpu.cli scheduler  --server http://127.0.0.1:8443
  python -m volcano_tpu.cli kubelet    --server http://127.0.0.1:8443

Controller and scheduler leader-elect through a store Lease by default
(reference cmd/controllers/app/server.go:103-125), so replicas can run
hot-standby exactly like the reference deployments.
"""

from __future__ import annotations

import os
import signal
import sys
import time


#: errors a store outage can surface through RemoteStore: connection
#: failures (OSError/URLError), server-side 5xx (RemoteStoreError), and a
#: response cut mid-body (http.client.HTTPException, NOT an OSError)
def _transient_errors():
    import http.client

    from volcano_tpu.store.client import RemoteStoreError

    return (RemoteStoreError, OSError, http.client.HTTPException)


def _maybe_debug_server(port: int, announce) -> None:
    """Serve /debug/trace (+ /metrics, /healthz) when ``port >= 0`` — the
    flight-recorder endpoint for daemons without their own metrics server
    (controller, kubelet).  0 picks a free port."""
    if port < 0:
        return
    from volcano_tpu.scheduler.metrics_server import MetricsServer

    srv = MetricsServer(port=port).start()
    announce(f"debug on http://127.0.0.1:{srv.port}/debug/trace", flush=True)


def _peer_list(peers: str):
    """``--peers`` comma list -> RemoteStore ``peers`` kwarg (None when
    unset, so single-server deployments keep the fail-fast client)."""
    urls = [p.strip() for p in peers.split(",") if p.strip()]
    return urls or None


def _elector(store, component: str, identity: str, enabled: bool):
    if not enabled:
        return None
    from volcano_tpu import chaos
    from volcano_tpu.leader import LeaderElector

    # lease clock-skew injection rides the elector's injectable clock: a
    # VOLCANO_TPU_CHAOS plan with leader.clock rules makes this candidate
    # see skewed time (chaos.chaos_clock), flapping real leases in real
    # daemon processes without touching election logic
    plan = chaos.env_plan()
    clock = None
    if plan is not None and plan.has_point("leader.clock"):
        clock = chaos.chaos_clock(plan)
    return LeaderElector(store, name=component, identity=identity, clock=clock)


def run_apiserver(port: int = 0, host: str = "127.0.0.1", default_queue: bool = True,
                  state: str = "", wal: bool = False, shards: int = 1,
                  replica_of: str = "", peers: str = "", repl_ack: str = "",
                  identity: str = "", lease_duration: float = 5.0,
                  proc_shards: int = 0, proc_replicas: int = 1,
                  announce=print) -> None:
    """``state`` names a JSON file the server persists all objects to (the
    etcd analogue): a restarted apiserver resumes with every CRD, and
    clients behind the restart relist.  ``wal=True`` adds the segment
    write-ahead log beside it (``<state>.wal/``): every ACKed mutation is
    fsynced before its 2xx, so a SIGKILLed apiserver recovers with zero
    acked loss (store/wal.py).  ``shards>1`` partitions the decision bus
    by namespace hash (store/partition.py): per-shard apply locks,
    per-shard WAL directories with independent group-commit fsync, and
    ``/watch?shard=i`` fan-out — the scheduler's applier splits each
    cycle's segment to match.

    ``replica_of=<leader url>`` boots this server as a FOLLOWER
    (store/replica.py): it pulls the leader's synced WAL feed, replays it
    through the recovery path, serves reads/watches locally, and rejects
    writes with a NotLeader redirect.  ``peers`` (comma list of every
    apiserver URL including this one) arms leader election for failover;
    ``repl_ack=sync`` makes the leader's 2xx wait for >=1 follower append
    (zero acked loss across a leader kill + promotion)."""
    from volcano_tpu import trace
    from volcano_tpu.api.objects import Metadata, Queue
    from volcano_tpu.store.server import StoreServer

    trace.set_component("apiserver")
    if proc_shards > 0:
        return _run_apiserver_procmesh(
            port=port, host=host, default_queue=default_queue, state=state,
            wal=wal, proc_shards=proc_shards, proc_replicas=proc_replicas,
            repl_ack=repl_ack or "sync", announce=announce,
        )
    peer_urls = [p.strip() for p in peers.split(",") if p.strip()]
    repl = None
    if replica_of or peer_urls or repl_ack:
        repl = {
            "identity": identity or None,
            "peers": peer_urls,
            "leader": replica_of or None,
            "ack": repl_ack or "async",
            "lease_duration": lease_duration,
        }
    if repl is not None and (not wal or not state):
        raise SystemExit("replication requires --wal and --state: the feed "
                         "ships fsynced WAL records and followers replay "
                         "into their own WAL dirs")
    srv = StoreServer(host=host, port=port, state_path=state or None,
                      wal=wal, shards=shards, repl=repl)
    # followers never seed: the default queue arrives via the feed (a
    # local create would fork the lineage before the first snapshot sync)
    if (default_queue and not replica_of
            and srv.store.get("Queue", "/default") is None):
        srv.store.create("Queue", Queue(meta=Metadata(name="default", namespace="")))
    announce(f"apiserver listening on {srv.url}", flush=True)

    # SIGTERM -> SystemExit on the serving (main) thread (httpd.shutdown()
    # from a signal handler would deadlock: shutdown must come from a
    # different thread than serve_forever); the finally flushes state
    install_sigterm_exit()
    try:
        srv.serve_forever()
    finally:
        # serve_forever has returned, so stop() is safe here: it joins the
        # saver thread, flushes state, and fsyncs the WAL tail in one
        # place.  A SECOND SIGTERM during that final flush would raise
        # SystemExit inside it and abort the very write that makes the
        # shutdown graceful — mask the signal for the flush (SIGKILL
        # still works; that is what the WAL recovers from).
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        srv.stop()


def _run_apiserver_procmesh(port: int, host: str, default_queue: bool,
                            state: str, wal: bool, proc_shards: int,
                            proc_replicas: int, repl_ack: str,
                            announce=print) -> None:
    """``apiserver --proc-shards N``: each store shard in its OWN OS
    process (store/procmesh), the router serving the apiserver port.
    The supervisor owns the shared seq/rv line and restarts dead shard
    members; the router is the single URL legacy clients keep using
    (mesh-aware clients pick up the shard map from its ``/healthz``)."""
    from volcano_tpu.api.objects import Metadata, Queue
    from volcano_tpu.store.client import RemoteStore
    from volcano_tpu.store.procmesh import ShardRouter, ShardSupervisor

    if proc_replicas > 1 and not (wal and state):
        raise SystemExit("per-shard replication requires --wal and --state: "
                         "the feed ships fsynced WAL records")
    if wal and not state:
        raise SystemExit("--wal requires --state (the WAL checkpoints into "
                         "the shard snapshots)")
    sup = ShardSupervisor(
        proc_shards, host=host, state=state or None,
        wal=(state + ".wal") if wal else None,
        replicas=proc_replicas, repl_ack=repl_ack,
    ).start()
    router = ShardRouter(sup.shard_map, supervisor=sup,
                         host=host, port=port).start()
    if default_queue:
        # seed THROUGH the router so the record lands on its namespace
        # shard with a WAL/watch entry like any client write
        rs = RemoteStore(router.url)
        if rs.get("Queue", "/default") is None:
            try:
                rs.create("Queue", Queue(meta=Metadata(name="default",
                                                       namespace="")))
            except KeyError:
                pass  # raced another seeder (supervisor restart)
    announce(f"apiserver (procmesh shards={proc_shards}) listening on "
             f"{router.url}", flush=True)
    from volcano_tpu import vtfleet

    if vtfleet.COLLECTOR is not None:
        # fleet forensics armed (VOLCANO_TPU_FLEET): the supervisor's
        # monitor loop caches member rings and writes an incident bundle
        # when a shard process dies
        announce("fleet collector armed: incident bundles in "
                 f"{vtfleet.COLLECTOR.incident_dir or '.'}", flush=True)
    install_sigterm_exit()
    try:
        # the router serves from its own thread; park here until SIGTERM
        # (install_sigterm_exit turns it into SystemExit on this thread)
        while True:
            signal.pause()
    finally:
        # same graceful-shutdown shape as the in-process apiserver: a
        # second SIGTERM must not abort the shard flushes mid-write
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        router.stop()
        sup.stop()


def run_controller(server: str, identity: str = "", leader_elect: bool = True,
                   period: float = 0.2, announce=print,
                   debug_port: int = -1, peers: str = "") -> None:
    from volcano_tpu import trace
    from volcano_tpu.controller import JobController
    from volcano_tpu.store.client import RemoteStore, StaleWatch

    trace.set_component("controller")
    _maybe_debug_server(debug_port, announce)
    ident = identity or f"controller-{os.getpid()}"

    def build():
        store = RemoteStore(server, peers=_peer_list(peers))
        return JobController(
            store, elector=_elector(store, "vk-controllers", ident, leader_elect)
        )

    from volcano_tpu.backoff import Backoff

    transient = _transient_errors()
    announce(f"controller {ident} watching {server}", flush=True)
    down = False
    ctl = None
    retry = Backoff(base=min(max(period, 0.01), 0.2))
    while True:
        try:
            if ctl is None:
                # build() lists every kind over the wire — it must sit
                # inside the outage guard too (INCLUDING the very first
                # build: a 5xx at boot must not kill the daemon, the
                # chaos env-plan test boots into exactly that), or a
                # flapping server kills the controller during the very
                # recovery it relists for
                ctl = build()
            ctl.pump()
            retry.reset()
            if down:
                announce(f"controller {ident}: store back, relisting", flush=True)
                down = False
                ctl = None  # full relist after an apiserver outage
                continue
        except StaleWatch:
            # fell off the server's event log (e.g. long standby) or the
            # server restarted: rebuild from a fresh list — this IS the
            # post-outage relist, so clear ``down`` or the next successful
            # pump would trigger a redundant second rebuild
            announce(f"controller {ident}: stale watch, relisting", flush=True)
            ctl = None
            down = False
            continue
        except transient as e:
            # apiserver outage: keep retrying as client-go reflectors do,
            # but on a decorrelated-jitter backoff, not the pump period —
            # a restarting apiserver must not be met by every daemon in
            # the deployment on the same fixed beat
            if not down:
                announce(f"controller {ident}: store unavailable ({e}); retrying",
                         flush=True)
                down = True
            retry.sleep()
            continue
        time.sleep(period)


def run_scheduler(server: str, conf_path: str = "", identity: str = "",
                  leader_elect: bool = True, period: float = 1.0,
                  metrics_port: int = 8080, announce=print,
                  peers: str = "", mesh_hosts: int = 0,
                  mesh_host_id: int = -1) -> None:
    """schedule-period defaults to the reference's 1s and /metrics to :8080,
    as the reference binary (options.go:28,63; server.go:86-89). Pass
    metrics_port<0 to disable the endpoint, 0 for a free port."""
    from volcano_tpu import trace
    from volcano_tpu.scheduler.conf import full_conf, load_conf
    from volcano_tpu.scheduler.scheduler import Scheduler
    from volcano_tpu.store.client import RemoteStore

    trace.set_component("scheduler")
    # deployed default: the fully-loaded 5-action conf on the tpu backend
    # (VOLCANO_TPU_BACKEND=host opts out — e.g. deployments without jax;
    # the test suite sets it to keep daemon subprocesses light)
    conf = (
        load_conf(open(conf_path).read())
        if conf_path
        else full_conf(os.environ.get("VOLCANO_TPU_BACKEND", "tpu"))
    )
    if conf.backend == "tpu":
        # a bare `pip install volcano-tpu` has no jax (the [tpu] extra);
        # degrade the deployed default to the native/host tier instead of
        # crash-looping the scheduler unit
        try:
            import jax  # noqa: F401
        except ImportError:
            announce("jax unavailable; scheduler falls back to "
                     "'native' backend (install volcano-tpu[tpu] for the "
                     "TPU path)", flush=True)
            conf.backend = "native"
            conf.fast_path = "off"
    # multi-controller launch: flag > env > conf.  One scheduler process
    # per mesh host; host 0 is the coordinator (publishes decisions),
    # the rest solve their shard and ship owned slices only.
    if mesh_hosts <= 0:
        mesh_hosts = int(os.environ.get("VOLCANO_TPU_MESH_HOSTS", "0"))
    if mesh_host_id < 0:
        mesh_host_id = int(os.environ.get("VOLCANO_TPU_MESH_HOST_ID", "-1"))
    if mesh_hosts > 0:
        conf.mesh_hosts = mesh_hosts
    if mesh_host_id >= 0:
        conf.mesh_host_id = mesh_host_id
    if conf.mesh_hosts > 1:
        if not (0 <= conf.mesh_host_id < conf.mesh_hosts):
            raise SystemExit(
                f"--mesh-host-id {conf.mesh_host_id} out of range for "
                f"--mesh-hosts {conf.mesh_hosts}")
        # every host must run every cycle in lockstep — leader election
        # would silence all but one host; identity stays unique per host
        # so a lease from a previous single-host deployment can expire
        leader_elect = False
        identity = (identity or f"scheduler-{os.getpid()}") \
            + f"-host{conf.mesh_host_id}"
    if conf.apply_mode is None:
        # deployed default: async batched decision application — a cycle's
        # binds are one bulk round trip off the critical path (a conf file
        # can still pin applyMode: sync)
        conf.apply_mode = "async"
    if conf.mirror_checkpoint is None:
        # env opt-in for deployments without a conf file (the systemd
        # unit's stable identity makes the path restart-stable)
        ckpt_env = os.environ.get("VOLCANO_TPU_MIRROR_CKPT")
        if ckpt_env:
            conf.mirror_checkpoint = ckpt_env
    ident = identity or f"scheduler-{os.getpid()}"
    if conf.backend == "tpu":
        from volcano_tpu.scheduler.scheduler import (
            enable_persistent_compilation_cache,
        )

        cache_dir = enable_persistent_compilation_cache(
            default_dir=os.path.join(
                os.path.expanduser("~"), ".cache", "volcano_tpu", "xla"
            )
        )
        if cache_dir:
            announce(f"scheduler {ident}: XLA compilation cache at {cache_dir}",
                     flush=True)
    from volcano_tpu.backoff import Backoff

    boot = Backoff(base=min(max(period, 0.01), 0.5))
    while True:
        try:
            # construction subscribes the fast mirror's watches over the
            # wire (tpu/native backends) — a 5xx or reset at boot must
            # retry, not kill the unit before its first cycle.  The store
            # is rebuilt per attempt: a failed construction would leave
            # orphaned watch queues on a shared client, buffering every
            # event forever
            store = RemoteStore(server, peers=_peer_list(peers))
            sched = Scheduler(store, conf=conf,
                              elector=_elector(store, "vk-scheduler", ident,
                                               leader_elect))
            break
        except _transient_errors() as e:
            announce(f"scheduler {ident}: store unavailable at boot ({e}); "
                     "retrying", flush=True)
            boot.sleep()
    announce(f"scheduler {ident} cycling every {period}s against {server}", flush=True)
    try:
        warm = sched.prewarm()
    except _transient_errors() as e:
        announce(f"scheduler {ident}: prewarm skipped (store unavailable: {e})",
                 flush=True)
    else:
        if warm:
            announce(f"scheduler {ident}: solves warm in {warm:.1f}s "
                     "(persistent XLA cache on)", flush=True)
    if metrics_port >= 0:
        from volcano_tpu.scheduler.metrics_server import MetricsServer

        ms = MetricsServer(port=metrics_port).start()
        announce(f"metrics on http://127.0.0.1:{ms.port}/metrics", flush=True)
    transient = _transient_errors()
    down = False
    cycles = 0
    retry = Backoff(base=min(max(period, 0.01), 0.5))
    while True:
        t0 = time.monotonic()
        try:
            sched.run_once()
            retry.reset()
            if down:
                announce(f"scheduler {ident}: store back", flush=True)
                down = False
        except transient as e:
            if not down:
                announce(f"scheduler {ident}: store unavailable ({e}); retrying",
                         flush=True)
                down = True
            # outage retry on jittered backoff; the healthy cycle cadence
            # below stays the reference's fixed schedule-period
            retry.sleep()
            continue
        cycles += 1
        if sched.conf.mirror_checkpoint and cycles % 30 == 0:
            # periodic mirror checkpoint (between cycles = consistent
            # state; skipped internally while async decisions are in
            # flight) so a crash-restart still delta-reconciles
            try:
                sched.save_mirror_checkpoint()
            except Exception as e:  # noqa: BLE001 — never kill the loop
                announce(f"scheduler {ident}: mirror checkpoint failed: {e}",
                         flush=True)
        time.sleep(max(0.0, period - (time.monotonic() - t0)))


def kubelet_step(store, now: float) -> None:
    """One pass of the simulated kubelet over the store: reap deleting
    pods, flip bound Pending pods Running (the Ready flip — a traced
    gang's pods join their trace here), and advance Provisioning elastic
    nodes.  Shared by ``run_kubelet`` and the in-process control planes
    in the chaos soak, so both paths carry identical semantics."""
    from volcano_tpu import trace
    from volcano_tpu.api.types import PodPhase
    from volcano_tpu.elastic.lifecycle import kubelet_provisioning_step
    from volcano_tpu.store.store import Conflict

    for pod in store.list("Pod"):
        if pod.deleting:
            store.delete("Pod", pod.meta.key)
        elif pod.node_name and pod.phase == PodPhase.PENDING:
            from volcano_tpu import chaos

            # seeded mid-ready-flip kill (crash.kubelet.ready): some pods
            # of a gang Running, the rest still Pending — a restarted
            # kubelet must finish the flips idempotently
            chaos.crash_point("crash.kubelet.ready", path=pod.meta.key)
            rv = pod.meta.resource_version
            pod.phase = PodPhase.RUNNING
            try:
                # CAS: the controller may have marked this pod
                # deleting since the list; never resurrect it with
                # a stale write
                store.update_cas("Pod", pod, rv)
            except (Conflict, KeyError):
                continue  # changed under us; reconcile next period
            if trace.TRACER is not None:
                tid = trace.gang_trace(pod.meta)
                if tid:
                    # the lifecycle's last leg: pod observed Running
                    with trace.span("kubelet.ready", trace_id=tid,
                                    pod=pod.meta.key, node=pod.node_name):
                        pass
    kubelet_provisioning_step(store, now)


def run_kubelet(server: str, period: float = 0.2, announce=print,
                debug_port: int = -1, peers: str = "") -> None:
    """Simulated kubelets over the remote store: bound pending pods start
    Running; pods marked deleting are reaped (the Releasing window the
    pipelined tasks wait on, SURVEY.md §3.5); Provisioning elastic nodes
    flip Ready once wall time passes their provision delay
    (elastic/lifecycle.py — elasticd stamps ready-at with time.time).
    ``debug_port>=0`` serves /debug/trace (+ /metrics) for the flight
    recorder."""
    import time as _time

    from volcano_tpu import trace
    from volcano_tpu.store.client import RemoteStore

    from volcano_tpu.backoff import Backoff

    trace.set_component("kubelet")
    _maybe_debug_server(debug_port, announce)
    store = RemoteStore(server, peers=_peer_list(peers))
    announce(f"kubelet simulating against {server}", flush=True)
    transient = _transient_errors()
    down = False
    retry = Backoff(base=min(max(period, 0.01), 0.2))
    while True:
        try:
            kubelet_step(store, _time.time())
            retry.reset()
            if down:
                announce("kubelet: store back", flush=True)
                down = False
        except transient as e:
            if not down:
                announce(f"kubelet: store unavailable ({e}); retrying", flush=True)
                down = True
            retry.sleep()
            continue
        time.sleep(period)


def run_elastic(server: str, identity: str = "", leader_elect: bool = True,
                period: float = 0.2, metrics_port: int = 8081,
                announce=print, peers: str = "") -> None:
    """elasticd: the node-pool autoscaler daemon (volcano_tpu/elastic/).
    Leader-elected like the controller/scheduler; the VOLCANO_TPU_CHAOS
    env plan's ``elastic.provision`` rules inject provisioning
    failures/delays; outage retries pace through the shared Backoff.
    ``volcano_elastic_*`` series expose on /metrics at ``metrics_port``
    (default :8081 — the scheduler owns :8080; <0 disables, 0 = free
    port)."""
    from volcano_tpu import chaos, trace
    from volcano_tpu.elastic import ElasticController
    from volcano_tpu.store.client import RemoteStore, StaleWatch

    from volcano_tpu.backoff import Backoff

    trace.set_component("elastic")
    ident = identity or f"elastic-{os.getpid()}"
    plan = chaos.env_plan()
    fault = plan if plan is not None and plan.has_point("elastic.provision") \
        else None

    def build():
        store = RemoteStore(server, peers=_peer_list(peers))
        return ElasticController(
            store,
            elector=_elector(store, "vk-elastic", ident, leader_elect),
            chaos=fault,
        )

    if metrics_port >= 0:
        from volcano_tpu.scheduler.metrics_server import MetricsServer

        ms = MetricsServer(port=metrics_port).start()
        announce(f"metrics on http://127.0.0.1:{ms.port}/metrics", flush=True)
    transient = _transient_errors()
    announce(f"elastic {ident} watching {server}", flush=True)
    down = False
    ctl = None
    retry = Backoff(base=min(max(period, 0.01), 0.2))
    while True:
        try:
            if ctl is None:
                # construction subscribes watches over the wire — build
                # inside the outage guard so a 5xx at boot retries instead
                # of killing the unit (same shape as run_controller)
                ctl = build()
            ctl.pump()
            retry.reset()
            if down:
                announce(f"elastic {ident}: store back, relisting", flush=True)
                down = False
                ctl = None  # full relist after an apiserver outage
                continue
        except StaleWatch:
            announce(f"elastic {ident}: stale watch, relisting", flush=True)
            ctl = None
            down = False
            continue
        except transient as e:
            if not down:
                announce(f"elastic {ident}: store unavailable ({e}); retrying",
                         flush=True)
                down = True
            retry.sleep()
            continue
        time.sleep(period)


def install_sigterm_exit() -> None:
    signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))


# -- one-command process model (the installer/ analogue) ----------------------

def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_http(url: str, timeout: float = 30.0) -> bool:
    from volcano_tpu.store.client import wait_healthy

    return wait_healthy(url, timeout=timeout)


def run_up(port: int = 8443, state: str = "", conf_path: str = "",
           pidfile: str = ".vt-up.json", detach: bool = False,
           schedulers: int = 1, controllers: int = 1, elastic: int = 0,
           host: str = "127.0.0.1", wal: bool = False, announce=print) -> int:
    """Bring up the whole control plane — apiserver (+durable state),
    scheduler(s), controller(s), kubelet — as real OS processes with
    health checks: the reference's helm-chart/3-image deployment collapsed
    to one command (installer/chart/volcano/templates analogue).

    Foreground by default (Ctrl-C tears everything down); ``detach=True``
    writes a pidfile and returns, ``run_down`` reads it back.  Extra
    scheduler/controller replicas hot-standby through store Leases exactly
    like the reference's leader-elected deployments.
    """
    import json
    import subprocess

    if wal and not state:
        # fail fast with the real constraint: the child apiserver would
        # die instantly on StoreServer's ValueError, burning the whole
        # 30 s health-check wait to report an unrelated-looking error
        announce("error: --wal requires --state (the WAL checkpoints "
                 "into the state file)", flush=True)
        return 1

    # refuse to orphan a previous detached control plane — every recorded
    # pid is checked (a crashed apiserver must not hide live schedulers)
    try:
        with open(pidfile) as f:
            prev = json.load(f)
    except (OSError, ValueError):
        prev = None
    if prev:
        alive = []
        for pid in prev.get("pids", []):
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                continue
            except OSError:
                alive.append(pid)  # exists but not ours: still refuse
            else:
                alive.append(pid)
        if alive:
            announce(
                f"error: a control plane from {pidfile} is still running "
                f"(pids {alive}); run 'vtctl down' first", flush=True,
            )
            return 1

    port_was_auto = port == 0
    if port_was_auto:
        port = _free_port()
    # children and the health probe dial loopback when the bind address is
    # a wildcard (0.0.0.0 in containers); a specific interface address is
    # dialed directly, since it may not answer on 127.0.0.1
    dial = "127.0.0.1" if host in ("0.0.0.0", "::", "") else host
    url = f"http://{dial}:{port}"
    py = sys.executable
    procs = []
    # detached daemons must not inherit our stdout (a piped `vtctl up -d`
    # would otherwise never see EOF): component output goes to a log file
    log = open(pidfile + ".log", "ab") if detach else None

    def spawn(*argv):
        p = subprocess.Popen([py, "-m", "volcano_tpu.cli", *argv],
                             stdout=log, stderr=log,
                             start_new_session=detach)
        procs.append(p)
        return p

    def start_apiserver():
        args = ["apiserver", "--port", str(port), "--host", host]
        if state:
            args += ["--state", state]
        if wal:
            args += ["--wal"]
        spawn(*args)
        return _wait_http(url)

    ok = start_apiserver()
    if not ok and port_was_auto:
        # _free_port's bind-then-close probe can lose a TOCTOU race on a
        # busy host: retry once on a fresh port. The failed process must be
        # fully gone first — two apiservers racing one --state file would
        # interleave flushes
        failed = procs.pop()
        failed.terminate()
        try:
            failed.wait(timeout=10)
        except subprocess.TimeoutExpired:
            failed.kill()
            failed.wait()
        port = _free_port()
        url = f"http://{dial}:{port}"
        ok = start_apiserver()
    if not ok:
        announce("error: apiserver failed its health check", flush=True)
        for p in procs:
            p.terminate()
        return 1
    announce(f"apiserver ready at {url}", flush=True)

    for i in range(schedulers):
        argv = ["scheduler", "--server", url, "--identity", f"sched-{i}",
                "--metrics-port", "-1"]
        if conf_path:
            argv += ["--conf", conf_path]
        spawn(*argv)
    for i in range(controllers):
        spawn("controller", "--server", url, "--identity", f"ctl-{i}")
    for i in range(elastic):
        spawn("elastic", "--server", url, "--identity", f"elastic-{i}",
              "--metrics-port", "-1")
    spawn("kubelet", "--server", url)

    time.sleep(0.3)
    dead = [p for p in procs if p.poll() is not None]
    if dead:
        announce("error: a component exited at startup", flush=True)
        for p in procs:
            if p.poll() is None:
                p.terminate()
        return 1
    announce(
        f"control plane up: 1 apiserver, {schedulers} scheduler(s), "
        f"{controllers} controller(s), 1 kubelet "
        f"(submit with: vtctl --server {url} job run ...)", flush=True,
    )

    with open(pidfile, "w") as f:
        json.dump({"url": url, "pids": [p.pid for p in procs]}, f)

    if detach:
        if log is not None:
            log.close()
        return 0
    try:
        while all(p.poll() is None for p in procs):
            time.sleep(0.5)
        announce("a component exited; shutting down", flush=True)
        code = 1
    except KeyboardInterrupt:
        code = 0
    finally:
        for p in reversed(procs):  # kubelet/controller first, apiserver last
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        try:
            os.unlink(pidfile)
        except OSError:
            pass
    return code


def run_down(pidfile: str = ".vt-up.json", announce=print) -> int:
    """Tear down a detached ``run_up`` control plane via its pidfile."""
    import json

    try:
        with open(pidfile) as f:
            info = json.load(f)
    except (OSError, ValueError):
        announce(f"no control plane found ({pidfile})", flush=True)
        return 1
    pids = info.get("pids", [])
    for pid in reversed(pids):
        try:
            os.kill(pid, signal.SIGTERM)
        except ProcessLookupError:
            pass

    def survivors():
        out = []
        for pid in pids:
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                continue
            out.append(pid)
        return out

    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and survivors():
        time.sleep(0.1)
    left = survivors()
    if left:
        # grace expired (e.g. a scheduler mid-XLA-compile): escalate
        for pid in left:
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        time.sleep(0.2)
        left = survivors()
    try:
        os.unlink(pidfile)
    except OSError:
        pass
    if left:
        announce(f"warning: pids still alive after SIGKILL: {left}",
                 flush=True)
        return 1
    announce("control plane stopped", flush=True)
    return 0
