"""vtctl: the user-facing CLI (reference vkctl, cmd/cli + pkg/cli/job).

``python -m volcano_tpu.cli`` drives a persisted simulated cluster; the
command functions also operate on any live Store for embedding.
"""

from volcano_tpu.cli.vtctl import (
    build_job_from_flags,
    cmd_cordon,
    cmd_describe_job,
    cmd_describe_pod,
    cmd_drain,
    cmd_events,
    cmd_list,
    cmd_node_list,
    cmd_pool_list,
    cmd_profile,
    cmd_resume,
    cmd_run,
    cmd_suspend,
    cmd_top,
    cmd_trace_render,
    cmd_uncordon,
    main,
)

__all__ = [
    "build_job_from_flags",
    "cmd_cordon",
    "cmd_describe_job",
    "cmd_describe_pod",
    "cmd_drain",
    "cmd_events",
    "cmd_list",
    "cmd_node_list",
    "cmd_pool_list",
    "cmd_profile",
    "cmd_resume",
    "cmd_run",
    "cmd_suspend",
    "cmd_top",
    "cmd_trace_render",
    "cmd_uncordon",
    "main",
]
