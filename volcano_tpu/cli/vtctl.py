"""vtctl command implementations + argparse entry.

Parity sources:
  * job run     — reference pkg/cli/job/run.go:30-108 (flags image/
    namespace/name/min/replicas/requests; single-task job)
  * job list    — reference pkg/cli/job/list.go:60-112 (column layout)
  * suspend     — reference pkg/cli/job/suspend.go:38-49 -> Command CR
    with AbortJob action (util.go:72-99)
  * resume      — reference pkg/cli/job/resume.go -> ResumeJob Command

The reference CLI talks to the API server; here commands target a Store.
The ``__main__`` entry persists a simulated Cluster between invocations
(``--state`` pickle), so run/list/suspend/resume round-trips work from a
shell the way the reference e2e drives the real binary (cli_util.go).
"""

from __future__ import annotations

import argparse
import io
import pickle
import sys
from typing import Optional

from volcano_tpu import trace
from volcano_tpu.api.job import Job, JobSpec, TaskSpec
from volcano_tpu.api.objects import Command, Metadata, PodSpec
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobAction


def parse_resource_list(spec: str) -> Resource:
    """cpu=1000m,memory=100Mi -> Resource (run.go populateResourceListV1)."""
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, value = part.partition("=")
        if not value:
            raise ValueError(f"bad resource entry {part!r}, want key=value")
        out[key.strip()] = value.strip()
    return Resource.from_resource_list(out)


def build_job_from_flags(
    name: str = "test",
    namespace: str = "default",
    image: str = "busybox",
    min_available: int = 1,
    replicas: int = 1,
    requests: str = "cpu=1000m,memory=100Mi",
    scheduler: str = "volcano-tpu",
    queue: str = "",
) -> Job:
    template = PodSpec(
        resources=parse_resource_list(requests),
        image=image,
        scheduler_name=scheduler,
        restart_policy="Never",
    )
    return Job(
        meta=Metadata(name=name, namespace=namespace),
        spec=JobSpec(
            scheduler_name=scheduler,
            min_available=min_available,
            queue=queue,
            tasks=[TaskSpec(name=name, replicas=replicas, template=template)],
        ),
    )


def _traced_job_create(job: Job, create):
    """The trace ROOT shared by the local and remote submission paths:
    with tracing armed (VOLCANO_TPU_TRACE) the span's trace id is stamped
    into the Job annotation and follows the gang through controller ->
    scheduler -> bind -> kubelet Ready flip."""
    with trace.span("vtctl.job.run", job=job.meta.key):
        trace.stamp(job.meta)
        return create(job)


def cmd_run(store, **flags) -> Job:
    """Create a job from flags, through the shared admission gate."""
    from volcano_tpu.admission import admit_and_create

    return _traced_job_create(
        build_job_from_flags(**flags),
        lambda job: admit_and_create(store, job),
    )


_COLUMNS = (
    "Name", "Creation", "Phase", "Replicas", "Min",
    "Pending", "Running", "Succeeded", "Failed", "RetryCount",
)


def cmd_list(store, namespace: str = "default", out: Optional[io.TextIOBase] = None) -> str:
    """Table of jobs in the namespace (list.go:79-100 column layout)."""
    jobs = [j for j in store.list("Job") if j.meta.namespace == namespace]
    buf = io.StringIO()
    if not jobs:
        buf.write("No resources found\n")
    else:
        name_w = max([len("Name")] + [len(j.meta.name) for j in jobs]) + 3
        widths = (name_w, 12, 12, 10, 6, 9, 9, 11, 8, 12)
        row = "".join(f"%-{w}s" for w in widths) + "\n"
        buf.write(row % _COLUMNS)
        import time

        for job in jobs:
            st = job.status
            created = (
                time.strftime("%H:%M:%S", time.localtime(job.meta.creation_timestamp))
                if job.meta.creation_timestamp
                else "<none>"
            )
            buf.write(
                row
                % (
                    job.meta.name,
                    created,
                    st.state.phase.value,
                    job.spec.total_replicas(),
                    st.min_available,
                    st.pending,
                    st.running,
                    st.succeeded,
                    st.failed,
                    st.retry_count,
                )
            )
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


# -- node / pool verbs (elastic capacity; kubectl cordon/drain analogues) -----


def cmd_cordon(store, name: str):
    """Mark the node unschedulable (kubectl cordon)."""
    from volcano_tpu.elastic import cordon

    return cordon(store, name)


def cmd_uncordon(store, name: str):
    from volcano_tpu.elastic import uncordon

    return uncordon(store, name)


def cmd_drain(store, name: str):
    """Cordon + evict resident pods through the existing eviction path
    (pods marked deleting; the kubelet reaps them — the Releasing window).
    Returns the evicted pod keys."""
    from volcano_tpu.elastic import drain

    _, evicted = drain(store, name)
    return evicted


def cmd_node_list(store, out: Optional[io.TextIOBase] = None) -> str:
    """Node table: kubectl-style STATUS including SchedulingDisabled for
    cordoned nodes, plus the elastic lifecycle state and owning pool."""
    from volcano_tpu.elastic import POOL_LABEL, node_state

    nodes = sorted(store.list("Node"), key=lambda n: n.meta.name)
    buf = io.StringIO()
    if not nodes:
        buf.write("No resources found\n")
    else:
        pods_on = {}
        for p in store.list("Pod"):
            if p.node_name and not p.deleting:
                pods_on[p.node_name] = pods_on.get(p.node_name, 0) + 1
        name_w = max([len("Name")] + [len(n.meta.name) for n in nodes]) + 3
        row = f"%-{name_w}s%-28s%-15s%-12s%-6s\n"
        buf.write(row % ("Name", "Status", "State", "Pool", "Pods"))
        for n in nodes:
            status = "Ready" if n.ready() else "NotReady"
            if n.unschedulable:
                status += ",SchedulingDisabled"
            buf.write(row % (
                n.meta.name, status, node_state(n),
                n.labels.get(POOL_LABEL, "<none>"),
                pods_on.get(n.meta.name, 0),
            ))
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


def cmd_pool_list(store, out: Optional[io.TextIOBase] = None) -> str:
    """NodePool table: size bounds + observed lifecycle counts."""
    pools = sorted(store.list("NodePool"), key=lambda p: p.meta.name)
    buf = io.StringIO()
    if not pools:
        buf.write("No resources found\n")
    else:
        name_w = max([len("Name")] + [len(p.meta.name) for p in pools]) + 3
        row = f"%-{name_w}s%-6s%-6s%-7s%-7s%-14s%-10s%-8s\n"
        buf.write(row % ("Name", "Min", "Max", "Size", "Ready",
                         "Provisioning", "Draining", "Demand"))
        for p in pools:
            st = p.status
            buf.write(row % (
                p.meta.name, p.min_size, p.max_size, st.size, st.ready,
                st.provisioning, st.draining, st.pending_demand,
            ))
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


# -- describe / events / trace (decision-level explainability) ----------------


def _why_lines(pg) -> list:
    """The "why is this gang not running" verdict: the True conditions the
    scheduler cycle wrote on the PodGroup (gang/predicate/proportion
    reasons, e.g. "0/3 nodes are available, 3 insufficient cpu.")."""
    return [
        f"  {c.kind:<16}{c.reason:<22}{c.message}"
        for c in pg.status.conditions
        if c.status == "True"
    ]


def _event_lines(evs) -> list:
    return [
        f"  {e.type:<9}{e.reason:<16}x{e.count:<4}{e.message}"
        for e in sorted(evs, key=lambda e: e.meta.uid)
    ]


def cmd_describe_job(store, namespace: str = "default", name: str = "",
                     out: Optional[io.TextIOBase] = None) -> str:
    """kubectl-describe analogue for a Job: status, the gang's
    Unschedulable verdict, per-pod placement, and the event stream."""
    from volcano_tpu import events as cluster_events
    from volcano_tpu.api.job import JOB_NAME_KEY

    key = f"{namespace}/{name}"
    job = store.get("Job", key)
    if job is None:
        raise KeyError(f"job {key} not found")
    pg = store.get("PodGroup", key)
    pods = [
        p for p in store.list("Pod")
        if p.meta.namespace == namespace
        and p.meta.annotations.get(JOB_NAME_KEY) == name
    ]
    buf = io.StringIO()
    st = job.status
    buf.write(f"Name:      {key}\n")
    buf.write(f"Phase:     {st.state.phase.value}\n")
    buf.write(f"Queue:     {job.spec.queue or 'default'}\n")
    buf.write(f"Min/Total: {job.spec.min_available}"
              f"/{job.spec.total_replicas()}\n")
    tid = trace.gang_trace(job.meta)
    if tid:
        buf.write(f"Trace:     {tid}\n")
    if pg is not None:
        buf.write(f"PodGroup:  {pg.status.phase.value}\n")
        why = _why_lines(pg)
        if why:
            buf.write("Conditions (why):\n")
            buf.write("\n".join(why) + "\n")
    if pods:
        buf.write("Pods:\n")
        for p in sorted(pods, key=lambda p: p.meta.name):
            buf.write(f"  {p.meta.name:<30}{p.phase.value:<12}"
                      f"{p.node_name or '<none>'}\n")
    evs = (cluster_events.events_for(store, "Job", key)
           + cluster_events.events_for(store, "PodGroup", key))
    if evs:
        buf.write("Events:\n")
        buf.write("\n".join(_event_lines(evs)) + "\n")
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


def cmd_describe_pod(store, namespace: str = "default", name: str = "",
                     out: Optional[io.TextIOBase] = None) -> str:
    """Per-pod view: phase/placement, its events, and — for a pending
    unbound pod — the owning gang's "why" verdict."""
    from volcano_tpu import events as cluster_events
    from volcano_tpu.api.job import POD_GROUP_KEY
    from volcano_tpu.api.types import PodPhase

    key = f"{namespace}/{name}"
    pod = store.get("Pod", key)
    if pod is None:
        raise KeyError(f"pod {key} not found")
    buf = io.StringIO()
    buf.write(f"Name:   {key}\n")
    buf.write(f"Phase:  {pod.phase.value}\n")
    buf.write(f"Node:   {pod.node_name or '<none>'}\n")
    tid = trace.gang_trace(pod.meta)
    if tid:
        buf.write(f"Trace:  {tid}\n")
    if pod.phase == PodPhase.PENDING and not pod.node_name:
        group = pod.meta.annotations.get(POD_GROUP_KEY, "")
        pg = store.get("PodGroup", f"{namespace}/{group}") if group else None
        if pg is not None:
            why = _why_lines(pg)
            if why:
                buf.write("Pending because (gang verdict):\n")
                buf.write("\n".join(why) + "\n")
    evs = cluster_events.events_for(store, "Pod", key)
    if evs:
        buf.write("Events:\n")
        buf.write("\n".join(_event_lines(evs)) + "\n")
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


def cmd_events(store, namespace: str = "",
               out: Optional[io.TextIOBase] = None) -> str:
    """The cluster event stream (kubectl get events), oldest first."""
    evs = sorted(store.list("Event"), key=lambda e: e.meta.uid)
    if namespace:
        evs = [e for e in evs if e.involved[1].startswith(namespace + "/")]
    buf = io.StringIO()
    if not evs:
        buf.write("No resources found\n")
    else:
        row = "%-9s%-16s%-7s%-36s%s\n"
        buf.write(row % ("Type", "Reason", "Count", "Object", "Message"))
        for e in evs:
            buf.write(row % (e.type, e.reason, e.count,
                             f"{e.involved[0]}/{e.involved[1]}", e.message))
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


def _phase_summary(phases: dict, top: int = 3) -> str:
    """The ``top`` costliest phases of one cycle, ``name=seconds``."""
    if not phases:
        return ""
    items = sorted(phases.items(), key=lambda kv: -kv[1])[:top]
    return " ".join(f"{k}={v:.3f}" for k, v in items)


def _dev_host_cell(s: dict) -> str:
    """``dev/host`` milliseconds for one cycle sample — present only when
    the vtprof profiler enriched the row (scheduler._record_cycle)."""
    if "device_s" not in s and "host_s" not in s:
        return "-"
    dev = (s.get("device_s") or 0.0) + (s.get("transfer_s") or 0.0)
    return f"{dev * 1e3:.1f}/{(s.get('host_s') or 0.0) * 1e3:.1f}"


def cmd_top(samples, out: Optional[io.TextIOBase] = None, n: int = 12,
            now: Optional[float] = None) -> str:
    """Render the per-cycle time-series ring (volcano_tpu/timeseries.py)
    as a live control-plane dashboard: last ``n`` scheduler cycles with
    duration / device-host split / backlog / binds / drain lag / top
    phases, a window percentile summary, an anomaly line (vtprof
    sentinel trips: steady-state recompiles, leak-sentinel hits), and
    the newest store-side sample (event-log position + WAL fsync
    accounting)."""
    import time as _time

    now = _time.time() if now is None else now
    cycles = [s for s in samples if s.get("kind") == "cycle"]
    stores = [s for s in samples if s.get("kind") == "store"]
    anomalies = [s for s in samples if s.get("kind") == "anomaly"]
    buf = io.StringIO()
    if not samples:
        buf.write("no time-series samples (arm the recorder with "
                  "VOLCANO_TPU_TIMESERIES=1)\n")
    else:
        row = "%-8s%-8s%-10s%-12s%-8s%-9s%-7s%-7s%-7s%s\n"
        buf.write(row % ("Cycle", "Age", "Dur(ms)", "Dev/Host", "Path",
                         "Backlog", "Binds", "Evict", "Drain", "Phases"))
        for s in cycles[-n:]:
            buf.write(row % (
                s.get("cycle", "-"),
                f"{max(now - s.get('ts', now), 0.0):.1f}s",
                f"{s.get('dur_s', 0.0) * 1e3:.1f}",
                _dev_host_cell(s),
                s.get("path", "-"),
                s.get("backlog", "-"),
                s.get("binds", "-"),
                s.get("evictions", "-"),
                s.get("drain_pending", "-"),
                _phase_summary(s.get("phases") or {}),
            ))
        if anomalies:
            kinds: dict = {}
            for a in anomalies:
                kinds[a.get("anomaly", "?")] = \
                    kinds.get(a.get("anomaly", "?"), 0) + 1
            last = anomalies[-1]
            buf.write(
                "anomalies: "
                + " ".join(f"{k}x{v}" for k, v in sorted(kinds.items()))
                + f" (last: {last.get('anomaly')} @ cycle "
                  f"{last.get('cycle', '?')})\n"
            )
        if cycles:
            durs = sorted(s.get("dur_s", 0.0) for s in cycles)
            p = lambda q: durs[min(int(q * len(durs)), len(durs) - 1)] * 1e3  # noqa: E731
            buf.write(
                f"cycles: {len(durs)} sampled, dur p50 {p(0.5):.1f}ms "
                f"p99 {p(0.99):.1f}ms max {durs[-1] * 1e3:.1f}ms\n"
            )
        # vtdelta panel: rows carry mode/fallback_reason only while
        # conf.delta is on — absent fields mean the panel stays silent
        dmodes = [s.get("mode") for s in cycles if s.get("mode")]
        if dmodes:
            micro = sum(1 for v in dmodes if v == "micro")
            reasons: dict = {}
            for s in cycles:
                r = s.get("fallback_reason")
                if r:
                    reasons[r] = reasons.get(r, 0) + 1
            last = cycles[-1]
            line = (f"delta: {micro}/{len(dmodes)} micro, "
                    f"backlog={last.get('backlog_gangs', 0)} gangs "
                    f"(held={last.get('held_gangs', 0)} "
                    f"shed={last.get('shed_gangs', 0)})")
            if reasons:
                line += " fallbacks: " + " ".join(
                    f"{k}x{v}" for k, v in sorted(reasons.items())
                )
            buf.write(line + "\n")
        # multi-controller panel: the newest cycle sample carrying
        # per-host solve walls (meshHosts > 1 deployments / lockstep sim)
        mh = next((s.get("mesh_hosts") for s in reversed(cycles)
                   if s.get("mesh_hosts")), None)
        if mh:
            buf.write("mesh hosts (build/dispatch/fetch, cumulative):\n")
            for h, hrow in sorted(mh.items(), key=lambda kv: kv[0]):
                path = sum(hrow.values())
                buf.write(
                    f"  host {h:<4} path={path * 1e3:.1f}ms "
                    + " ".join(f"{k.removesuffix('_s')}={v * 1e3:.1f}ms"
                               for k, v in sorted(hrow.items()))
                    + "\n")
        if stores:
            s = stores[-1]
            line = (f"store: seq={s.get('log_seq')} "
                    f"log_rows={s.get('log_rows')}")
            wal = s.get("wal")
            if wal:
                line += (f" wal: records={wal.get('records')} "
                         f"fsyncs={wal.get('fsync_total')} "
                         f"fsync_s={wal.get('fsync_s')}")
            buf.write(line + "\n")
            repl = s.get("repl")
            if repl:
                line = (f"repl: role={repl.get('role')} "
                        f"epoch={repl.get('epoch')} "
                        f"applied={repl.get('applied')}")
                if repl.get("role") == "leader":
                    line += (f" followers={repl.get('followers', 0)} "
                             f"max_lag_rows={repl.get('max_lag_rows', 0)}")
                else:
                    line += f" lag_s={repl.get('lag_s', 0)}"
                buf.write(line + "\n")
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


def cmd_profile(payload, out: Optional[io.TextIOBase] = None) -> str:
    """Flame-style critical-path report from a vtprof payload (the local
    profiler's or a remote ``/debug/prof`` body): per-phase
    host/dispatch/wait/transfer bars, the per-kernel dispatch/compile
    table, memory watermarks, anomalies."""
    from volcano_tpu import vtprof

    text = vtprof.report_text(payload)
    if out is not None:
        out.write(text)
    return text


# -- vtfleet: cross-process observability plane (volcano_tpu/vtfleet.py) ------


def _parse_daemon_flags(entries) -> list:
    """``--daemon name=url`` flags -> ``[(name, url)]``, order kept."""
    out = []
    for entry in entries or []:
        name, sep, url = entry.partition("=")
        if not sep or not name.strip() or not url.strip():
            raise ValueError(f"bad --daemon entry {entry!r}, "
                             "want name=http://host:port")
        out.append((name.strip(), url.strip().rstrip("/")))
    return out


def _fleet_snapshot(args) -> dict:
    """One harvest round for a ``--fleet`` command: the --server front
    (router or plain store) plus any --daemon sidecars; without --server
    the in-process rings are harvested, so embedders and tests get the
    same report shape a live mesh produces."""
    from volcano_tpu import vtfleet

    daemons = _parse_daemon_flags(getattr(args, "daemon", None))
    if getattr(args, "server", ""):
        return vtfleet.harvest(args.server, daemons=daemons)
    return vtfleet.harvest(None, daemons=daemons, include_local=True)


def _fleet_proc_lines(merged: dict, counted: str) -> str:
    """The provenance header every fleet report opens with: one line per
    harvested proc (pid / ring depth / clock offset), one UNREACHABLE
    line per proc the harvest could not reach — a dead shard must be
    VISIBLE in the report, not an error that hides the live ones."""
    buf = io.StringIO()
    for name in sorted(merged.get("procs") or {}):
        m = merged["procs"][name]
        buf.write(f"proc {name:<12} pid={m.get('pid')} "
                  f"{counted}={m.get(counted, 0)} "
                  f"offset={m.get('offset_s', 0.0):+.3f}s"
                  + ("" if m.get("armed") else "  (disarmed)") + "\n")
    for name in merged.get("unreachable") or []:
        buf.write(f"proc {name:<12} UNREACHABLE (harvest degraded)\n")
    return buf.getvalue()


def cmd_trace_fleet(snap, trace_id: str = "",
                    out: Optional[io.TextIOBase] = None) -> str:
    """One gang's timeline across every harvested process: spans merge
    onto the harvester's clock (vtfleet.merge_trace) and render as the
    usual span tree — router span, shard apply, replica apply and
    scheduler cycle interleave in true order."""
    from volcano_tpu import vtfleet

    merged = vtfleet.merge_trace(snap)
    buf = io.StringIO()
    buf.write(_fleet_proc_lines(merged, "spans"))
    records = merged["spans"]
    if not records:
        buf.write("no spans recorded in any harvested proc (arm tracing "
                  "with VOLCANO_TPU_TRACE=1)\n")
    else:
        for r in records:
            # spans from a proc that never set a component label still
            # need cross-process attribution in the tree
            if not r.get("component"):
                r["component"] = r.get("proc", "")
        buf.write(trace.render_tree(
            records, trace_id or trace.latest_trace(records)))
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


def cmd_top_fleet(snap, out: Optional[io.TextIOBase] = None,
                  n: int = 12) -> str:
    """The fleet dashboard: per-shard apply/fsync/lag table with the
    straggler verdict (vtfleet.top_fleet_text), then the merged
    time-series ring through the usual ``vtctl top`` renderer."""
    from volcano_tpu import vtfleet

    buf = io.StringIO()
    buf.write(vtfleet.top_fleet_text(snap))
    merged = vtfleet.merge_timeseries(snap)
    if merged["samples"]:
        buf.write("\n")
        cmd_top(merged["samples"], out=buf, n=n)
    else:
        buf.write("no time-series samples in any harvested proc (arm the "
                  "recorder with VOLCANO_TPU_TIMESERIES=1)\n")
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


def cmd_profile_fleet(snap, out: Optional[io.TextIOBase] = None) -> str:
    """The fleet profile: the first harvested proc with cycle samples
    renders the usual critical-path report, then the cross-process drain
    attribution joins the applier's per-shard walls with each shard's
    server-side fsync time (vtfleet.critical_path_text)."""
    from volcano_tpu import vtfleet, vtprof

    merged = vtfleet.merge_prof(snap)
    buf = io.StringIO()
    best = None
    for name in sorted(merged["procs"]):
        if (merged["procs"][name] or {}).get("cycles"):
            best = name
            break
    if best is None:
        buf.write("no profile samples in any harvested proc (arm the "
                  "profiler with VOLCANO_TPU_PROF=1)\n")
    else:
        buf.write(f"profile from proc {best}:\n")
        buf.write(vtprof.report_text(merged["procs"][best]))
    for name in merged.get("unreachable") or []:
        buf.write(f"proc {name:<12} UNREACHABLE (harvest degraded)\n")
    buf.write(vtfleet.critical_path_text(snap))
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


def cmd_describe_job_fleet(store, args,
                           out: Optional[io.TextIOBase] = None) -> str:
    """``describe job --fleet``: the ordinary describe body, then the
    gang's cross-process span timeline (the trace id stamped on the job
    annotation, reassembled from every reachable proc)."""
    buf = io.StringIO()
    cmd_describe_job(store, args.namespace, args.name, out=buf)
    job = store.get("Job", f"{args.namespace}/{args.name}")
    tid = trace.gang_trace(job.meta) if job is not None else ""
    buf.write("Fleet trace:\n")
    cmd_trace_fleet(_fleet_snapshot(args), trace_id=tid, out=buf)
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


def _fetch_debug(server_url: str, path: str):
    """GET one /debug/* admin payload from a remote daemon."""
    import json as _json
    import urllib.request

    with urllib.request.urlopen(
        server_url.rstrip("/") + path, timeout=10
    ) as r:
        return _json.load(r)


def _fetch_debug_prof(server_url: str) -> dict:
    """The remote profile: GET <server>/debug/prof."""
    return _fetch_debug(server_url, "/debug/prof")


def _fetch_debug_timeseries(server_url: str) -> list:
    """The remote time-series ring: GET <server>/debug/timeseries."""
    return _fetch_debug(server_url, "/debug/timeseries").get("samples") or []


def cmd_replica_list(urls, out: Optional[io.TextIOBase] = None) -> str:
    """One row per replica URL: role / epoch / applied seq / follower
    ack ledger, from each server's ``/repl/status``.  Unreachable or
    un-armed replicas render as rows too — a dead follower should be
    VISIBLE in the panel, not silently dropped."""
    buf = io.StringIO()
    row = "%-28s%-10s%-7s%-10s%-9s%s\n"
    buf.write(row % ("Replica", "Role", "Epoch", "Applied", "Unsynced",
                     "Followers (acked/lag_rows/age_s)"))
    for url in urls:
        try:
            st = _fetch_debug(url, "/repl/status")
        except Exception as e:  # noqa: BLE001 — keep probing the rest
            buf.write(row % (url, "down", "-", "-", "-", repr(e)))
            continue
        fol = st.get("followers") or {}
        cell = " ".join(
            f"{fid}={f.get('acked')}/{f.get('lag_rows')}/{f.get('age_s')}"
            for fid, f in sorted(fol.items())
        ) or "-"
        buf.write(row % (st.get("identity", url), st.get("role", "?"),
                         st.get("epoch", "-"), st.get("applied", "-"),
                         st.get("unsynced", "-"), cell))
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


def cmd_shard_list(server_url: str,
                   out: Optional[io.TextIOBase] = None) -> str:
    """One row per procmesh member (``/procmesh/shards``, served by the
    router/supervisor): shard index, role, URL, pid, liveness, restart
    count — the operator's view of a multi-process store."""
    st = _fetch_debug(server_url, "/procmesh/shards")
    buf = io.StringIO()
    buf.write(f"shards={st.get('shards', '?')}  "
              f"replicas={st.get('replicas', 1)}  "
              f"seq={st.get('seq', '-')}  "
              f"restarts={st.get('restarts', 0)}\n")
    row = "%-7s%-10s%-28s%-9s%-7s%s\n"
    buf.write(row % ("Shard", "Role", "URL", "Pid", "Alive", "Restarts"))
    for m in st.get("members") or []:
        buf.write(row % (m.get("shard", "?"), m.get("role", "?"),
                         m.get("url", "?"), m.get("pid", "-"),
                         {True: "yes", False: "NO"}.get(m.get("alive"), "?"),
                         m.get("restarts", 0)))
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


# -- vtaudit: state-digest audit (volcano_tpu/vtaudit.py) ---------------------


def _audit_localize(maint, truth):
    """The localization walk over two DigestTables: mismatched
    (kind, namespace) buckets -> mismatched objects.  Returns sorted
    ``(kind, namespace, name, maintained_hex, actual_hex)`` rows."""
    from volcano_tpu import vtaudit

    zero = vtaudit.hexd(0)
    out = []
    for bk in vtaudit.diff_maps(maint.bucket_payload(),
                                truth.bucket_payload()):
        kind, _, ns = bk.partition("|")
        a = maint.object_payload(kind, ns)
        b = truth.object_payload(kind, ns)
        for key in vtaudit.diff_maps(a, b):
            out.append((kind, ns, key.rpartition("/")[2],
                        a.get(key, zero), b.get(key, zero)))
    return sorted(out)


def cmd_audit_local(store, out: Optional[io.TextIOBase] = None) -> str:
    """Audit a local store: the incrementally maintained digest against
    a ground-truth recompute from the objects, localized on mismatch."""
    from volcano_tpu import vtaudit

    buf = io.StringIO()
    truth = store.recompute_digest()
    maint = store._digest
    if maint is None:
        buf.write("digest maintenance disarmed (VOLCANO_TPU_AUDIT=0); "
                  f"recomputed root={vtaudit.hexd(truth.root())}\n")
    else:
        bad = _audit_localize(maint, truth)
        if not bad:
            nobj = sum(len(m) for m in maint.objd.values())
            buf.write(f"state digest OK  root={vtaudit.hexd(maint.root())}"
                      f"  objects={nobj}\n")
        else:
            buf.write("STATE DIGEST DIVERGENCE  "
                      f"maintained={vtaudit.hexd(maint.root())}  "
                      f"actual={vtaudit.hexd(truth.root())}\n")
            for kind, ns, name, mine, actual in bad:
                buf.write(f"  {kind} {ns}/{name}: maintained={mine} "
                          f"actual={actual}\n")
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


def cmd_audit_remote(server_url: str,
                     out: Optional[io.TextIOBase] = None,
                     retries: int = 5) -> str:
    """Audit a remote store server three ways: the incrementally
    maintained /debug/digest rollups against a server-side ground-truth
    recompute of the raw objects (``?recompute=1`` — catches state
    corruption that bypassed the mutation verbs), walking
    shard -> bucket -> object on mismatch, plus a client-side recompute
    from the wire lists (catches serving-cache / transport drift).

    The walk is not seq-pinned, so a mutation landing mid-walk — on a
    replicated control plane a background lease renewal is enough —
    makes a clean server look diverged.  A diverged pass that also saw
    ``seq`` move is therefore retried (up to ``retries`` passes): only
    divergence observed with a stable seq, or reproduced on every
    pass, is reported."""
    text = _audit_remote_pass(server_url)
    for _ in range(max(1, retries) - 1):
        if "DIVERGENCE" not in text or "state moved during audit" not in text:
            break
        text = _audit_remote_pass(server_url)
    if out is not None:
        out.write(text)
    return text


def _audit_remote_pass(server_url: str) -> str:
    """One (unpinned) audit walk — see ``cmd_audit_remote``."""
    from urllib.parse import quote

    from volcano_tpu import vtaudit
    from volcano_tpu.store.client import RemoteStore

    buf = io.StringIO()
    dbg = _fetch_debug(server_url, "/debug/digest")
    if not dbg.get("enabled"):
        buf.write("server digest maintenance disarmed "
                  "(VOLCANO_TPU_AUDIT=0)\n")
        return buf.getvalue()
    shards = max(1, len(dbg.get("shards") or []))
    truth = _fetch_debug(server_url, "/debug/digest?recompute=1")
    rs = RemoteStore(server_url)
    wire = vtaudit.table_from_objects(
        (kind, obj) for kind in sorted(vtaudit.AUDITED_KINDS)
        for obj in rs.list(kind)
    )
    wire_root = vtaudit.hexd(wire.root())
    bad_shards = [i for i, (a, b) in enumerate(zip(dbg["shards"],
                                                   truth["shards"]))
                  if a != b]
    if not bad_shards and wire_root == truth["root"]:
        buf.write(f"state digest OK  root={dbg['root']}  seq={dbg['seq']}"
                  f"  shards={shards}\n")
    else:
        zero = vtaudit.hexd(0)
        if bad_shards:
            buf.write(f"STATE DIGEST DIVERGENCE  shards={bad_shards}  "
                      f"maintained={dbg['root']}  actual={truth['root']}\n")
            srv_buckets = _fetch_debug(
                server_url, "/debug/digest?detail=buckets")["buckets"]
            true_buckets = _fetch_debug(
                server_url,
                "/debug/digest?recompute=1&detail=buckets")["buckets"]
            for bk in vtaudit.diff_maps(srv_buckets, true_buckets):
                kind, _, ns = bk.partition("|")
                tier = f"kind={quote(kind)}&namespace={quote(ns)}"
                srv_objs = _fetch_debug(
                    server_url, f"/debug/digest?{tier}")["objects"]
                true_objs = _fetch_debug(
                    server_url,
                    f"/debug/digest?recompute=1&{tier}")["objects"]
                for key in vtaudit.diff_maps(srv_objs, true_objs):
                    buf.write(f"  {kind} {ns}/{key.rpartition('/')[2]}: "
                              f"maintained={srv_objs.get(key, zero)} "
                              f"actual={true_objs.get(key, zero)}\n")
        if wire_root != truth["root"]:
            buf.write("WIRE DIGEST DIVERGENCE  (served list encodings "
                      f"disagree with raw state)  wire={wire_root}  "
                      f"actual={truth['root']}\n")
            for bk in vtaudit.diff_maps(
                    wire.bucket_payload(None, shards),
                    _fetch_debug(
                        server_url,
                        "/debug/digest?recompute=1&detail=buckets"
                    )["buckets"]):
                kind, _, ns = bk.partition("|")
                my_objs = wire.object_payload(kind, ns)
                true_objs = _fetch_debug(
                    server_url,
                    "/debug/digest?recompute=1&"
                    f"kind={quote(kind)}&namespace={quote(ns)}")["objects"]
                for key in vtaudit.diff_maps(my_objs, true_objs):
                    buf.write(f"  {kind} {ns}/{key.rpartition('/')[2]}: "
                              f"wire={my_objs.get(key, zero)} "
                              f"actual={true_objs.get(key, zero)}\n")
        # the walk above is not seq-pinned: if the server moved while
        # we walked, a clean server can look diverged — say so
        seq2 = _fetch_debug(server_url, "/debug/digest").get("seq")
        if seq2 != dbg.get("seq"):
            buf.write(f"  (state moved during audit: seq {dbg.get('seq')}"
                      f" -> {seq2}; re-run to confirm)\n")
    return buf.getvalue()


def cmd_audit_wal(wal_dir: str, state: str = "", server_url: str = "",
                  out: Optional[io.TextIOBase] = None) -> str:
    """Replay a snapshot+WAL lineage into a digest (scratch copy — the
    live lineage is never touched) and, with --server, verify it against
    the live server's current digest."""
    from volcano_tpu import vtaudit

    buf = io.StringIO()
    state_path = state or (wal_dir[:-4] if wal_dir.endswith(".wal")
                           else wal_dir)
    res = vtaudit.replay_wal_digest(state_path)
    dg = res["digest"]
    if dg is None:
        buf.write("digest maintenance disarmed (VOLCANO_TPU_AUDIT=0); "
                  "nothing to verify\n")
    else:
        buf.write(f"WAL replay digest  root={dg['root']}  seq={res['seq']}"
                  f"  shards={res['shards']}  "
                  f"replayed={res['replayed_records']}  "
                  f"torn_tails={res['torn_tails']}\n")
        if server_url:
            live = _fetch_debug(server_url, "/debug/digest")
            verdict = ("MATCH" if live.get("root") == dg["root"]
                       else "MISMATCH")
            buf.write(f"live server root={live.get('root')}  "
                      f"seq={live.get('seq')}  {verdict}\n")
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


def cmd_trace_render(records, trace_id: str = "",
                     out: Optional[io.TextIOBase] = None) -> str:
    """Span tree for one trace — the given id, or the most recent trace
    in the flight recorder (``vtctl trace last``)."""
    buf = io.StringIO()
    if not records:
        buf.write("no spans recorded (arm tracing with "
                  "VOLCANO_TPU_TRACE=1)\n")
    else:
        buf.write(trace.render_tree(
            records, trace_id or trace.latest_trace(records)))
    text = buf.getvalue()
    if out is not None:
        out.write(text)
    return text


def _fetch_debug_trace(server_url: str) -> list:
    """The remote flight recorder: GET <server>/debug/trace."""
    return _fetch_debug(server_url, "/debug/trace").get("spans") or []


def _local_trace_records(state_path: str) -> list:
    """Local-mode flight recorder: the live in-process ring when armed
    (embedders/tests), else the sidecar dump the previous armed
    invocation wrote next to the --state file."""
    import json as _json

    if trace.TRACER is not None:
        recs = trace.TRACER.records()
        if recs:
            return recs
    try:
        with open(state_path + ".trace.json", encoding="utf-8") as f:
            return _json.load(f).get("spans") or []
    except (OSError, ValueError):
        return []


def _issue_command(store, namespace: str, name: str, action: JobAction) -> Command:
    from volcano_tpu.api.objects import new_uid

    if store.get("Job", f"{namespace}/{name}") is None:
        raise KeyError(f"job {namespace}/{name} not found")
    # generated suffix keeps repeated suspend/resume idempotent-safe: the
    # controller consumes commands by target, not name
    cmd = Command(
        meta=Metadata(name=new_uid(f"{action.value.lower()}-{name}"), namespace=namespace),
        action=action.value,
        target=("Job", name),
    )
    return store.create("Command", cmd)


def cmd_suspend(store, namespace: str, name: str) -> Command:
    """AbortJob via Command CR (suspend.go:38-49)."""
    return _issue_command(store, namespace, name, JobAction.ABORT_JOB)


def cmd_resume(store, namespace: str, name: str) -> Command:
    """ResumeJob via Command CR."""
    return _issue_command(store, namespace, name, JobAction.RESUME_JOB)


def _main_remote(args) -> int:
    """job/cluster commands against a remote store server — the reference's
    vkctl-to-API-server path. No local state; admission runs server-side."""
    from volcano_tpu.store.client import RemoteStore

    store = RemoteStore(args.server)
    try:
        if args.group == "cluster" and args.cmd == "init":
            from volcano_tpu.api.objects import Metadata, Node, Queue

            for entry in args.queues.split(","):
                qname, _, weight = entry.partition("=")
                qname = qname.strip()
                if store.get("Queue", f"/{qname}") is None:
                    store.create("Queue", Queue(
                        meta=Metadata(name=qname, namespace=""),
                        weight=int(weight or 1)))
            for i in range(args.nodes):
                name = f"node-{i}"
                if store.get("Node", f"/{name}") is None:
                    store.create("Node", Node(
                        meta=Metadata(name=name, namespace=""),
                        allocatable=Resource.from_resource_list(
                            {"cpu": args.cpu, "memory": args.memory, "pods": 110})))
            print(f"initialized remote cluster: {args.nodes} nodes")
        elif args.group == "cluster":
            print("error: cluster step is local-only (daemons drive the "
                  "remote cluster)", file=sys.stderr)
            return 1
        elif args.group == "node":
            rc = _node_dispatch(store, args)
            if rc is not None:
                return rc
        elif args.group == "pool":
            cmd_pool_list(store, out=sys.stdout)
        elif args.group == "describe":
            if args.cmd == "job" and getattr(args, "fleet", False):
                cmd_describe_job_fleet(store, args, out=sys.stdout)
            elif args.cmd == "job":
                cmd_describe_job(store, args.namespace, args.name,
                                 out=sys.stdout)
            else:
                cmd_describe_pod(store, args.namespace, args.name,
                                 out=sys.stdout)
        elif args.group == "events":
            cmd_events(store, namespace=args.namespace, out=sys.stdout)
        elif args.group == "trace":
            records = _fetch_debug_trace(args.server)
            if args.cmd == "dump":
                import json as _json

                print(_json.dumps(records))
            else:
                cmd_trace_render(records, trace_id=args.trace,
                                 out=sys.stdout)
        elif args.cmd == "run":
            # server-side admission mutates/validates (the webhook path)
            _traced_job_create(
                build_job_from_flags(
                    name=args.name, namespace=args.namespace,
                    image=args.image, min_available=args.min_available,
                    replicas=args.replicas, requests=args.requests,
                    queue=args.queue),
                lambda job: store.create("Job", job),
            )
            print(f"job {args.namespace}/{args.name} created")
        elif args.cmd == "list":
            cmd_list(store, namespace=args.namespace, out=sys.stdout)
        elif args.cmd == "suspend":
            cmd_suspend(store, args.namespace, args.name)
            print(f"job {args.namespace}/{args.name} suspend requested")
        elif args.cmd == "resume":
            cmd_resume(store, args.namespace, args.name)
            print(f"job {args.namespace}/{args.name} resume requested")
    except Exception as e:  # surface as CLI error, not traceback
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


def _node_dispatch(store, args) -> Optional[int]:
    """Shared node-verb dispatch for the remote and local entries."""
    if args.cmd == "cordon":
        cmd_cordon(store, args.name)
        print(f"node/{args.name} cordoned")
    elif args.cmd == "uncordon":
        cmd_uncordon(store, args.name)
        print(f"node/{args.name} uncordoned")
    elif args.cmd == "drain":
        evicted = cmd_drain(store, args.name)
        print(f"node/{args.name} cordoned, evicting {len(evicted)} pod(s)")
    elif args.cmd == "list":
        cmd_node_list(store, out=sys.stdout)
    return None


# -- standalone entry over a pickled simulated cluster ------------------------


def _load_cluster(path: str):
    from volcano_tpu.sim import Cluster

    try:
        with open(path, "rb") as f:
            return pickle.load(f)
    except (FileNotFoundError, EOFError):
        return Cluster()


def _save_cluster(cluster, path: str) -> None:
    import os

    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(cluster, f)
    os.replace(tmp, path)  # never leave a truncated state file behind


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vtctl")
    parser.add_argument("--state", default=".vtctl-state.pkl",
                        help="cluster state file (simulated cluster)")
    parser.add_argument("--server", default="",
                        help="store server URL; job/cluster commands then "
                             "target the remote API server instead of the "
                             "local pickled cluster")
    # accepted both before and after the subcommand; SUPPRESS keeps the
    # subparser from clobbering a value parsed at the top level
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--server", default=argparse.SUPPRESS)
    sub = parser.add_subparsers(dest="group", required=True)

    job_p = sub.add_parser("job", help="job operations")
    job_sub = job_p.add_subparsers(dest="cmd", required=True)

    run_p = job_sub.add_parser("run", parents=[common])
    run_p.add_argument("--name", "-n", default="test")
    run_p.add_argument("--namespace", "-N", default="default")
    run_p.add_argument("--image", "-i", default="busybox")
    run_p.add_argument("--min", "-m", dest="min_available", type=int, default=1)
    run_p.add_argument("--replicas", "-r", type=int, default=1)
    run_p.add_argument("--requests", "-R", default="cpu=1000m,memory=100Mi")
    run_p.add_argument("--queue", "-q", default="")

    list_p = job_sub.add_parser("list", parents=[common])
    list_p.add_argument("--namespace", "-N", default="default")

    for verb in ("suspend", "resume"):
        p = job_sub.add_parser(verb, parents=[common])
        p.add_argument("--name", "-n", required=True)
        p.add_argument("--namespace", "-N", default="default")

    node_p = sub.add_parser("node", help="node lifecycle (cordon/drain)")
    node_sub = node_p.add_subparsers(dest="cmd", required=True)
    for verb in ("cordon", "uncordon", "drain"):
        p = node_sub.add_parser(verb, parents=[common])
        p.add_argument("name")
    node_sub.add_parser("list", parents=[common])

    pool_p = sub.add_parser("pool", help="elastic node pools")
    pool_sub = pool_p.add_subparsers(dest="cmd", required=True)
    pool_sub.add_parser("list", parents=[common])

    # explainability verbs (vtrace; volcano_tpu/trace.py + events.py)
    desc_p = sub.add_parser("describe",
                            help="why-focused object detail (job|pod)")
    desc_sub = desc_p.add_subparsers(dest="cmd", required=True)
    # shared --fleet/--daemon surface (vtfleet): harvest the whole
    # process fleet behind --server (router topology discovery) plus any
    # --daemon sidecars, and render ONE merged report
    def add_fleet_flags(p):
        p.add_argument("--fleet", action="store_true",
                       help="harvest every proc behind --server (plus "
                            "--daemon sidecars) and render one merged "
                            "cross-process report")
        p.add_argument("--daemon", action="append", default=[],
                       metavar="NAME=URL",
                       help="extra daemon admin endpoint to harvest "
                            "(repeatable), e.g. sched=http://127.0.0.1:8080")

    for what in ("job", "pod"):
        p = desc_sub.add_parser(what, parents=[common])
        p.add_argument("--name", "-n", required=True)
        p.add_argument("--namespace", "-N", default="default")
        if what == "job":
            add_fleet_flags(p)
    ev_p = sub.add_parser("events", parents=[common],
                          help="cluster event stream")
    ev_p.add_argument("--namespace", "-N", default="")
    tr_p = sub.add_parser("trace", help="scheduling traces "
                                        "(flight recorder)")
    tr_sub = tr_p.add_subparsers(dest="cmd", required=True)
    last_p = tr_sub.add_parser("last", parents=[common])
    last_p.add_argument("--trace", "-t", default="",
                        help="trace id (default: most recent)")
    add_fleet_flags(last_p)
    tr_sub.add_parser("dump", parents=[common])

    # vtload: the per-cycle time-series dashboard (timeseries.py)
    top_p = sub.add_parser("top", parents=[common],
                           help="live per-cycle dashboard from the "
                                "/debug/timeseries ring")
    top_p.add_argument("--n", type=int, default=12,
                       help="cycle rows to show")
    top_p.add_argument("--watch", type=float, default=0.0,
                       help="refresh every N seconds (0 = render once)")
    top_p.add_argument("--count", type=int, default=0,
                       help="refresh iterations with --watch (0 = forever)")
    add_fleet_flags(top_p)

    # vtprof: the critical-path profile report (vtprof.py)
    prof_p = sub.add_parser("profile", parents=[common],
                            help="device/host critical-path profile from "
                                 "the /debug/prof ring")
    prof_p.add_argument("--json", action="store_true",
                        help="raw payload instead of the text report")
    add_fleet_flags(prof_p)

    # vtaudit: the state-digest auditor (vtaudit.py)
    audit_p = sub.add_parser("audit", parents=[common],
                             help="state-digest audit: divergence "
                                  "detection with (kind, namespace, "
                                  "name) localization")
    audit_sub = audit_p.add_subparsers(dest="cmd")
    awal_p = audit_sub.add_parser(
        "wal", parents=[common],
        help="replay a snapshot+WAL lineage into a digest (scratch "
             "copy) and verify it against the live server")
    awal_p.add_argument("dir",
                        help="the WAL directory (<state>.wal) or the "
                             "state path itself")
    awal_p.add_argument("--snapshot", default="",
                        help="snapshot path when it is not "
                             "<dir minus .wal>")

    cl_p = sub.add_parser("cluster", help="simulated cluster management")
    cl_sub = cl_p.add_subparsers(dest="cmd", required=True)
    init_p = cl_sub.add_parser("init", parents=[common])
    init_p.add_argument("--nodes", type=int, default=2)
    init_p.add_argument("--cpu", default="8")
    init_p.add_argument("--memory", default="16Gi")
    init_p.add_argument("--queues", default="default=1")
    cl_sub.add_parser("step", parents=[common])

    # one-command process model (installer/chart analogue)
    up_p = sub.add_parser("up", parents=[common],
                          help="bring up apiserver+scheduler+controller+"
                               "kubelet with health checks")
    up_p.add_argument("--port", type=int, default=8443,
                      help="apiserver port (0 = pick a free port)")
    up_p.add_argument("--host", default="127.0.0.1",
                      help="apiserver bind address (0.0.0.0 in containers)")
    up_p.add_argument("--state", default="",
                      help="durable apiserver state file (etcd analogue)")
    up_p.add_argument("--wal", action="store_true",
                      help="segment write-ahead log beside --state: every "
                           "ACKed mutation is fsynced before its 2xx "
                           "(zero acked loss on crash)")
    up_p.add_argument("--conf", default="", help="scheduler-conf YAML path")
    up_p.add_argument("--detach", "-d", action="store_true",
                      help="return after startup; tear down with 'vtctl down'")
    up_p.add_argument("--pidfile", default=".vt-up.json")
    up_p.add_argument("--schedulers", type=int, default=1)
    up_p.add_argument("--controllers", type=int, default=1)
    up_p.add_argument("--elastic", type=int, default=0,
                      help="elasticd (node-pool autoscaler) replicas")
    down_p = sub.add_parser("down", parents=[common],
                            help="stop a detached 'vtctl up' control plane")
    down_p.add_argument("--pidfile", default=".vt-up.json")

    # control-plane daemons (the reference's three binaries; SURVEY.md §1)
    api_p = sub.add_parser("apiserver", parents=[common], help="run the store API server")
    api_p.add_argument("--port", type=int, default=8443)
    api_p.add_argument("--host", default="127.0.0.1")
    api_p.add_argument("--state", default="",
                       help="persist objects to this JSON file (etcd analogue); "
                            "a restart resumes with all CRDs")
    api_p.add_argument("--wal", action="store_true",
                       help="segment write-ahead log beside --state "
                            "(store/wal.py): ACK-after-fsync, crash "
                            "recovery = snapshot + replay, zero acked loss")
    api_p.add_argument("--shards", type=int, default=1,
                       help="partition the decision bus by namespace hash "
                            "(store/partition.py): per-shard segment "
                            "apply locks, per-shard WAL files with "
                            "independent group-commit fsync, "
                            "/watch?shard=i fan-out; 1 = unpartitioned")
    api_p.add_argument("--proc-shards", type=int, default=0,
                       help="deploy each shard as its OWN OS process "
                            "behind a router on --port "
                            "(store/procmesh): supervised shard "
                            "servers on a shared seq/rv line, merged "
                            "/watch, per-shard WAL dirs; 0 = in-process")
    api_p.add_argument("--proc-replicas", type=int, default=1,
                       help="replica group size per shard process "
                            "(procmesh only): 2 = each shard leader "
                            "gets a sync follower on its own WAL/state")
    api_p.add_argument("--replica-of", default="",
                       help="boot as a FOLLOWER of this leader URL "
                            "(store/replica.py): pull the synced WAL "
                            "feed, serve reads/watches locally, redirect "
                            "writes with NotLeader; requires --wal --state")
    api_p.add_argument("--peers", default="",
                       help="comma list of every apiserver URL in the "
                            "replication group (incl. this one): arms "
                            "leader election so the highest-applied "
                            "follower promotes on lease loss")
    api_p.add_argument("--repl-ack", default="", choices=["", "async", "sync"],
                       help="sync = the leader's 2xx waits for >=1 "
                            "follower append (zero acked loss across "
                            "failover); async = ship after ack (default)")
    api_p.add_argument("--identity", default="",
                       help="stable replica identity (defaults to the "
                            "server's own URL)")
    api_p.add_argument("--lease-duration", type=float, default=5.0,
                       help="replication leader lease seconds (failover "
                            "detection window)")

    # replication introspection: per-follower lag/applied-seq panel
    repl_p = sub.add_parser("replica", parents=[common],
                            help="inspect a replication group")
    repl_sub = repl_p.add_subparsers(dest="cmd")
    repl_list = repl_sub.add_parser(
        "list", parents=[common],
        help="one row per replica: role, epoch, applied seq, lag")
    repl_list.add_argument("--peers", default="",
                           help="extra replica URLs to probe beside "
                                "--server (comma list)")

    # procmesh introspection: per-shard-process liveness/restart panel
    shard_p = sub.add_parser("shard", parents=[common],
                             help="inspect a multi-process shard store")
    shard_sub = shard_p.add_subparsers(dest="cmd")
    shard_sub.add_parser(
        "list", parents=[common],
        help="one row per shard process: role, url, pid, restarts")

    for comp in ("controller", "scheduler", "kubelet", "elastic"):
        p = sub.add_parser(comp, parents=[common], help=f"run the {comp} against --server")
        p.add_argument("--identity", default="")
        p.add_argument("--peers", default="",
                       help="comma list of replicated apiserver URLs: the "
                            "daemon re-resolves the leader through "
                            "wait_healthy on NotLeader/refused instead of "
                            "failing the cycle")
        p.add_argument("--period", type=float,
                       default=1.0 if comp == "scheduler" else 0.2)
        if comp != "kubelet":
            p.add_argument("--no-leader-elect", action="store_true")
        if comp == "scheduler":
            p.add_argument("--conf", default="", help="scheduler-conf YAML path")
            p.add_argument("--metrics-port", type=int, default=8080,
                           help="/metrics port (0 = free port, <0 = disabled)")
            p.add_argument("--mesh-hosts", type=int, default=0,
                           help="multi-controller launch: total mesh "
                                "hosts (one scheduler process per host; "
                                "0 = conf/VOLCANO_TPU_MESH_HOSTS)")
            p.add_argument("--mesh-host-id", type=int, default=-1,
                           help="this process's host id, 0-based "
                                "(0 = coordinator; -1 = conf/"
                                "VOLCANO_TPU_MESH_HOST_ID)")
        if comp == "elastic":
            p.add_argument("--metrics-port", type=int, default=8081,
                           help="/metrics port (0 = free port, <0 = disabled)")
        if comp in ("controller", "kubelet"):
            p.add_argument("--debug-port", type=int, default=-1,
                           help="/debug/trace port (flight recorder; "
                                "0 = free port, <0 = disabled)")

    args = parser.parse_args(argv)

    if args.group == "top":
        from volcano_tpu import timeseries

        def samples_once():
            if args.server:
                return _fetch_debug_timeseries(args.server)
            return (timeseries.RECORDER.samples()
                    if timeseries.RECORDER is not None else [])

        def render_once():
            if args.fleet:
                cmd_top_fleet(_fleet_snapshot(args), out=sys.stdout,
                              n=args.n)
            else:
                cmd_top(samples_once(), out=sys.stdout, n=args.n)

        import time as _time

        i = 0
        try:
            while True:
                render_once()
                i += 1
                if args.watch <= 0 or (args.count and i >= args.count):
                    break
                _time.sleep(args.watch)
        except KeyboardInterrupt:
            pass
        except Exception as e:  # surface as CLI error, not traceback
            print(f"error: {e}", file=sys.stderr)
            return 1
        return 0

    if args.group == "profile":
        from volcano_tpu import vtfleet, vtprof

        try:
            if args.fleet:
                snap = _fleet_snapshot(args)
                if args.json:
                    import json as _json

                    print(_json.dumps(vtfleet.merge_prof(snap)))
                else:
                    cmd_profile_fleet(snap, out=sys.stdout)
                return 0
            if args.server:
                payload = _fetch_debug_prof(args.server)
            else:
                payload = vtprof.debug_payload()
            if args.json:
                import json as _json

                print(_json.dumps(payload))
            else:
                cmd_profile(payload, out=sys.stdout)
        except Exception as e:  # surface as CLI error, not traceback
            print(f"error: {e}", file=sys.stderr)
            return 1
        return 0

    if args.group == "trace" and getattr(args, "fleet", False):
        # `trace last --fleet`: one harvest round, one merged timeline —
        # works remote (--server router/store) and local (in-process
        # rings) alike, so it sits before the remote/local split
        try:
            cmd_trace_fleet(_fleet_snapshot(args), trace_id=args.trace,
                            out=sys.stdout)
        except Exception as e:  # surface as CLI error, not traceback
            print(f"error: {e}", file=sys.stderr)
            return 1
        return 0

    if args.group == "audit":
        try:
            if getattr(args, "cmd", None) == "wal":
                text = cmd_audit_wal(args.dir, state=args.snapshot,
                                     server_url=args.server,
                                     out=sys.stdout)
            elif args.server:
                text = cmd_audit_remote(args.server, out=sys.stdout)
            else:
                cluster = _load_cluster(args.state)
                text = cmd_audit_local(cluster.store, out=sys.stdout)
        except Exception as e:  # surface as CLI error, not traceback
            print(f"error: {e}", file=sys.stderr)
            return 1
        # exit 2 on divergence so scripts/CI can gate on a clean audit
        return 2 if ("DIVERGENCE" in text or "MISMATCH" in text) else 0

    if args.group == "replica":
        urls = [u for u in ([args.server] if args.server else [])
                + [p.strip() for p in
                   getattr(args, "peers", "").split(",") if p.strip()]]
        # dedupe, order preserved: --server first, then --peers
        seen: list = []
        for u in urls:
            u = u.rstrip("/")
            if u not in seen:
                seen.append(u)
        if not seen:
            print("error: --server (and/or --peers) is required",
                  file=sys.stderr)
            return 1
        try:
            cmd_replica_list(seen, out=sys.stdout)
        except Exception as e:  # surface as CLI error, not traceback
            print(f"error: {e}", file=sys.stderr)
            return 1
        return 0

    if args.group == "shard":
        if not args.server:
            print("error: --server is required", file=sys.stderr)
            return 1
        try:
            cmd_shard_list(args.server, out=sys.stdout)
        except Exception as e:  # surface as CLI error, not traceback
            print(f"error: {e}", file=sys.stderr)
            return 1
        return 0

    if args.group == "up":
        from volcano_tpu.cli import daemons

        return daemons.run_up(port=args.port, state=args.state,
                              conf_path=args.conf, pidfile=args.pidfile,
                              detach=args.detach,
                              schedulers=args.schedulers,
                              controllers=args.controllers,
                              elastic=args.elastic,
                              host=args.host, wal=args.wal)
    if args.group == "down":
        from volcano_tpu.cli import daemons

        return daemons.run_down(pidfile=args.pidfile)

    if args.group in ("apiserver", "controller", "scheduler", "kubelet",
                      "elastic"):
        if args.group != "apiserver" and not args.server:
            print("error: --server is required", file=sys.stderr)
            return 1
        from volcano_tpu.cli import daemons

        daemons.install_sigterm_exit()
        try:
            if args.group == "apiserver":
                daemons.run_apiserver(port=args.port, host=args.host,
                                      state=args.state, wal=args.wal,
                                      shards=args.shards,
                                      replica_of=args.replica_of,
                                      peers=args.peers,
                                      repl_ack=args.repl_ack,
                                      identity=args.identity,
                                      lease_duration=args.lease_duration,
                                      proc_shards=args.proc_shards,
                                      proc_replicas=args.proc_replicas)
            elif args.group == "controller":
                daemons.run_controller(args.server, identity=args.identity,
                                       leader_elect=not args.no_leader_elect,
                                       period=args.period,
                                       debug_port=args.debug_port,
                                       peers=args.peers)
            elif args.group == "scheduler":
                daemons.run_scheduler(args.server, conf_path=args.conf,
                                      identity=args.identity,
                                      leader_elect=not args.no_leader_elect,
                                      period=args.period,
                                      metrics_port=args.metrics_port,
                                      peers=args.peers,
                                      mesh_hosts=args.mesh_hosts,
                                      mesh_host_id=args.mesh_host_id)
            elif args.group == "elastic":
                daemons.run_elastic(args.server, identity=args.identity,
                                    leader_elect=not args.no_leader_elect,
                                    period=args.period,
                                    metrics_port=args.metrics_port,
                                    peers=args.peers)
            else:
                daemons.run_kubelet(args.server, period=args.period,
                                    debug_port=args.debug_port,
                                    peers=args.peers)
        except KeyboardInterrupt:
            pass
        except Exception:
            # failure forensics: the flight recorder's last N spans become
            # a JSON artifact before the daemon dies (no-op disarmed)
            trace.crash_dump(f"{args.group}-crash")
            raise
        return 0

    if args.server:
        return _main_remote(args)

    try:
        cluster = _load_cluster(args.state)
        if args.group == "cluster" and args.cmd == "init":
            from volcano_tpu.sim import Cluster

            cluster = Cluster()
            for entry in args.queues.split(","):
                qname, _, weight = entry.partition("=")
                cluster.add_queue(qname.strip(), int(weight or 1))
            for i in range(args.nodes):
                cluster.add_node(
                    f"node-{i}", {"cpu": args.cpu, "memory": args.memory, "pods": 110}
                )
            print(f"initialized cluster: {args.nodes} nodes")
        elif args.group == "cluster" and args.cmd == "step":
            steps = cluster.run_until_idle()
            print(f"quiesced in {steps} steps")
        elif args.group == "node":
            _node_dispatch(cluster.store, args)
            if args.cmd != "list":
                cluster.run_until_idle()
        elif args.group == "pool":
            cmd_pool_list(cluster.store, out=sys.stdout)
        elif args.group == "describe":
            if args.cmd == "job" and getattr(args, "fleet", False):
                cmd_describe_job_fleet(cluster.store, args, out=sys.stdout)
            elif args.cmd == "job":
                cmd_describe_job(cluster.store, args.namespace, args.name,
                                 out=sys.stdout)
            else:
                cmd_describe_pod(cluster.store, args.namespace, args.name,
                                 out=sys.stdout)
        elif args.group == "events":
            cmd_events(cluster.store, namespace=args.namespace,
                       out=sys.stdout)
        elif args.group == "trace":
            records = _local_trace_records(args.state)
            if args.cmd == "dump":
                import json as _json

                print(_json.dumps(records))
            else:
                cmd_trace_render(records, trace_id=args.trace,
                                 out=sys.stdout)
        elif args.cmd == "run":
            cmd_run(
                cluster.store,
                name=args.name, namespace=args.namespace, image=args.image,
                min_available=args.min_available, replicas=args.replicas,
                requests=args.requests, queue=args.queue,
            )
            cluster.run_until_idle()
            print(f"job {args.namespace}/{args.name} created")
        elif args.cmd == "list":
            cmd_list(cluster.store, namespace=args.namespace, out=sys.stdout)
        elif args.cmd == "suspend":
            cmd_suspend(cluster.store, args.namespace, args.name)
            cluster.run_until_idle()
            print(f"job {args.namespace}/{args.name} suspended")
        elif args.cmd == "resume":
            cmd_resume(cluster.store, args.namespace, args.name)
            cluster.run_until_idle()
            print(f"job {args.namespace}/{args.name} resumed")
    except Exception as e:  # surface as CLI error, not traceback
        print(f"error: {e}", file=sys.stderr)
        return 1

    if trace.TRACER is not None and args.group != "trace" \
            and trace.TRACER.records():
        # local mode runs the whole control plane in-process: persist the
        # flight recorder beside the cluster state so a later
        # `vtctl trace last|dump` (a fresh process) can read it.  Only a
        # non-empty ring writes — an armed read-only command (describe,
        # list) must not clobber the previous invocation's recorder
        trace.TRACER.dump_to(args.state + ".trace.json")
    _save_cluster(cluster, args.state)
    return 0


if __name__ == "__main__":
    sys.exit(main())
