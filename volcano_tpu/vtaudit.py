"""vtaudit: incremental state-digest auditor for the store bus.

The observability stack covers *time* (vtrace spans, vtload histograms,
vtprof critical-path attribution) but nothing covered *state*: after the
partitioned bus (PR 11) the truth lives in N per-shard WALs, a columnar
server log, and a delta-fed ArrayMirror — and the only agreement proof
was an offline byte-identity test harness.  This module is the live
instrument: an **incremental, order-independent digest** of the whole
object state, cheap enough to maintain on every mutation, comparable
across processes, and localizable to the exact object on mismatch.

Digest contract (the ANALYSIS.md "State digest" section is the
normative copy):

* Per object: ``D(kind, key, enc) = (M(kind,key) * sum(leaf_hash(path,
  value))) mod 2^64`` over the flattened **canonical encoded form**
  (``codec.encode``), where ``M`` is a per-identity odd multiplier and
  ``leaf_hash`` mixes ``crc32(path + typed-scalar-repr)`` through a
  splitmix64 finalizer.  Multilinearity is the point: a patch that
  changes k leaves updates the digest with k cached hash lookups and one
  multiply — never a re-flatten of the object.
* Per ``(kind, namespace)`` bucket: sum of its objects' digests mod
  2^64 — order-independent, so any two replicas that hold the same SET
  of objects agree regardless of apply interleaving.
* Rollups: namespace -> shard via ``partition.shard_of`` (the one hash
  the whole bus routes by), shards -> root by the same modular sum.
* ``meta.resource_version`` is excluded (``SKIP_LEAVES``): rv is
  bus-assigned bookkeeping, restamped by WAL replay and recovery, and
  excluding it is what lets recovery maintain the digest through the
  ordinary verbs instead of a wholesale rebuild.
* ``Event`` objects are excluded (``AUDITED_KINDS``): fire-and-forget,
  shadowless, never mirrored — and hashing 100k lazy Event rows per
  cycle would be the drain's new hot path.

Collision math: each leaf contributes ~32 bits (crc32 input) spread over
64 by the finalizer; a single corrupted leaf goes undetected with
probability ~2^-32, independent per check.  This is an auditor, not an
authenticator — it trades cryptographic strength for O(1) maintenance
under the apply locks.

Consumers: ``store/store.py`` maintains the authoritative table under
``_mu``; ``store/server.py`` exposes it (/healthz, /debug/digest) and
stamps **digest beacons** into the event stream; ``scheduler/fastpath/
mirror.py`` maintains an independent table from its watch stream and
verifies against beacons (remote) or the store table (in-process);
``cli/vtctl.py audit`` walks shard -> bucket -> object on mismatch.
"""

from __future__ import annotations

import os
import zlib
from typing import Any, Dict, Iterable, List, Optional, Tuple

from volcano_tpu.store import codec
from volcano_tpu.store.partition import shard_of

_MASK = (1 << 64) - 1

#: leaves excluded from every digest: bus-assigned bookkeeping that WAL
#: replay and snapshot recovery legitimately restamp
SKIP_LEAVES = frozenset({"meta.resource_version"})

#: kinds the digest covers — everything in the codec registry except the
#: fire-and-forget Event stream (shadowless, never mirrored, and the
#: single hottest create path in a drain)
AUDITED_KINDS = frozenset(k for k in codec.KIND_CLASSES if k != "Event")

#: wire kind of a digest beacon entry in the server's event log — never
#: a real object kind, delivered to every watcher regardless of filters
BEACON_KIND = "__beacon__"

#: markers for empty containers (a leaf must exist or {} and absent
#: would hash alike); control prefix keeps them out of real string space
_EMPTY_DICT = "\x01{}"
_EMPTY_LIST = "\x01[]"

_CACHE_CAP = 1 << 20


def enabled() -> bool:
    """Digest maintenance arming — ON by default, ``VOLCANO_TPU_AUDIT=0``
    disarms (the bench's digest-off comparison arm).  Read at each
    construction site, never cached at import."""
    return os.environ.get("VOLCANO_TPU_AUDIT", "1").lower() not in (
        "0", "off", "false", "no",
    )


# -- hash primitives ----------------------------------------------------------


def _mix64(x: int) -> int:
    """splitmix64 finalizer: spreads crc32's 32 bits over all 64."""
    x &= _MASK
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK
    return x ^ (x >> 31)


#: (path, scalar) -> leaf hash.  Paths and values repeat massively
#: (every pod shares its field paths; node names and phases intern
#: themselves here), so the hot-path cost is one dict hit.
_leaf_cache: Dict[Tuple[str, Any], int] = {}
#: (kind, key) -> odd multiplier
_mult_cache: Dict[Tuple[str, str], int] = {}


def leaf_hash(path: str, value: Any) -> int:
    """Hash of one flattened scalar leaf.  The value repr is type-tagged
    so ``1``/``1.0``/``"1"``/``True`` stay distinct across the JSON
    round trip (json preserves int/float/str/bool identity)."""
    ck = (path, value)
    try:
        h = _leaf_cache.get(ck)
    except TypeError:  # unhashable scalar cannot occur in encoded forms
        h = None
        ck = None
    if h is None:
        if value is None:
            tag = "z"
        elif value is True:
            tag = "b1"
        elif value is False:
            tag = "b0"
        elif isinstance(value, str):
            tag = "s" + value
        else:
            tag = "n" + repr(value)
        h = _mix64(zlib.crc32(f"{path}\x00{tag}".encode()))
        if ck is not None and len(_leaf_cache) < _CACHE_CAP:
            _leaf_cache[ck] = h
    return h


def key_mult(kind: str, key: str) -> int:
    """The per-identity odd multiplier — binds every leaf sum to WHICH
    object it describes, so two objects with identical content still
    produce distinct bucket contributions."""
    m = _mult_cache.get((kind, key))
    if m is None:
        m = _mix64(zlib.crc32(f"{kind}\x00{key}".encode())
                   + 0x9E3779B97F4A7C15) | 1
        if len(_mult_cache) < _CACHE_CAP:
            _mult_cache[(kind, key)] = m
    return m


def _flatten(enc: Any, path: str, out: List[Tuple[str, Any]]) -> None:
    if isinstance(enc, dict):
        if not enc:
            out.append((path, _EMPTY_DICT))
            return
        for k in sorted(enc):
            _flatten(enc[k], f"{path}.{k}" if path else str(k), out)
    elif isinstance(enc, (list, tuple)):
        if not enc:
            out.append((path, _EMPTY_LIST))
            return
        for i, v in enumerate(enc):
            _flatten(v, f"{path}.{i}", out)
    else:
        if path not in SKIP_LEAVES:
            out.append((path, enc))


def leaf_sum(enc: Any, path: str = "") -> int:
    """Sum of leaf hashes of one encoded subtree rooted at ``path`` —
    the building block of both absolute digests and patch deltas (a
    scalar at ``path`` contributes exactly its absolute-flatten leaf)."""
    out: List[Tuple[str, Any]] = []
    _flatten(enc, path, out)
    s = 0
    for p, v in out:
        s += leaf_hash(p, v)
    return s & _MASK


def obj_digest_enc(kind: str, key: str, enc: Any) -> int:
    """Per-object digest from its canonical encoded form."""
    return (key_mult(kind, key) * leaf_sum(enc)) & _MASK


def obj_digest(kind: str, obj: Any) -> int:
    """Per-object digest from a decoded object (encodes first — the
    absolute path; deltas never come here)."""
    return obj_digest_enc(kind, obj.meta.key, codec.encode(obj))


def field_delta(path: str, old_value: Any, new_value: Any) -> int:
    """Leaf-sum delta of one field changing ``old_value -> new_value``
    (values are decoded; object-valued patches flatten their encoding).
    Multiply by ``key_mult`` to get the digest delta."""
    return (leaf_sum(codec.encode(new_value), path)
            - leaf_sum(codec.encode(old_value), path)) & _MASK


def ns_of_key(key: str) -> str:
    return key.partition("/")[0]


def hexd(d: int) -> str:
    return "%016x" % (d & _MASK)


# -- the digest table ---------------------------------------------------------


class DigestTable:
    """Incremental digest state: per-object digests plus per-``(kind,
    namespace)`` bucket sums.  All mutators are O(changed leaves); the
    caller provides the locking (Store under ``_mu``, mirror on its own
    thread).  Plain dicts throughout — pickles with the store snapshot.
    """

    def __init__(self) -> None:
        #: (kind, namespace) -> modular sum of object digests
        self.buckets: Dict[Tuple[str, str], int] = {}
        #: kind -> {key -> object digest}
        self.objd: Dict[str, Dict[str, int]] = {}

    # -- mutators (caller holds the apply lock) ---------------------------

    def set_obj(self, kind: str, key: str, obj: Any) -> None:
        """Absolute (re)digest of one object — create/update path."""
        if kind not in AUDITED_KINDS:
            return
        self.set_enc(kind, key, codec.encode(obj))

    def set_enc(self, kind: str, key: str, enc: Any) -> None:
        if kind not in AUDITED_KINDS:
            return
        d = obj_digest_enc(kind, key, enc)
        per = self.objd.setdefault(kind, {})
        old = per.get(key, 0)
        per[key] = d
        b = (kind, ns_of_key(key))
        self.buckets[b] = (self.buckets.get(b, 0) + d - old) & _MASK

    def apply_fields(self, kind: str, key: str,
                     trips: Iterable[Tuple[str, Any, Any]],
                     obj: Any = None) -> None:
        """Delta path: ``trips`` is ``(dotted_path, old_value,
        new_value)`` per changed field — the COW patch and lazy-staging
        hot paths.  Falls back to an absolute set when the object was
        never digested (defensive; cannot happen through the verbs)."""
        if kind not in AUDITED_KINDS:
            return
        per = self.objd.setdefault(kind, {})
        old = per.get(key)
        if old is None:
            if obj is not None:
                self.set_obj(kind, key, obj)
            return
        delta = 0
        for path, ov, nv in trips:
            delta += field_delta(path, ov, nv)
        delta = (key_mult(kind, key) * (delta & _MASK)) & _MASK
        per[key] = (old + delta) & _MASK
        b = (kind, ns_of_key(key))
        self.buckets[b] = (self.buckets.get(b, 0) + delta) & _MASK

    def remove(self, kind: str, key: str) -> None:
        if kind not in AUDITED_KINDS:
            return
        per = self.objd.get(kind)
        d = per.pop(key, None) if per else None
        if d is not None:
            b = (kind, ns_of_key(key))
            self.buckets[b] = (self.buckets.get(b, 0) - d) & _MASK

    def clear(self) -> None:
        self.buckets.clear()
        self.objd.clear()

    # -- rollups -----------------------------------------------------------

    def shard_rollup(self, nshards: int) -> List[int]:
        out = [0] * max(1, int(nshards))
        for (_, ns), d in self.buckets.items():
            s = shard_of(ns, len(out))
            out[s] = (out[s] + d) & _MASK
        return out

    def kind_rollup(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for (kind, _), d in self.buckets.items():
            out[kind] = (out.get(kind, 0) + d) & _MASK
        # zero sums drop out: a fully-deleted kind must compare equal to
        # a never-seen one (diff_* treat absent as zero)
        return {k: d for k, d in out.items() if d}

    def root(self) -> int:
        s = 0
        for d in self.buckets.values():
            s += d
        return s & _MASK

    def payload(self, nshards: int = 1) -> Dict[str, Any]:
        """The wire/debug shape every surface speaks: hex digests so the
        values survive JSON without precision loss."""
        return {
            "root": hexd(self.root()),
            "shards": [hexd(d) for d in self.shard_rollup(nshards)],
            "kinds": {k: hexd(d) for k, d in sorted(self.kind_rollup()
                                                    .items())},
        }

    def bucket_payload(self, shard: Optional[int] = None,
                       nshards: int = 1) -> Dict[str, str]:
        """Per-``(kind, namespace)`` buckets (``"kind|ns"`` keys),
        optionally restricted to one shard — the localization walk's
        middle tier."""
        out: Dict[str, str] = {}
        for (kind, ns), d in self.buckets.items():
            if not d:
                continue  # emptied bucket == never-seen bucket
            if shard is not None and shard_of(ns, nshards) != shard:
                continue
            out[f"{kind}|{ns}"] = hexd(d)
        return out

    def object_payload(self, kind: str, namespace: str) -> Dict[str, str]:
        """Per-object digests of one bucket — the walk's bottom tier."""
        per = self.objd.get(kind) or {}
        return {k: hexd(d) for k, d in per.items()
                if ns_of_key(k) == namespace}


def table_from_objects(items: Iterable[Tuple[str, Any]]) -> DigestTable:
    """Full recompute from ``(kind, obj)`` pairs — recovery of old
    snapshots, the mirror's list seed, and the audit walk's ground
    truth."""
    t = DigestTable()
    for kind, obj in items:
        if kind in AUDITED_KINDS:
            t.set_obj(kind, obj.meta.key, obj)
    return t


def merge_digest_payloads(payloads: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Roll N per-shard ``payload()`` dicts (each from a ``shards=1``
    server owning a disjoint namespace slice) into the one payload a
    single server covering the union would report.  Sound because every
    rollup is a MODULAR SUM of disjoint bucket sums: the mesh root is
    the sum of the shard roots, per-kind digests add the same way, and
    the shard list is just the roots in mesh order.  The procmesh
    router's ``/debug/digest`` aggregation — ``vtctl audit`` pointed at
    a router sees the same shape it sees against one process."""
    root = 0
    shard_roots: List[str] = []
    kinds: Dict[str, int] = {}
    for p in payloads:
        r = int(str(p.get("root", "0")), 16)
        root = (root + r) & _MASK
        shard_roots.append(hexd(r))
        for k, v in (p.get("kinds") or {}).items():
            kinds[k] = (kinds.get(k, 0) + int(str(v), 16)) & _MASK
    return {
        "root": hexd(root),
        "shards": shard_roots,
        "kinds": {k: hexd(d) for k, d in sorted(kinds.items())},
    }


# -- comparison / localization ------------------------------------------------


def diff_maps(a: Dict[str, str], b: Dict[str, str]) -> List[str]:
    """Keys whose hex digests differ (absent == zero state on either
    side is NOT equal to a present non-zero digest)."""
    zero = hexd(0)
    keys = set(a) | set(b)
    return sorted(k for k in keys
                  if a.get(k, zero) != b.get(k, zero))


def diff_kinds(a: Dict[str, str], b: Dict[str, str],
               kinds: Iterable[str]) -> List[str]:
    """Per-kind digest comparison restricted to ``kinds`` — replicas
    that subscribe to a subset (the mirror's watch set) compare only
    what they both claim to hold."""
    zero = hexd(0)
    return sorted(k for k in kinds
                  if a.get(k, zero) != b.get(k, zero))


# -- beacon -------------------------------------------------------------------


def beacon_interval_s() -> float:
    """Seconds between beacon stamps on a moving event log (env-tunable;
    tests pin it low for prompt verification)."""
    try:
        return float(os.environ.get("VOLCANO_TPU_AUDIT_BEACON_S", "1.0"))
    except ValueError:
        return 1.0


def beacon_entry(seq: int, payload: Dict[str, Any],
                 ts: float) -> Dict[str, Any]:
    """One seq-pinned checkpoint record for the server's event log.
    ``kind`` is the sentinel every watch filter passes through; the
    digest payload describes the state EXACTLY at ``seq`` (the entry is
    appended at the tail of its pump batch, under the server lock)."""
    return {"seq": seq, "kind": BEACON_KIND, "type": "Beacon",
            "digest": dict(payload, seq=seq, ts=round(ts, 6))}


# -- debug payload registry (MetricsServer /debug/digest) ---------------------

#: the armed process's digest source — a callable returning the
#: /debug/digest JSON body (the scheduler registers its mirror's view,
#: the same pattern as vtprof's PROFILER singleton)
_DEBUG_SOURCE = None


def set_debug_source(fn) -> None:
    global _DEBUG_SOURCE
    _DEBUG_SOURCE = fn


def has_debug_source() -> bool:
    return _DEBUG_SOURCE is not None


def debug_payload() -> Dict[str, Any]:
    src = _DEBUG_SOURCE
    if src is None:
        return {"enabled": enabled(), "digest": None}
    try:
        body = src()
    except Exception as e:  # noqa: BLE001 — debug surface, never raises out
        return {"enabled": enabled(), "error": repr(e)}
    return body


# -- WAL replay audit ---------------------------------------------------------


def replay_wal_digest(state_path: str, shards: int = 0,
                      ) -> Dict[str, Any]:
    """Replay a snapshot + segment-WAL lineage into a digest, WITHOUT
    touching the original files: recovery rotates segments, stamps
    snapshots, and reaps covered files, so the lineage is copied into a
    scratch directory and the real ``StoreServer`` recovery runs there
    (never started — ``__init__`` does the whole replay).  Returns the
    recovered digest payload plus replay forensics."""
    import shutil
    import tempfile

    from volcano_tpu.store.partition import leftover_shard_dirs
    from volcano_tpu.store.server import StoreServer

    wal_dir = state_path + ".wal"
    tmp = tempfile.mkdtemp(prefix="vtaudit-wal-")
    try:
        scratch_state = os.path.join(tmp, os.path.basename(state_path))
        if os.path.exists(state_path):
            shutil.copy2(state_path, scratch_state)
        if os.path.isdir(wal_dir):
            shutil.copytree(wal_dir, scratch_state + ".wal")
        if shards <= 0:
            shards = max(1, len(leftover_shard_dirs(scratch_state + ".wal")))
        srv = StoreServer(port=0, state_path=scratch_state, wal=True,
                          shards=shards)
        try:
            with srv.store._mu:
                dg = srv.store._digest
                payload = (dg.payload(shards) if dg is not None else None)
            stats = srv.wal.stats() if srv.wal is not None else {}
            return {
                "digest": payload,
                "seq": srv.seq,
                "shards": shards,
                "replayed_records": stats.get("replayed_records", 0),
                "torn_tails": stats.get("torn_tails", 0),
            }
        finally:
            if srv.wal is not None:
                srv.wal.sync_close()
            srv.httpd.server_close()  # free the (never-served) socket
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
