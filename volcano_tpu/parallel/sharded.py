"""Sharded allocate cycle: node state partitioned over a device mesh.

Design (the "How to Scale Your Model" recipe: pick a mesh, annotate
shardings, let XLA insert collectives):

  * mesh axis ``nodes`` — the cluster's node dimension. All node-shaped
    state (idle/releasing/used/allocatable ``[N, R]``, task_count,
    node_valid, per-class predicate masks ``[C, N]``) is sharded along N.
  * task/job/queue state is replicated; it is small relative to node state
    and every shard needs the full job ranking each round.
  * the round body's [M, N] feasibility+score block — the FLOP/bandwidth
    hot spot, replacing the reference's 16-goroutine task x node loop
    (scheduler_helper.go:53,74) — computes shard-locally; the global
    top-k over nodes and the scatter updates back to node rows become XLA
    collectives (all-gather / selective scatter) over ICI.

The cycle function is jitted with explicit NamedSharding in_shardings, so
the same code runs single-chip (trivial mesh) or on a slice. The driver's
``dryrun_multichip`` entry exercises it on an N-device virtual CPU mesh.

Sharded-vs-unsharded equivalence is policy-level by default, bit-level on
request: the batch solve's spill targets come from ``approx_max_k``, whose
bucketed reduction depends on data layout, so a mesh-sharded run may choose
different (equally feasible, comparably scored) nodes than the
single-device run at large N. Small-N runs reduce to exact top-k and match
bit-for-bit; ``exact_topk=True`` swaps in the exact, layout-independent
``lax.top_k`` so ANY mesh size reproduces the single-device run
bit-for-bit at any N (tests/test_parallel.py sweeps 1/2/4/8 devices) at
the cost of the slower reduction; all hard policies hold in either mode.

Why GSPMD rather than hand-written shard_map collectives: every round's
cross-shard data is tiny (per-job candidate lists), while the sharded
[M, N] block dominates — exactly the regime the SPMD partitioner handles
well. A hand-scheduled shard_map variant of the top-k exchange is a
planned optimization, not a correctness need.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from volcano_tpu import vtprof
from volcano_tpu.scheduler.kernels import allocate_solve_batch, water_fill

#: argument name -> PartitionSpec over the ("nodes",) mesh axis.
_SPECS: Dict[str, P] = {
    "idle": P("nodes", None),
    "releasing": P("nodes", None),
    "used": P("nodes", None),
    "node_alloc": P("nodes", None),
    "node_max_tasks": P("nodes"),
    "task_count": P("nodes"),
    "node_valid": P("nodes"),
    "class_mask": P(None, "nodes"),
    "class_score": P(None, "nodes"),
    # dynamic-solve node planes (ports/affinity resident state): node
    # axis 0, like idle/used — the dyn wave's feasibility masks shard
    # with the node rows they gate
    "node_ports_w": P("nodes", None),
    "node_selcnt": P("nodes", None),
}

#: cycle arguments that REPLICATE across the mesh, listed explicitly so
#: the ``shard-spec-complete`` vtlint rule can prove every array entering
#: the jitted sharded cycle has a declared placement (a name in neither
#: table is a silent default — exactly the drift the rule fences).
#: task/job/queue state is small relative to [*, N] node planes and every
#: shard needs the full job ranking each round; the volsel claim bitsets
#: replicate too (task-major rows whose node axis is PACKED into u32
#: words — words do not split on a node boundary, and volume waves are
#: residue-scale, so replication is bytes, not a bandwidth term).
_REPLICATED = frozenset({
    "task_req", "task_job", "task_class", "task_valid",
    "job_queue", "job_min", "job_prio", "job_ready_init",
    "job_alloc_init", "job_schedulable", "job_start", "job_ntasks",
    "queue_weight", "queue_request", "queue_alloc_init",
    "queue_participates",
    "total", "eps",
    "task_volmask_w", "task_claims", "claim_group", "group_cap",
    "group_global",
    "task_ports_w", "task_aff_w", "task_anti_w", "task_self_w",
})


def make_mesh(n_devices: Optional[int] = None, axis: str = "nodes") -> Mesh:
    """Mesh over the first ``n_devices`` devices (all by default)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def resolve_mesh(setting: Optional[str]) -> Optional[Mesh]:
    """The scheduler-conf ``mesh:`` key -> a Mesh (or None = single device).

    "off"/None/empty -> None; "auto" -> every visible device; "N" -> the
    first N.  A size-1 result resolves to None (nothing to shard); asking
    for more devices than exist raises, because silently running
    single-device would defeat the conf's intent."""
    if not setting or setting == "off":
        return None
    devs = jax.devices()
    if setting == "auto":
        n = len(devs)
        # snapshot node axes bucket to powers of two; a non-pow2 mesh
        # could never divide them — auto rounds down to the largest
        # shardable size instead of silently not sharding
        while n & (n - 1):
            n -= 1
    else:
        n = int(setting)
        if n > len(devs):
            raise ValueError(
                f"mesh: {setting} requested but only {len(devs)} "
                "devices are visible"
            )
        if n & (n - 1):
            raise ValueError(
                f"mesh: {setting} is not a power of two — snapshot node "
                "axes bucket to powers of two, so this mesh could never "
                "divide them and every solve would silently run "
                "single-device"
            )
    if n <= 1:
        return None
    return make_mesh(n)


def named_sharding_for(mesh: Mesh, name: str) -> Optional[NamedSharding]:
    """The node-axis NamedSharding for a snapshot/victim field, or None
    when the field replicates (task/job/queue state)."""
    spec = _SPECS.get(name)
    if spec is None:
        return None
    return NamedSharding(mesh, spec)


def cycle_shardings(mesh: Mesh, args: Dict[str, object]) -> Dict[str, NamedSharding]:
    """NamedSharding per cycle argument; non-node args replicate."""
    out = {}
    for k in args:
        spec = _SPECS.get(k, P())
        out[k] = NamedSharding(mesh, spec)
    return out


def _cycle(args, w_least, w_balanced, job_key_order, use_gang_ready,
           use_proportion, m_chunk, p_chunk, exact_topk=False):
    """One full decision cycle: proportion water-fill + batched allocate."""
    deserved = water_fill(
        args["queue_weight"], args["queue_request"], args["total"],
        args["eps"], args["queue_participates"],
    )
    return allocate_solve_batch(
        args["idle"], args["releasing"], args["used"], args["node_alloc"],
        args["node_max_tasks"], args["task_count"], args["node_valid"],
        args["task_req"], args["task_job"], args["task_class"],
        args["task_valid"],
        args["job_queue"], args["job_min"], args["job_prio"],
        args["job_ready_init"], args["job_alloc_init"], args["job_schedulable"],
        args["job_start"], args["job_ntasks"],
        args["queue_alloc_init"], deserved,
        args["class_mask"], args["class_score"],
        args["total"], args["eps"],
        w_least, w_balanced,
        job_key_order=job_key_order,
        use_gang_ready=use_gang_ready,
        use_proportion=use_proportion,
        m_chunk=m_chunk,
        p_chunk=p_chunk,
        exact_topk=exact_topk,
    )


def run_cycle_reference(args, w_least=1.0, w_balanced=1.0,
                        job_key_order=("priority", "gang", "drf"),
                        use_gang_ready=True, use_proportion=True,
                        m_chunk=512, p_chunk=16, exact_topk=False):
    """Unsharded cycle on default device placement (parity oracle)."""
    import jax.numpy as jnp

    return _cycle(
        {k: jnp.asarray(v) for k, v in args.items()},
        jnp.float32(w_least), jnp.float32(w_balanced),
        job_key_order, use_gang_ready, use_proportion, m_chunk, p_chunk,
        exact_topk,
    )


#: VictimConsts/VictimState fields shard by the SAME node-axis map as the
#: cycle args (identical names and shapes); the [V] victim pool replicates
#: (its sorts and segment sums are global over V and V rows are small next
#: to [C, N] masks).
_VICTIM_SPECS = _SPECS


def make_sharded_victim_step(mesh: Mesh, consts, state, **static_kw):
    """(jitted_fn, device_consts, device_state): victim_step compiled with
    node-axis shardings over the mesh. ``jitted_fn(consts, state, t_req,
    t_cls, jt, qt)`` runs one preemptor's solve; the returned new state
    keeps node-shaped rows sharded so chained solves stay distributed.
    The compile cache is ``victim_step``'s own (already in the vtprof
    registry under that name, registered at victim_kernels import) — the
    sharded path adds placements, not a second jit wrapper, so the
    recompile sentinel sees its compiles without double counting."""
    from volcano_tpu.scheduler.victim_kernels import victim_step

    def shard_tuple(tup):
        placed = {}
        for name in tup._fields:
            spec = _VICTIM_SPECS.get(name, P())
            placed[name] = jax.device_put(
                np.asarray(getattr(tup, name)), NamedSharding(mesh, spec)
            )
        return type(tup)(**placed)

    dev_consts = shard_tuple(consts)
    dev_state = shard_tuple(state)
    # victim_step is already jitted; committed input shardings from the
    # device_put above drive the SPMD partitioning
    fn = functools.partial(victim_step, **static_kw)
    return fn, dev_consts, dev_state


def make_sharded_cycle(
    mesh: Mesh,
    args: Dict[str, object],
    w_least: float = 1.0,
    w_balanced: float = 1.0,
    job_key_order=("priority", "gang", "drf"),
    use_gang_ready: bool = True,
    use_proportion: bool = True,
    m_chunk: int = 512,
    p_chunk: int = 16,
    exact_topk: bool = False,
):
    """Return (jitted_fn, device_args): the cycle compiled with node-axis
    shardings, and the host args placed onto the mesh accordingly.

    ``jitted_fn(device_args)`` runs one cycle; outputs keep node-shaped
    results sharded (idle/releasing/used) and replicate the rest.
    """
    n_shards = mesh.devices.size
    n_rows = np.shape(args["idle"])[0]
    if n_rows % n_shards:
        raise ValueError(
            f"node bucket {n_rows} not divisible by mesh size {n_shards}"
        )
    shardings = cycle_shardings(mesh, args)
    device_args = {
        k: jax.device_put(np.asarray(v), shardings[k]) for k, v in args.items()
    }
    fn = jax.jit(
        functools.partial(
            _cycle,
            job_key_order=job_key_order,
            use_gang_ready=use_gang_ready,
            use_proportion=use_proportion,
            m_chunk=m_chunk,
            p_chunk=p_chunk,
            exact_topk=exact_topk,
        ),
        in_shardings=(shardings, None, None),
    )
    # every sharded-cycle jit joins the vtprof compile-cache registry so
    # the recompile sentinel and `vtctl profile` see the mesh path too
    # (registration is unconditional; scanning happens only while armed)
    vtprof.register_jit("sharded_cycle", fn)
    import jax.numpy as jnp

    return (
        lambda a: fn(a, jnp.float32(w_least), jnp.float32(w_balanced)),
        device_args,
    )


def fetch_outputs(out, kernel: str = "sharded_cycle", phase: str = "solve",
                  host=None):
    """THE sanctioned device→host fetch boundary for a sharded cycle's
    output tuple: disarmed it is exactly ``np.asarray`` per output (the
    device-sync-discipline contract); armed, each output's block-until-
    ready wait splits from its host copy and attributes to ``kernel`` —
    so the mesh path's wall-clock lands in named vtprof segments instead
    of vanishing into the caller's host time.  ``host`` forwards to the
    per-mesh-host rollup (vtprof.fetch_outputs): the multi-controller
    path passes its host id so owned-slice fetch walls attribute per
    host."""
    return vtprof.fetch_outputs(out, kernel=kernel, phase=phase, host=host)
