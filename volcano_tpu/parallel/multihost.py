"""Multi-controller device-mesh solve: one process per host, each host
feeding and fetching ONLY its shard of the task/node planes.

Design (the pjit-on-pods recipe: the jitted cycle is one SPMD program
over the GLOBAL logical mesh; every controller process runs the same
program and owns the slice of inputs/outputs its devices hold):

  * 2-D host mesh ``(hosts, nodes)`` — ``Mesh(devices.reshape(H, D/H))``.
    Node-shaped planes shard over the COMBINED ``("hosts", "nodes")``
    axis pair, which splits the node dimension into the same ``D`` blocks
    as the single-controller 1-D ``("nodes",)`` mesh — so the degenerate
    ``--mesh-hosts 1`` run is the existing sharded path, bit-for-bit
    under ``exact_topk`` (tests/test_parallel.py gates it).
  * task planes (``task_req``/``task_job``/``task_class``/``task_valid``)
    move OUT of the replicated set and shard over ``"hosts"``: each host
    builds and dispatches only its 1/H task block; the all-gather XLA
    inserts is value-exact, so solve outputs are unchanged.
  * job/queue planes and the packed claim/port bitset words stay
    replicated (small next to the task/node planes; word-packed node
    axes do not split on a host boundary).
  * outputs: each host fetches ONLY the slice it owns through the
    per-host ``vtprof.fetch_outputs`` boundary — task-axis outputs by
    task block, node-axis outputs by node block; the coordinator (host
    0) additionally fetches the replicated job/queue/scalar outputs.
    The per-host critical path is build + dispatch + owned-slice fetch;
    the device compute between dispatch and fetch is the SAME global
    program regardless of host count (cfg9 gates it) and is reported
    separately as ``solve_wait_s``.

CPU simulation (how CI gates this without a pod): a single process sees
all virtual devices, so ``run_lockstep`` executes the one global cycle
and measures each host's critical-path components individually — host
``h``'s build wall is slicing ITS plane shard out of the snapshot
(snapshot_build.host_plane_shard), its dispatch wall is the device puts
for ITS mesh row plus the shared jit call, its fetch wall is ITS owned
output slices.  Other hosts' puts are the simulation standing in for
work those processes would do concurrently, never charged to ``h``.

Process mode (``python -m volcano_tpu.parallel.multihost --mesh-hosts N``)
runs one OS process per host in lockstep over identically-seeded args:
the coordinator spawns workers, every process runs the SPMD cycle,
workers ship their owned slices back through the rendezvous directory,
and the coordinator verifies the merged slices against its own full
outputs.  Failure contract: a coordinator death mid-cycle degrades each
worker to a FULL single-host cycle (``"fallback": true`` in its result)
rather than wedging on the rendezvous; a worker death degrades the
coordinator to its own full outputs (``"degraded": true``).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from volcano_tpu import vtprof

#: the jitted cycle's output tuple, in order (parallel/sharded.py's
#: cycle returns the same tuple — the kernels define it)
OUTPUT_NAMES = (
    "task_node", "task_kind", "task_seq", "ready", "job_alloc",
    "queue_alloc", "idle", "releasing", "used", "dropped", "rounds",
)
#: output indices per owning axis: task-axis and node-axis outputs are
#: fetched as owned slices per host; the rest (job/queue planes +
#: scalars) replicate and only the coordinator fetches them
_TASK_OUT = (0, 1, 2)
_NODE_OUT = (6, 7, 8)
_GLOBAL_OUT = (3, 4, 5, 9, 10)

# PartitionSpec construction is deferred so importing this module never
# initializes jax (daemons import the CLI layer eagerly); the literal
# tables below are what the vtlint shard-spec-complete rule reads.

#: argument name -> axis-spec tuple over the ("hosts", "nodes") mesh.
#: Node planes split over BOTH axes combined — the same D-way node
#: blocking as the 1-D sharded mesh; task planes split over hosts only.
_SPECS = {
    "idle": (("hosts", "nodes"), None),
    "releasing": (("hosts", "nodes"), None),
    "used": (("hosts", "nodes"), None),
    "node_alloc": (("hosts", "nodes"), None),
    "node_max_tasks": (("hosts", "nodes"),),
    "task_count": (("hosts", "nodes"),),
    "node_valid": (("hosts", "nodes"),),
    "class_mask": (None, ("hosts", "nodes")),
    "class_score": (None, ("hosts", "nodes")),
    "node_ports_w": (("hosts", "nodes"), None),
    "node_selcnt": (("hosts", "nodes"), None),
    # task planes: host-sharded (the multi-controller point — each host
    # builds/dispatches only its 1/H task block; XLA's all-gather is
    # value-exact so outputs match the replicated layout bit-for-bit
    # under exact_topk)
    "task_req": ("hosts", None),
    "task_job": ("hosts",),
    "task_class": ("hosts",),
    "task_valid": ("hosts",),
}

#: cycle arguments that REPLICATE across every host's devices, listed
#: explicitly so the shard-spec-complete vtlint rule can prove every
#: array entering the jitted multihost cycle has a declared placement.
#: job/queue planes are small and every host needs the full job ranking
#: each round; the claim/port bitset words keep task-major rows whose
#: node axis is PACKED into u32 words — words do not split on a host
#: boundary, and volume waves are residue-scale, so replication is
#: bytes, not a bandwidth term.
_REPLICATED = frozenset({
    "job_queue", "job_min", "job_prio", "job_ready_init",
    "job_alloc_init", "job_schedulable", "job_start", "job_ntasks",
    "queue_weight", "queue_request", "queue_alloc_init",
    "queue_participates",
    "total", "eps",
    "task_volmask_w", "task_claims", "claim_group", "group_cap",
    "group_global",
    "task_ports_w", "task_aff_w", "task_anti_w", "task_self_w",
})


def host_bounds(n_rows: int, n_hosts: int) -> List[Tuple[int, int]]:
    """Per-host ``[lo, hi)`` block bounds over an ``n_rows`` axis —
    XLA's ceil-block convention (shard ``h`` owns rows
    ``[h*ceil, (h+1)*ceil)`` clipped to ``n_rows``), so owned output
    slices line up with what the host's devices actually hold."""
    n_hosts = max(int(n_hosts), 1)
    q = -(-int(n_rows) // n_hosts)
    return [(min(h * q, n_rows), min((h + 1) * q, n_rows))
            for h in range(n_hosts)]


def make_host_mesh(n_hosts: int, n_devices: Optional[int] = None):
    """2-D ``(hosts, nodes)`` mesh: ``n_hosts`` rows of equal device
    count over the first ``n_devices`` devices (all by default)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    if len(devs) % n_hosts:
        raise ValueError(
            f"{len(devs)} devices not divisible by {n_hosts} hosts"
        )
    per = len(devs) // n_hosts
    return Mesh(np.asarray(devs).reshape(n_hosts, per), ("hosts", "nodes"))


def _spec_of(name: str):
    from jax.sharding import PartitionSpec as P

    axes = _SPECS.get(name)
    return P() if axes is None else P(*axes)


def cycle_shardings(mesh, args: Dict[str, object]) -> Dict[str, object]:
    """NamedSharding per cycle argument over the host mesh; names in
    neither table replicate (the vtlint rule fences drift)."""
    from jax.sharding import NamedSharding

    return {k: NamedSharding(mesh, _spec_of(k)) for k in args}


def _cycle(args, w_least, w_balanced, job_key_order, use_gang_ready,
           use_proportion, m_chunk, p_chunk, exact_topk=False):
    """One full decision cycle over the host mesh: proportion water-fill
    + batched allocate — the sharded cycle body, re-declared here so the
    ``args[...]`` reads check against THIS module's host-axis
    ``_SPECS``/``_REPLICATED`` tables (shard-spec-complete)."""
    from volcano_tpu.scheduler.kernels import allocate_solve_batch, water_fill

    deserved = water_fill(
        args["queue_weight"], args["queue_request"], args["total"],
        args["eps"], args["queue_participates"],
    )
    return allocate_solve_batch(
        args["idle"], args["releasing"], args["used"], args["node_alloc"],
        args["node_max_tasks"], args["task_count"], args["node_valid"],
        args["task_req"], args["task_job"], args["task_class"],
        args["task_valid"],
        args["job_queue"], args["job_min"], args["job_prio"],
        args["job_ready_init"], args["job_alloc_init"],
        args["job_schedulable"],
        args["job_start"], args["job_ntasks"],
        args["queue_alloc_init"], deserved,
        args["class_mask"], args["class_score"],
        args["total"], args["eps"],
        w_least, w_balanced,
        job_key_order=job_key_order,
        use_gang_ready=use_gang_ready,
        use_proportion=use_proportion,
        m_chunk=m_chunk,
        p_chunk=p_chunk,
        exact_topk=exact_topk,
    )


#: output name -> axis-spec tuple (out_shardings): task outputs land
#: host-blocked, node outputs land device-blocked, the rest replicate —
#: so each host's owned fetch reads ONLY its local device shards (no
#: cross-host transfer), exactly the multi-controller contract
_OUT_AXES = {
    "task_node": ("hosts",),
    "task_kind": ("hosts",),
    "task_seq": ("hosts",),
    "idle": (("hosts", "nodes"), None),
    "releasing": (("hosts", "nodes"), None),
    "used": (("hosts", "nodes"), None),
}


def _jit_cycle(mesh, shardings, w_least, w_balanced, **static_kw):
    """The jitted multihost cycle with committed input shardings AND
    explicit output shardings (task outputs host-blocked, node outputs
    device-blocked — each host fetches from its own devices only);
    registered in the vtprof compile registry as ``multihost_cycle`` so
    the recompile sentinel and ``vtctl profile`` see this path too."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    out_sh = tuple(
        NamedSharding(mesh, P(*_OUT_AXES.get(name, ())))
        for name in OUTPUT_NAMES
    )
    fn = jax.jit(
        functools.partial(_cycle, **static_kw),
        in_shardings=(shardings, None, None),
        out_shardings=out_sh,
    )
    vtprof.register_jit("multihost_cycle", fn)
    return lambda a: fn(a, jnp.float32(w_least), jnp.float32(w_balanced))


def make_multihost_cycle(
    mesh,
    args: Dict[str, object],
    w_least: float = 1.0,
    w_balanced: float = 1.0,
    job_key_order=("priority", "gang", "drf"),
    use_gang_ready: bool = True,
    use_proportion: bool = True,
    m_chunk: int = 512,
    p_chunk: int = 16,
    exact_topk: bool = False,
):
    """Return (jitted_fn, device_args): the cycle compiled with host-axis
    shardings, host args placed accordingly — the make_sharded_cycle
    shape, for the degenerate-parity tests and embedders that do their
    own dispatch timing (run_lockstep is the measured path)."""
    import jax

    n_devs = mesh.devices.size
    n_rows = np.shape(args["idle"])[0]
    if n_rows % n_devs:
        raise ValueError(
            f"node bucket {n_rows} not divisible by mesh size {n_devs}"
        )
    shardings = cycle_shardings(mesh, args)
    device_args = {
        k: jax.device_put(np.asarray(v), shardings[k])
        for k, v in args.items()
    }
    call = _jit_cycle(
        mesh, shardings, w_least, w_balanced,
        job_key_order=job_key_order,
        use_gang_ready=use_gang_ready,
        use_proportion=use_proportion,
        m_chunk=m_chunk,
        p_chunk=p_chunk,
        exact_topk=exact_topk,
    )
    return call, device_args


def _host_shard_pieces(arr, devset):
    """ONE host's distinct data pieces of a sharded jax array, ordered
    by axis offset: the single-device shards resident on the host's
    devices, with replicated copies deduped to one.  Reading shards
    directly (instead of device-slicing the global array) is both the
    faithful multi-controller mechanic — a real host can only see its
    addressable shards — and the fast path: no slice program launches,
    just host copies of owned bytes."""
    by_idx = {}
    for s in arr.addressable_shards:
        if s.device not in devset:
            continue
        key = tuple((sl.start or 0) for sl in s.index)
        by_idx.setdefault(key, s.data)
    return [by_idx[k] for k in sorted(by_idx)]


def owned_output_slices(out, host: int, n_hosts: int,
                        kernel: str = "multihost_cycle",
                        phase: str = "fetch") -> Dict[str, np.ndarray]:
    """Fetch ONE host's owned slice of the cycle output tuple through
    the per-host vtprof.fetch_outputs boundary: task-axis outputs by
    task block, node-axis outputs by node block — read straight off the
    host's addressable device shards (the jit's ``_OUT_AXES`` output
    shardings put each block exactly there); the coordinator (host 0)
    also fetches the replicated job/queue/scalar outputs."""
    devset = set(out[_NODE_OUT[0]].sharding.mesh.devices[host].flat)
    picks = [(OUTPUT_NAMES[i], _host_shard_pieces(out[i], devset))
             for i in _TASK_OUT + _NODE_OUT]
    if host == 0:
        picks += [(OUTPUT_NAMES[i], _host_shard_pieces(out[i], devset)[:1])
                  for i in _GLOBAL_OUT]
    flat = tuple(p for _, ps in picks for p in ps)
    arrs = vtprof.fetch_outputs(flat, kernel=kernel, phase=phase, host=host)
    res: Dict[str, np.ndarray] = {}
    k = 0
    for name, ps in picks:
        got = arrs[k:k + len(ps)]
        k += len(ps)
        res[name] = got[0] if len(got) == 1 else np.concatenate(got)
    return res


def merge_output_slices(per_host: List[Dict[str, np.ndarray]]):
    """Reassemble the full output tuple from every host's owned slices
    (the lockstep merge — also the proof that the owned slices cover
    the whole output plane exactly once)."""
    merged = {}
    for i in _TASK_OUT + _NODE_OUT:
        name = OUTPUT_NAMES[i]
        merged[name] = np.concatenate([ph[name] for ph in per_host])
    for i in _GLOBAL_OUT:
        name = OUTPUT_NAMES[i]
        merged[name] = per_host[0][name]
    return tuple(merged[n] for n in OUTPUT_NAMES)


def run_lockstep(
    args: Dict[str, object],
    n_hosts: int,
    *,
    reps: int = 1,
    w_least: float = 1.0,
    w_balanced: float = 1.0,
    job_key_order=("priority", "gang", "drf"),
    use_gang_ready: bool = True,
    use_proportion: bool = True,
    m_chunk: int = 512,
    p_chunk: int = 16,
    exact_topk: bool = True,
    mesh=None,
):
    """One global multihost cycle with each host's critical path
    measured individually (CPU lockstep simulation — module docstring).

    Returns ``{"outputs": 11-tuple, "per_host": [{build_s, dispatch_s,
    fetch_s, path_s}], "critical_path_s", "solve_wait_s", "n_hosts"}``
    — walls are the best of ``reps`` timed repetitions (one untimed
    warmup rep absorbs the XLA compile)."""
    import jax

    from volcano_tpu.scheduler.fastpath.snapshot_build import (
        host_plane_shard,
    )

    if mesh is None:
        mesh = make_host_mesh(n_hosts)
    H = int(mesh.devices.shape[0])
    shardings = cycle_shardings(mesh, args)
    call = _jit_cycle(
        mesh, shardings, w_least, w_balanced,
        job_key_order=job_key_order,
        use_gang_ready=use_gang_ready,
        use_proportion=use_proportion,
        m_chunk=m_chunk,
        p_chunk=p_chunk,
        exact_topk=exact_topk,
    )
    host_devs = [list(mesh.devices[h].flat) for h in range(H)]
    amaps = {}
    for name, v in args.items():
        arr = np.asarray(v)
        sh = shardings[name]
        amaps[name] = (arr, sh, sh.addressable_devices_indices_map(arr.shape))

    best = None
    for rep in range(max(int(reps), 1) + 1):
        warmup = rep == 0
        prof = None if warmup else vtprof.PROFILER
        if prof is not None:
            prof.begin_cycle()
        build_s = [0.0] * H
        disp_s = [0.0] * H
        fetch_s = [0.0] * H
        # per-host snapshot-shard build: host h materializes ONLY its
        # slice of the task/node planes
        for h in range(H):
            t0 = time.perf_counter()
            host_plane_shard(args, h, H)
            build_s[h] = time.perf_counter() - t0
        # per-host device dispatch: host h puts the shards for ITS mesh
        # row's devices (other rows' puts are sim scaffolding for the
        # processes that would run concurrently — timed under THEIR host)
        pieces: Dict[str, Dict] = {name: {} for name in amaps}
        for h in range(H):
            t0 = time.perf_counter()
            for name, (arr, sh, dmap) in amaps.items():
                store = pieces[name]
                for dev in host_devs[h]:
                    store[dev] = jax.device_put(arr[dmap[dev]], dev)
            disp_s[h] = time.perf_counter() - t0
        device_args = {
            name: jax.make_array_from_single_device_arrays(
                arr.shape, sh, [pieces[name][d] for d in dmap]
            )
            for name, (arr, sh, dmap) in amaps.items()
        }
        # the SPMD cycle: every host calls the same jitted program —
        # the (async) call wall charges to each host
        t0 = time.perf_counter()
        out = call(device_args)
        call_s = time.perf_counter() - t0
        for h in range(H):
            disp_s[h] += call_s
        # device compute barrier: identical global program at every
        # host count (cfg9's claim) — reported, not host-attributed
        t0 = time.perf_counter()
        jax.block_until_ready(out)
        wait_s = time.perf_counter() - t0
        slices = []
        for h in range(H):
            t0 = time.perf_counter()
            slices.append(owned_output_slices(out, h, H))
            fetch_s[h] = time.perf_counter() - t0
        merged = merge_output_slices(slices)
        path = [build_s[h] + disp_s[h] + fetch_s[h] for h in range(H)]
        crit = int(np.argmax(path))
        if prof is not None:
            for h in range(H):
                prof.note_mesh_host(
                    h, build_s=build_s[h], dispatch_s=disp_s[h],
                    fetch_s=fetch_s[h],
                )
            prof.end_cycle(
                path[crit],
                {"build": build_s[crit], "dispatch": disp_s[crit],
                 "fetch": fetch_s[crit]},
                "multihost",
            )
        if warmup:
            continue
        rec = {
            "outputs": merged,
            "per_host": [
                {"build_s": build_s[h], "dispatch_s": disp_s[h],
                 "fetch_s": fetch_s[h], "path_s": path[h]}
                for h in range(H)
            ],
            "critical_path_s": max(path),
            "solve_wait_s": wait_s,
            "n_hosts": H,
        }
        if best is None or rec["critical_path_s"] < best["critical_path_s"]:
            best = rec
    return best


# -- process mode: one OS process per host --------------------------------


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except OSError:
        return False
    return True


def _result_paths(outdir: str, host: int) -> Tuple[str, str]:
    return (os.path.join(outdir, f"host{host:02d}.json"),
            os.path.join(outdir, f"host{host:02d}.npz"))


def _sim_args(ns):
    from volcano_tpu.scheduler.simargs import build_sim_args

    return build_sim_args(
        n_nodes=ns.nodes, n_tasks=ns.tasks, n_jobs=ns.jobs,
        n_queues=2, seed=ns.seed,
    )


def _worker(ns) -> int:
    """One mesh-host worker: run the lockstep cycle, ship the owned
    slices through the rendezvous dir.  If the coordinator dies at any
    checkpoint, degrade to a FULL single-host cycle (``fallback``) and
    exit cleanly — the degrade-not-wedge contract."""
    host = ns.host_id
    coord = ns.coordinator_pid or os.getppid()
    os.makedirs(ns.outdir, exist_ok=True)
    json_path, npz_path = _result_paths(ns.outdir, host)
    args = _sim_args(ns)

    fallback = not _pid_alive(coord)
    res = None
    if not fallback:
        res = run_lockstep(args, ns.mesh_hosts, reps=ns.reps,
                           exact_topk=True)
        # mid-cycle coordinator death: the rendezvous has no reader —
        # this host's owned slices alone cannot bind the cluster
        fallback = not _pid_alive(coord)
    if fallback:
        res = run_lockstep(args, 1, reps=1, exact_topk=True)
    outs = res["outputs"]
    if fallback:
        own = {n: np.asarray(outs[i]) for i, n in enumerate(OUTPUT_NAMES)}
    else:
        T = outs[0].shape[0]
        N = outs[6].shape[0]
        tlo, thi = host_bounds(T, ns.mesh_hosts)[host]
        nlo, nhi = host_bounds(N, ns.mesh_hosts)[host]
        own = {OUTPUT_NAMES[i]: outs[i][tlo:thi] for i in _TASK_OUT}
        own.update({OUTPUT_NAMES[i]: outs[i][nlo:nhi] for i in _NODE_OUT})
    np.savez(npz_path + ".tmp.npz", **own)
    os.replace(npz_path + ".tmp.npz", npz_path)
    payload = {
        "host": host, "fallback": fallback,
        "per_host": res["per_host"],
        "critical_path_s": res["critical_path_s"],
    }
    with open(json_path + ".tmp", "w", encoding="utf-8") as f:
        json.dump(payload, f)
    os.replace(json_path + ".tmp", json_path)
    if not ns.quiet:
        print(json.dumps(payload))
    return 0


def _coordinator(ns) -> int:
    """Spawn one worker process per non-coordinator host, run host 0's
    cycle, verify every worker's owned slices against the merged
    outputs.  A dead/late worker degrades the run to the coordinator's
    own full outputs (``degraded``) instead of wedging."""
    import subprocess
    import tempfile

    H = ns.mesh_hosts
    outdir = ns.outdir or tempfile.mkdtemp(prefix="vtmesh-")
    os.makedirs(outdir, exist_ok=True)
    procs = []
    base = [sys.executable, "-m", "volcano_tpu.parallel.multihost",
            "--mesh-hosts", str(H),
            "--nodes", str(ns.nodes), "--tasks", str(ns.tasks),
            "--jobs", str(ns.jobs), "--seed", str(ns.seed),
            "--reps", str(ns.reps), "--outdir", outdir,
            "--coordinator-pid", str(os.getpid()), "--quiet"]
    for h in range(1, H):
        procs.append(subprocess.Popen(base + ["--host-id", str(h)]))
    args = _sim_args(ns)
    res = run_lockstep(args, H, reps=ns.reps, exact_topk=True)
    outs = res["outputs"]
    degraded = False
    workers = []
    for h, p in zip(range(1, H), procs):
        row = {"host": h, "rc": None, "ok": False, "fallback": None}
        try:
            row["rc"] = p.wait(timeout=ns.timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=10)
            row["rc"] = -9
            degraded = True
            workers.append(row)
            continue
        json_path, npz_path = _result_paths(outdir, h)
        try:
            with open(json_path, encoding="utf-8") as f:
                wres = json.load(f)
            shipped = np.load(npz_path)
            row["fallback"] = bool(wres.get("fallback"))
            T = outs[0].shape[0]
            N = outs[6].shape[0]
            tlo, thi = host_bounds(T, H)[h]
            nlo, nhi = host_bounds(N, H)[h]
            ok = all(
                np.array_equal(shipped[OUTPUT_NAMES[i]],
                               outs[i][tlo:thi]) for i in _TASK_OUT
            ) and all(
                np.array_equal(shipped[OUTPUT_NAMES[i]],
                               outs[i][nlo:nhi]) for i in _NODE_OUT
            )
            row["ok"] = ok and row["rc"] == 0 and not row["fallback"]
            if not row["ok"]:
                degraded = True
        except (OSError, ValueError, KeyError):
            degraded = True
        workers.append(row)
    # degraded = the coordinator's own full outputs carry the cycle
    # (every host computed the identical SPMD program); the summary
    # says so instead of pretending the fleet fetched its slices
    summary = {
        # degraded still reports ok: the cycle completed on the
        # coordinator's full outputs (degrade, don't wedge) — the
        # ``degraded`` flag is what a supervisor alarms on
        "ok": degraded or all(w["ok"] for w in workers),
        "hosts": H,
        "degraded": degraded,
        "workers": workers,
        "per_host": res["per_host"],
        "critical_path_s": res["critical_path_s"],
        "solve_wait_s": res["solve_wait_s"],
        "binds": int((np.asarray(outs[1]) == 1).sum()),
    }
    print(json.dumps(summary))
    return 0


def _run_sweep(ns) -> int:
    """In-process host sweep (cfg9e/cfg9f capture): run the lockstep
    cycle at each host count, report per-host critical paths, the
    per-doubling scaling ratios, merged-output parity across host
    counts, and the vtprof attribution coverage."""
    import jax

    hosts = [int(x) for x in str(ns.sweep).split(",") if x.strip()]
    args = _sim_args(ns)
    profiler = vtprof.arm() if ns.prof else None
    sweep = {}
    ref = None
    parity = True
    try:
        for H in hosts:
            res = run_lockstep(args, H, reps=ns.reps, exact_topk=True)
            sweep[str(H)] = {
                "critical_path_s": round(res["critical_path_s"], 6),
                "solve_wait_s": round(res["solve_wait_s"], 6),
                "per_host": [
                    {k: round(v, 6) for k, v in row.items()}
                    for row in res["per_host"]
                ],
            }
            if ref is None:
                ref = res["outputs"]
            else:
                parity = parity and all(
                    np.array_equal(np.asarray(a), np.asarray(b))
                    for a, b in zip(ref, res["outputs"])
                )
        coverage = None
        if profiler is not None:
            coverage = round(
                vtprof.attribution(profiler.payload())["coverage"], 4
            )
    finally:
        if profiler is not None:
            vtprof.disarm()
    scaling = {
        f"{hosts[i]}->{hosts[i + 1]}": round(
            sweep[str(hosts[i + 1])]["critical_path_s"]
            / max(sweep[str(hosts[i])]["critical_path_s"], 1e-9), 3)
        for i in range(len(hosts) - 1)
    }
    payload = {
        "sweep": sweep,
        "scaling_per_doubling": scaling,
        "parity": parity,
        "prof_attribution": coverage,
        "binds": int((np.asarray(ref[1]) == 1).sum()),
        "n_nodes": ns.nodes, "n_tasks": ns.tasks, "n_jobs": ns.jobs,
        "n_devices": len(jax.devices()),
        "device": str(jax.devices()[0]),
    }
    print(json.dumps(payload))
    return 0


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m volcano_tpu.parallel.multihost",
        description="multi-controller mesh solve runner "
                    "(CPU-simulable: one process per host)",
    )
    ap.add_argument("--mesh-hosts", type=int,
                    default=int(os.environ.get("VOLCANO_TPU_MESH_HOSTS",
                                               "1")))
    ap.add_argument("--host-id", type=int, default=None,
                    help="worker mode (spawned by the coordinator)")
    ap.add_argument("--sweep", default="",
                    help="in-process host sweep, e.g. 1,2,4 (cfg9e)")
    ap.add_argument("--nodes", type=int, default=512)
    ap.add_argument("--tasks", type=int, default=2048)
    ap.add_argument("--jobs", type=int, default=128)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--reps", type=int, default=1)
    ap.add_argument("--prof", action="store_true",
                    help="arm vtprof for the run (sweep mode)")
    ap.add_argument("--outdir", default="",
                    help="rendezvous dir for worker results")
    ap.add_argument("--coordinator-pid", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--quiet", action="store_true")
    ns = ap.parse_args(argv)
    if ns.sweep:
        return _run_sweep(ns)
    if ns.host_id is not None:
        return _worker(ns)
    if ns.mesh_hosts > 1:
        return _coordinator(ns)
    # degenerate single host: one full cycle, the deployed-path shape
    res = run_lockstep(_sim_args(ns), 1, reps=ns.reps, exact_topk=True)
    print(json.dumps({
        "ok": True, "hosts": 1,
        "critical_path_s": round(res["critical_path_s"], 6),
        "binds": int((np.asarray(res["outputs"][1]) == 1).sum()),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
