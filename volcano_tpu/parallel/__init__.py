"""Multi-chip execution of the scheduler solve over a jax.sharding.Mesh.

The reference scales its hot loop with 16 worker goroutines on one host
(KB/pkg/scheduler/util/scheduler_helper.go:53,74); the TPU-native analogue
is SPMD over a device mesh: node state is sharded across chips, XLA
inserts the collectives (all-gather for the global node argmax/top-k,
psum-style scatter reductions) over ICI. See parallel/sharded.py.
"""

from volcano_tpu.parallel.multihost import (
    host_bounds,
    make_host_mesh,
    make_multihost_cycle,
    run_lockstep,
)
from volcano_tpu.parallel.sharded import (
    cycle_shardings,
    make_mesh,
    make_sharded_cycle,
    run_cycle_reference,
)

__all__ = [
    "cycle_shardings",
    "host_bounds",
    "make_host_mesh",
    "make_mesh",
    "make_multihost_cycle",
    "make_sharded_cycle",
    "run_cycle_reference",
    "run_lockstep",
]
