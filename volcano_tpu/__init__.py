"""volcano-tpu: a TPU-native batch-scheduling framework.

A ground-up rebuild of the capabilities of Volcano (sivanzcw/volcano):
gang/co-scheduling of multi-task jobs, weighted fair-share queues (DRF +
proportion), preemption and cross-queue reclaim, backfill, lifecycle-policy
driven error handling, admission validation and a CLI — with the scheduler's
hot task x node inner loops (predicate filtering, node scoring, fair-share
math, victim selection) implemented as jitted JAX/XLA solves over a
device-resident tensor snapshot of the cluster.

Layer map (mirrors reference SURVEY.md section 1):
  api/          object model (Job, PodGroup, Queue, Command, Pod, Node, Resource)
  store/        in-memory watchable object store (the "API server" bus analog)
  scheduler/    tensor snapshot, session, actions, plugins, JAX kernels
  controllers/  job reconciler + state machine + lifecycle policies
  admission/    validating + mutating webhook logic (pure functions)
  cli/          vtctl-style command line
"""

__version__ = "0.1.0"
