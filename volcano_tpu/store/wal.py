"""Segment write-ahead log: crash-consistent durability for the store server.

The reference treats etcd as the durable bus — every ACKed write survives
an apiserver crash because etcd fsyncs its raft log before replying
(SURVEY.md §1).  The StoreServer's interval snapshots explicitly did not:
with ``save_interval > 0`` a mutation was ACKed before persistence and up
to one interval of acknowledged writes died with the process.  This module
closes that gap with the same mechanism etcd uses, shaped for this store's
wire: an append-only log of CRC-framed records whose payloads ARE the
existing wire forms (per-op patches, whole ``DecisionSegment`` dicts from
store/segment.py — a 102k-bind cycle is ONE record, not 102k), fsynced in
group-commit batches before any 2xx leaves the server.

Layout: a directory of numbered segment files (``00000001.wal``, ...).
Each record is ``<u32 payload length><u32 crc32(payload)><payload json>``.
Appends go to the newest segment; a checkpoint (StoreServer.flush_state)
``rotate()``\\ s to a fresh segment under the server lock, snapshots the
store with the new segment index as its ``wal_floor``, and then
``drop_below(floor)`` unlinks the covered segments.  Recovery = load the
snapshot, replay every record in segments >= floor, torn-tail tolerant: a
truncated or CRC-failing record ends replay (the bytes after it are
discarded — they were never ACKed), never raises.

Group commit: appends are cheap buffered-at-the-OS writes (the file is
opened unbuffered, so a SIGKILLed process cannot lose a completed append
to a userspace buffer); ``commit(ticket)`` blocks until the record is
fsynced, with one leader thread fsyncing on behalf of every waiter that
arrived while the previous fsync was in flight — N concurrent mutations
pay ~1 fsync, and a decision segment amortizes one fsync over a whole
cycle's binds.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

from volcano_tpu.locksan import make_condition

#: per-record frame header: payload byte length + crc32(payload)
_HEADER = struct.Struct("<II")

#: segment file name shape (index order == replay order)
_SEG_FMT = "{:08d}.wal"


def _seg_path(dir_path: str, index: int) -> str:
    return os.path.join(dir_path, _SEG_FMT.format(index))


def list_segment_indices(dir_path: str):
    """Sorted indices of the segment files in ``dir_path`` (module-level:
    also used by WAL-off recovery to absorb a leftover tail)."""
    try:
        names = os.listdir(dir_path)
    except OSError:
        return []
    return sorted(i for i in (_seg_index(n) for n in names) if i is not None)


def fsync_dir(dir_path: str) -> None:
    """Make directory-entry changes (segment create, unlink, snapshot
    rename) durable: record-level fsyncs protect file DATA, but a power
    loss can still drop a freshly created name from an un-synced
    directory — taking every acked record in that segment with it."""
    try:
        fd = os.open(dir_path, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds: best effort
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _seg_index(name: str) -> Optional[int]:
    if not name.endswith(".wal"):
        return None
    stem = name[:-4]
    return int(stem) if stem.isdigit() else None


def frame_record(record: Dict[str, Any]) -> bytes:
    """One wire frame for ``record``: length + crc32 header, json payload."""
    payload = json.dumps(record, separators=(",", ":")).encode()
    return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def read_records(path: str) -> Tuple[List[Dict[str, Any]], bool]:
    """Every intact record in one segment file, in append order, plus
    whether the file ended torn (a truncated or CRC-failing record —
    discarded, never an error: bytes after the last intact frame were
    never fsync-ACKed, so dropping them IS the durability contract)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return out, True
    off, n = 0, len(data)
    while off + _HEADER.size <= n:
        length, crc = _HEADER.unpack_from(data, off)
        start = off + _HEADER.size
        end = start + length
        if end > n:
            return out, True  # torn tail: record advertised more bytes
        payload = data[start:end]
        if zlib.crc32(payload) != crc:
            return out, True  # torn/corrupt record: discard it and the rest
        try:
            out.append(json.loads(payload))
        except ValueError:
            return out, True
        off = end
    return out, off != n  # trailing partial header counts as torn


class WriteAheadLog:
    """Appendable segment WAL over a directory (see module docstring).

    Thread contract: ``append`` may run under the StoreServer lock (it
    only takes the WAL's own condition, never the reverse), ``commit``
    must run OUTSIDE the server lock — the fsync is the slow half and
    group commit exists so concurrent requests share it.
    """

    def __init__(self, dir_path: str):
        os.makedirs(dir_path, exist_ok=True)
        self.dir = dir_path
        self._cv = make_condition("WriteAheadLog._cv")
        self._appended = 0  # append tickets issued
        self._synced = 0  # highest ticket covered by an fsync
        self._syncing = False  # a leader fsync is in flight
        self._killed = False
        # observability (mirrored into volcano_store_wal_* by the server)
        self.appended_records = 0
        self.fsync_total = 0
        self.fsync_s = 0.0
        self.replayed_records = 0
        self.torn_tails = 0
        existing = self.segment_indices()
        self._index = (existing[-1] + 1) if existing else 1
        # a fresh segment per process: never append to a file whose tail
        # may be torn from the previous life
        self._f = open(_seg_path(self.dir, self._index), "ab", buffering=0)
        fsync_dir(self.dir)  # the new segment's NAME must survive too

    # -- append / group-commit fsync --------------------------------------

    def append(self, record: Dict[str, Any]) -> int:
        """Write one framed record (unbuffered; survives SIGKILL once the
        write returns) and return its commit ticket.  The record is NOT
        yet durable against power loss — ``commit(ticket)`` is the
        ACK barrier."""
        frame = frame_record(record)
        with self._cv:
            if self._killed:
                raise OSError("WAL killed")
            self._f.write(frame)
            self._appended += 1
            self.appended_records += 1
            return self._appended

    def commit(self, ticket: Optional[int] = None) -> None:
        """Block until every record up to ``ticket`` (default: all
        appended so far) is fsynced.  Leader-based group commit: the
        first waiter fsyncs everything appended so far; waiters that
        arrive mid-fsync are covered by the NEXT leader's single fsync."""
        import time as _time

        with self._cv:
            if ticket is None:
                ticket = self._appended
            while True:
                if self._synced >= ticket or self._killed:
                    return
                if not self._syncing:
                    break  # become the leader
                self._cv.wait()
            self._syncing = True
            target = self._appended
            fd = self._f.fileno()
        t0 = _time.perf_counter()
        ok = False
        try:
            os.fsync(fd)
            ok = True
        finally:
            dur = _time.perf_counter() - t0
            with self._cv:
                self._syncing = False
                if ok:
                    # advance ONLY on success: a failed fsync must leave
                    # the range un-synced so a follower retakes leadership
                    # and retries — marking it synced would 2xx mutations
                    # that were never made durable
                    self._synced = max(self._synced, target)
                    self.fsync_total += 1
                self.fsync_s += dur
                self._cv.notify_all()
        if ok:
            from volcano_tpu.scheduler import metrics

            metrics.register_wal_fsync()
            # group-commit fsync tail latency: the histogram behind
            # volcano_store_wal_fsync_seconds on /metrics and vtctl top
            metrics.observe_wal_fsync(dur)

    def append_commit(self, record: Dict[str, Any]) -> None:
        self.commit(self.append(record))

    def synced_ticket(self) -> int:
        """Highest append ticket covered by a successful fsync — the
        replication shipping watermark (store/replica.py): a record whose
        ticket is above this line has been ACKed to nobody and must never
        leave the process."""
        with self._cv:
            return self._synced

    # -- checkpoint protocol ----------------------------------------------

    def rotate(self) -> int:
        """Close the live segment and open the next one; returns the new
        segment index — the ``wal_floor`` for a snapshot taken in the
        same critical section (every record already appended lives in a
        segment below the floor; every later record lands at/above it)."""
        with self._cv:
            # a group-commit leader may be fsyncing this descriptor
            # outside the lock: closing it under them would turn an
            # applied, durable mutation into an EBADF 500 (or fsync a
            # reused fd); wait the in-flight sync out first
            while self._syncing:
                self._cv.wait()
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
            self._synced = self._appended
            self._index += 1
            self._f = open(_seg_path(self.dir, self._index), "ab", buffering=0)
            fsync_dir(self.dir)
            return self._index

    def drop_below(self, floor: int) -> None:
        """Unlink segments the snapshot now covers (index < floor).
        Called AFTER the snapshot's atomic rename — a crash in between
        leaves stale segments that the next recovery skips (and reaps)
        via the snapshot's recorded floor."""
        dropped = False
        for idx in self.segment_indices():
            # never the live segment: a restored-from-backup snapshot can
            # carry a floor ABOVE this life's rebuilt index — unlinking
            # the open file would turn every future acked append into an
            # anonymous-inode write the next recovery cannot see
            if idx < floor and idx < self._index:
                try:
                    os.unlink(_seg_path(self.dir, idx))
                    dropped = True
                except OSError:
                    pass
        if dropped:
            fsync_dir(self.dir)

    def drop_all(self) -> None:
        """Discard every non-live segment — stale lineage (the newest
        snapshot was written by a WAL-off life; see StoreServer._recover)."""
        self.drop_below(self._index)

    def segment_indices(self) -> List[int]:
        return list_segment_indices(self.dir)

    # -- recovery ----------------------------------------------------------

    def replay(self, floor: int = 0) -> Iterator[Dict[str, Any]]:
        """Yield every intact record from segments >= ``floor`` in append
        order; stale segments below the floor are reaped.  A torn/CRC-
        failing record ends replay of ITS segment only — torn bytes are
        by construction un-ACKed (the frame never finished, so no fsync
        covered it and no 2xx left the server), while records in LATER
        segments were appended by a later process life on top of exactly
        this repaired prefix, so replay continues through them."""
        self.drop_below(floor)
        for idx in self.segment_indices():
            if idx < floor or idx >= self._index:
                continue  # own live segment is empty by construction
            records, torn = read_records(_seg_path(self.dir, idx))
            for rec in records:
                self.replayed_records += 1
                yield rec
            if torn:
                self.torn_tails += 1

    # -- lifecycle ----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            return {
                "records": self.appended_records,
                "fsync_total": self.fsync_total,
                "fsync_s": round(self.fsync_s, 4),
                "replayed_records": self.replayed_records,
                "torn_tails": self.torn_tails,
                "segment": self._index,
            }

    def sync_close(self) -> None:
        """Graceful shutdown: fsync the tail, close the segment."""
        with self._cv:
            if self._killed:
                return
            while self._syncing:  # same descriptor-close race as rotate()
                self._cv.wait()
            self._killed = True
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            finally:
                self._f.close()
            self._synced = self._appended
            self._cv.notify_all()

    def kill(self) -> None:
        """Crash-harness hook: die like SIGKILL — close the descriptor
        with NO fsync and refuse further appends.  (Unbuffered appends
        already issued are in the page cache, exactly as they would be
        after a real process kill.)"""
        with self._cv:
            if self._killed:
                return
            self._killed = True
            try:
                self._f.close()
            except OSError:
                pass
            self._cv.notify_all()
