from volcano_tpu.store.store import Conflict, Event, EventType, Store

__all__ = ["Store", "Event", "EventType", "Conflict"]
