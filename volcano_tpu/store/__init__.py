from volcano_tpu.store.store import Store, Event, EventType

__all__ = ["Store", "Event", "EventType"]
