"""Columnar decision segments: one wire unit for a whole cycle's output.

The r5 publish path shipped ~102k per-object dict ops per cfg7 cycle
(bind patches compressed by ``patch_col``, but every Scheduled Event was
still a full per-object encode) and the server expanded them back into
per-object ``Store.patch``/``create`` calls — 14.9 s of off-cycle drain
at 100k tasks x 10k nodes (BASELINE.md r5).  A ``DecisionSegment`` is
the columnar alternative: parallel columns (task keys, node ids, reason
codes) over interned string tables, built STRAIGHT from the fast cycle's
solve-output arrays, carried in ONE bulk op, and applied server-side
under one lock acquisition with lazy per-object materialization
(store entries and Scheduled/Evict Events materialize on first read —
see Store.apply_segment_lazy and the StoreServer ``segment`` verb).

The log-block classes at the bottom are the server's columnar watch
cache: the event log holds one block per segment section instead of one
encoded dict per object, and watch fan-out expands rows lazily (memoized
once per block, shared by every watcher) into dicts byte-compatible with
the r5 per-object log entries.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from volcano_tpu.api.objects import Metadata, reserve_uids
from volcano_tpu.events import (
    NORMAL,
    WARNING,
    ClusterEvent,
    evicted_message,
    scheduled_message,
)

#: reason strings for the two event sections a segment carries
BIND_REASON = "Scheduled"
EVICT_REASON = "Evict"


class DecisionSegment:
    """One cycle's binds + evicts in columnar form.

    ``bind_keys[i]`` is placed on ``node_table[bind_nodes[i]]``;
    ``evict_keys[j]`` is evicted for ``reason_table[evict_reasons[j]]``.
    ``ev_token``/``ev_start`` reserve the uid block the per-decision
    Events draw their names from (``event_name``), so the server can
    materialize Event objects lazily without a uid round trip.
    """

    __slots__ = (
        "bind_keys", "bind_nodes", "node_table",
        "evict_keys", "evict_reasons", "reason_table",
        "ev_token", "ev_start", "_hosts", "_reasons",
    )

    def __init__(self, bind_keys, bind_nodes, node_table,
                 evict_keys, evict_reasons, reason_table,
                 ev_token, ev_start):
        self.bind_keys: List[str] = bind_keys
        self.bind_nodes: List[int] = bind_nodes
        self.node_table: List[str] = node_table
        self.evict_keys: List[str] = evict_keys
        self.evict_reasons: List[int] = evict_reasons
        self.reason_table: List[str] = reason_table
        self.ev_token: str = ev_token
        self.ev_start: int = ev_start
        self._hosts: Optional[List[str]] = None
        self._reasons: Optional[List[str]] = None

    @classmethod
    def build(cls, bind_keys: List[str], bind_nodes: List[int],
              node_table: List[str],
              evicts: Optional[List[Tuple[str, str]]] = None,
              ) -> "DecisionSegment":
        """Assemble a segment from the publish tail's columns.  ``evicts``
        (small: storm victims) are interned here; binds arrive already
        columnar from the solve outputs."""
        evict_keys: List[str] = []
        evict_reasons: List[int] = []
        reason_table: List[str] = []
        if evicts:
            interned: Dict[str, int] = {}
            for key, reason in evicts:
                idx = interned.get(reason)
                if idx is None:
                    idx = interned[reason] = len(reason_table)
                    reason_table.append(reason)
                evict_keys.append(key)
                evict_reasons.append(idx)
        token, start = reserve_uids("event", len(bind_keys) + len(evict_keys))
        return cls(bind_keys, bind_nodes, node_table,
                   evict_keys, evict_reasons, reason_table, token, start)

    # -- derived columns (memoized: submit bookkeeping + logs reuse them) ----

    @property
    def bind_hosts(self) -> List[str]:
        if self._hosts is None:
            table = self.node_table
            self._hosts = [table[i] for i in self.bind_nodes]
        return self._hosts

    @property
    def evict_reason_strs(self) -> List[str]:
        if self._reasons is None:
            table = self.reason_table
            self._reasons = [table[i] for i in self.evict_reasons]
        return self._reasons

    @property
    def empty(self) -> bool:
        return not self.bind_keys and not self.evict_keys

    def bind_pairs(self) -> List[Tuple[str, str]]:
        return list(zip(self.bind_keys, self.bind_hosts))

    def evict_pairs(self) -> List[Tuple[str, str]]:
        return list(zip(self.evict_keys, self.evict_reason_strs))

    # -- wire --------------------------------------------------------------

    def to_wire(self) -> Dict[str, Any]:
        return {
            "op": "segment",
            "binds": {"keys": self.bind_keys, "nodes": self.bind_nodes,
                      "node_table": self.node_table},
            "evicts": {"keys": self.evict_keys,
                       "reasons": self.evict_reasons,
                       "reason_table": self.reason_table},
            "events": {"token": self.ev_token, "start": self.ev_start},
        }

    @classmethod
    def from_wire(cls, op: Dict[str, Any]) -> "DecisionSegment":
        b = op.get("binds") or {}
        e = op.get("evicts") or {}
        ev = op.get("events") or {}
        return cls(
            b.get("keys") or [], b.get("nodes") or [],
            b.get("node_table") or [],
            e.get("keys") or [], e.get("reasons") or [],
            e.get("reason_table") or [],
            str(ev.get("token") or ""), int(ev.get("start") or 0),
        )


def event_name(token: str, idx: int) -> str:
    """The Event object name for uid-block slot ``idx`` — the same wire
    shape ``new_uid('event')`` produces, so segment-born Events sort and
    aggregate exactly like per-object ones."""
    return f"event-{token}-{idx:08d}"


def materialize_event(name: str, involved_key: str, reason: str,
                      message: str, type_: str, rv: int,
                      stamp: float) -> ClusterEvent:
    """Build the ClusterEvent a segment row denotes.  uid == name (both
    are unique and monotonic within the reserved block), so
    ``events_for``'s uid ordering matches creation order."""
    return ClusterEvent(
        meta=Metadata(name=name, namespace="", uid=name,
                      resource_version=rv, creation_timestamp=stamp),
        involved=("Pod", involved_key),
        reason=reason,
        message=message,
        type=type_,
    )


def encode_event_row(name: str, involved_key: str, reason: str,
                     message: str, type_: str, rv: int,
                     stamp: float) -> Dict[str, Any]:
    """The codec encoding of ``materialize_event(...)``, built directly —
    field-for-field identical to ``codec.encode(ClusterEvent(...))``
    (tests/test_columnar_wire.py proves the byte equality)."""
    return {
        "meta": {
            "name": name, "namespace": "", "uid": name,
            "labels": {}, "annotations": {},
            "resource_version": rv, "creation_timestamp": stamp,
            "owner": None,
        },
        "involved": ["Pod", involved_key],
        "reason": reason,
        "message": message,
        "type": type_,
        "count": 1,
    }


# -- server-side columnar log blocks ----------------------------------------


class PatchLogBlock:
    """A run of same-field scalar patches in the server's event log: one
    block instead of N encoded-dict entries.  Rows expand lazily into
    dicts byte-compatible with the per-object COW patch entries the r5
    ``_encode_event_obj`` produced (``object`` = pre-encoding + delta,
    ``old`` = the shared pre-encoding reference)."""

    kind = "Pod"
    type = "Updated"

    __slots__ = ("field", "keys", "values", "pre", "rv0", "seq0", "post",
                 "_rows")

    def __init__(self, field: str, keys: List[str], values: List[Any],
                 pre: List[Dict[str, Any]], rv0: int):
        self.field = field
        self.keys = keys
        self.values = values  # parallel to keys (per-row scalars)
        self.pre = pre
        self.rv0 = rv0  # resource_version of row 0
        self.seq0 = 0  # seq of row 0, stamped when appended to the log
        self.post: List[Optional[Dict[str, Any]]] = [None] * len(keys)
        self._rows: Optional[List[Dict[str, Any]]] = None

    def __len__(self) -> int:
        return len(self.keys)

    def materialize_enc(self, i: int) -> Dict[str, Any]:
        enc = self.post[i]
        if enc is None:
            enc = dict(self.pre[i])
            meta = dict(enc["meta"])
            meta["resource_version"] = self.rv0 + i
            enc["meta"] = meta
            enc[self.field] = self.values[i]
            self.post[i] = enc
        return enc

    def wire_rows(self, a: int, b: int) -> List[Dict[str, Any]]:
        rows = self._rows
        if rows is None:
            seq0, kind, type_, pre = self.seq0, self.kind, self.type, self.pre
            rows = self._rows = [
                {"seq": seq0 + i, "kind": kind, "type": type_,
                 "object": self.materialize_enc(i), "old": pre[i]}
                for i in range(len(self.keys))
            ]
        return rows[a:b]


class EventLogBlock:
    """A run of segment-born Event creates in the server's log.  Rows
    never exist as ClusterEvent objects here — names, messages, and
    encodings derive from the columns on demand (``Store`` materializes
    the objects separately, only when an Event read asks for them)."""

    kind = "Event"
    type = "Added"

    __slots__ = ("reason", "ev_type", "token", "uid_idx", "inv_keys",
                 "values", "rv0", "stamp", "seq0", "encs", "_rows")

    def __init__(self, reason: str, token: str, uid_idx: List[int],
                 inv_keys: List[str], values: List[str], rv0: int,
                 stamp: float):
        self.reason = reason
        self.ev_type = WARNING if reason == EVICT_REASON else NORMAL
        self.token = token
        self.uid_idx = uid_idx  # uid-block slot per row
        self.inv_keys = inv_keys
        self.values = values  # hostnames (binds) / reason strings (evicts)
        self.rv0 = rv0
        self.stamp = stamp
        self.seq0 = 0
        self.encs: List[Optional[Dict[str, Any]]] = [None] * len(inv_keys)
        self._rows: Optional[List[Dict[str, Any]]] = None

    def __len__(self) -> int:
        return len(self.inv_keys)

    def name(self, i: int) -> str:
        return event_name(self.token, self.uid_idx[i])

    def key(self, i: int) -> str:
        return f"/{self.name(i)}"  # Metadata.key with namespace ""

    def message(self, i: int) -> str:
        if self.reason == BIND_REASON:
            return scheduled_message(self.inv_keys[i], self.values[i])
        return evicted_message(self.values[i])

    def materialize(self, i: int) -> ClusterEvent:
        return materialize_event(
            self.name(i), self.inv_keys[i], self.reason, self.message(i),
            self.ev_type, self.rv0 + i, self.stamp,
        )

    def materialize_enc(self, i: int) -> Dict[str, Any]:
        enc = self.encs[i]
        if enc is None:
            enc = encode_event_row(
                self.name(i), self.inv_keys[i], self.reason,
                self.message(i), self.ev_type, self.rv0 + i, self.stamp,
            )
            self.encs[i] = enc
        return enc

    def wire_rows(self, a: int, b: int) -> List[Dict[str, Any]]:
        rows = self._rows
        if rows is None:
            seq0, kind, type_ = self.seq0, self.kind, self.type
            rows = self._rows = [
                {"seq": seq0 + i, "kind": kind, "type": type_,
                 "object": self.materialize_enc(i), "old": None}
                for i in range(len(self.inv_keys))
            ]
        return rows[a:b]
