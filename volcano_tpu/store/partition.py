"""Partitioned store bus: shard the decision stream by namespace hash.

ROADMAP item 1's store half.  The columnar wire (PR 6) made a cycle's
output ONE ``DecisionSegment`` and the WAL (PR 7) made it ONE durable
record — but both still funnel through one server lock, one WAL file,
one fsync leader, and one watch log: cfg7's 1.7–2.1 s drain is a single
pipe however many decisions it carries.  This module partitions that
pipe.  The shard key is the **namespace hash** (``shard_of``): every
decision row, WAL record, and watch-log entry for a namespace lands on
the same shard deterministically, so per-shard streams are complete and
ordered for the objects they cover.

Three pieces:

* ``split_segment`` — the client half: one cycle's ``DecisionSegment``
  splits into per-shard sub-segments (row order preserved within a
  shard, node tables re-interned per shard, one reserved Event uid block
  per sub-segment).  The async applier ships them concurrently; the
  server applies each under its shard's apply lock.

* ``ShardedWAL`` — per-shard ``WriteAheadLog`` directories
  (``<wal>/s00``, ``s01``, …) with INDEPENDENT group-commit fsync: a
  segment for shard 2 never waits behind shard 0's fsync leader, and
  concurrent sub-segment ships fsync different files in parallel.
  Records keep their global ``seq`` stamps, so recovery merges the
  shards' tails back into one ordered replay.

* ``shard_of``/``shard_of_key``/``wal_shard`` — the one hash everybody
  agrees on (client split, server routing, WAL placement, watch
  tagging).  Cluster-scoped objects (namespace ``""``) hash like any
  other namespace — deterministically onto one shard.

StoreServer grows ``shards=N`` (server.py): shard-tagged watch-log
entries, ``/watch?shard=i`` fan-out, per-shard apply locks, and the
sharded WAL wired through the existing checkpoint/recovery protocol
(per-shard floors in the snapshot's ``wal_floor``).  ``shards=1`` is
byte-for-byte the unpartitioned server.
"""

from __future__ import annotations

import os
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

from volcano_tpu.locksan import make_lock

#: subdirectory name shape for one shard's WAL segments
_SHARD_DIR_FMT = "s{:02d}"


def shard_wal_dir(wal_dir: str, shard: int) -> str:
    """The WAL directory shard ``shard`` owns under a partitioned bus's
    root (``<wal>/s00`` …).  ShardedWAL (in-process shards) and the
    procmesh supervisor (per-shard OS processes) both build from this,
    so the SAME directory layout serves either deployment — a mesh shard
    process recovers exactly the segments its in-process predecessor
    appended, and vice versa."""
    return os.path.join(wal_dir, _SHARD_DIR_FMT.format(int(shard)))


def shard_of(namespace: str, nshards: int) -> int:
    """The shard a namespace's decision stream lands on: crc32 of the
    namespace modulo the shard count — stable across processes and runs
    (never Python's salted ``hash``)."""
    if nshards <= 1:
        return 0
    return zlib.crc32(namespace.encode()) % nshards


def shard_of_key(key: str, nshards: int) -> int:
    """Shard of an object key (``namespace/name``; cluster-scoped keys
    carry an empty namespace and hash like any other)."""
    if nshards <= 1:
        return 0
    ns, _, _ = key.partition("/")
    return shard_of(ns, nshards)


def wal_shard(rec: Dict[str, Any], nshards: int) -> int:
    """The WAL shard one wire record belongs to.  Segments carry their
    shard explicitly (the client split already decided); per-op records
    route by their object's namespace so one namespace's history stays
    on one shard (replay order within a shard == append order)."""
    if nshards <= 1:
        return 0
    if rec.get("op") == "segment":
        return int(rec.get("shard", 0)) % nshards
    key = rec.get("key")
    if isinstance(key, str):
        return shard_of_key(key, nshards)
    keys = rec.get("keys")
    if isinstance(keys, list) and keys and isinstance(keys[0], str):
        # columnar patch run: the client compresses runs per cycle —
        # rows of one run share a kind and, in practice, a namespace
        # stream; route by the first key (deterministic either way)
        return shard_of_key(keys[0], nshards)
    obj = rec.get("object")
    if isinstance(obj, dict):
        meta = obj.get("meta") or {}
        return shard_of(str(meta.get("namespace") or ""), nshards)
    return 0


def split_segment(seg, nshards: int) -> List[Tuple[int, Any]]:
    """Split one cycle's ``DecisionSegment`` into per-shard sub-segments.

    Rows keep their original relative order within a shard; node tables
    re-intern only the nodes a shard references; every non-empty
    sub-segment reserves its OWN Event uid block (``DecisionSegment.
    build``), so the server derives its Event names with no cross-shard
    coordination.  Returns ``[(shard, sub_segment)]`` for the non-empty
    shards — callers ship each with the ``shard`` tag on the wire op.
    """
    from volcano_tpu.store.segment import DecisionSegment

    if nshards <= 1:
        return [(0, seg)]
    binds: List[List[Tuple[str, str]]] = [[] for _ in range(nshards)]
    evicts: List[List[Tuple[str, str]]] = [[] for _ in range(nshards)]
    table = seg.node_table
    # namespace -> shard memo: the hash runs once per DISTINCT namespace
    # (dozens), not once per row (100k+) — the split is on the drain path
    ns_shard: Dict[str, int] = {}

    def _shard(key: str) -> int:
        ns, _, _ = key.partition("/")
        s = ns_shard.get(ns)
        if s is None:
            s = ns_shard[ns] = shard_of(ns, nshards)
        return s

    for i, key in enumerate(seg.bind_keys):
        binds[_shard(key)].append((key, table[seg.bind_nodes[i]]))
    reasons = seg.evict_reason_strs
    for j, key in enumerate(seg.evict_keys):
        evicts[_shard(key)].append((key, reasons[j]))
    out: List[Tuple[int, Any]] = []
    for s in range(nshards):
        if not binds[s] and not evicts[s]:
            continue
        interned: Dict[str, int] = {}
        node_table: List[str] = []
        bind_keys: List[str] = []
        bind_nodes: List[int] = []
        for key, host in binds[s]:
            idx = interned.get(host)
            if idx is None:
                idx = interned[host] = len(node_table)
                node_table.append(host)
            bind_keys.append(key)
            bind_nodes.append(idx)
        out.append((s, DecisionSegment.build(
            bind_keys, bind_nodes, node_table, evicts[s] or None
        )))
    return out


class ShardedWAL:
    """N independent ``WriteAheadLog``\\ s under one directory, one per
    shard (``s00/``, ``s01/``, …), presenting the single-WAL surface the
    StoreServer's checkpoint/recovery protocol already speaks — except
    ``rotate``/``replay``/``drop_below`` carry a per-shard floor LIST
    and ``append`` takes the target shard.

    Independence is the point: each shard has its own fsync leader, so
    group commit batches per shard and concurrent sub-segment ships
    never share a durability barrier.  Global ordering is recovered at
    replay from the records' ``seq`` stamps (assigned under the server
    lock), merged across shards.
    """

    def __init__(self, dir_path: str, nshards: int):
        from volcano_tpu.store.wal import WriteAheadLog

        if nshards < 2:
            raise ValueError("ShardedWAL needs >= 2 shards; use "
                             "WriteAheadLog for the single-shard bus")
        os.makedirs(dir_path, exist_ok=True)
        self.dir = dir_path
        self.nshards = nshards
        self.wals: List[WriteAheadLog] = [
            WriteAheadLog(shard_wal_dir(dir_path, s))
            for s in range(nshards)
        ]
        # serializes floor bookkeeping across rotate/drop (each shard's
        # own appends/fsyncs stay under its WAL's condition, untouched)
        self._mu = make_lock("ShardedWAL._mu")

    # -- append / group commit --------------------------------------------

    def append(self, rec: Dict[str, Any], shard: Optional[int] = None) -> int:
        s = wal_shard(rec, self.nshards) if shard is None else shard
        return self.wals[s % self.nshards].append(rec)

    def commit(self, ticket: Optional[int] = None) -> None:
        """Fsync every shard with un-synced appends.  Each shard's
        ``commit`` returns immediately when its tail is already durable,
        so a request that touched one shard pays one fsync — and two
        requests on different shards pay two CONCURRENT fsyncs, never a
        shared leader."""
        for w in self.wals:
            w.commit()

    def synced_tickets(self) -> List[int]:
        """Per-shard fsync watermarks (see WriteAheadLog.synced_ticket):
        the replication feed ships a record only once ITS shard's
        watermark covers its append ticket."""
        return [w.synced_ticket() for w in self.wals]

    # -- checkpoint protocol ----------------------------------------------

    def rotate(self) -> List[int]:
        """Rotate every shard; returns the per-shard floor list — the
        snapshot's ``wal_floor`` payload for a partitioned bus."""
        with self._mu:
            return [w.rotate() for w in self.wals]

    def drop_below(self, floors) -> None:
        with self._mu:
            for w, f in zip(self.wals, self._floor_list(floors)):
                w.drop_below(f)

    def drop_all(self) -> None:
        with self._mu:
            for w in self.wals:
                w.drop_all()

    def _floor_list(self, floors) -> List[int]:
        if isinstance(floors, int):
            # a floor stamped by a single-shard life: only meaningful as
            # "everything covered" (recovery re-absorbs via seq merge)
            return [floors] * self.nshards
        out = [int(f) for f in floors]
        if len(out) < self.nshards:
            out += [0] * (self.nshards - len(out))
        return out[: self.nshards]

    # -- recovery ----------------------------------------------------------

    def replay(self, floors=0) -> Iterator[Dict[str, Any]]:
        """Every intact record from every shard's segments at/above its
        floor, merged into GLOBAL order by the records' ``seq`` stamps
        (append order within a shard is preserved by the stable sort —
        ties can only be same-shard records appended under one seq,
        which the server never produces)."""
        records: List[Tuple[int, int, Dict[str, Any]]] = []
        for s, (w, f) in enumerate(
            zip(self.wals, self._floor_list(floors))
        ):
            for i, rec in enumerate(w.replay(f)):
                records.append((int(rec.get("seq", 0)), i, rec))
        records.sort(key=lambda t: (t[0], t[1]))
        for _, _, rec in records:
            yield rec

    @property
    def torn_tails(self) -> int:
        return sum(w.torn_tails for w in self.wals)

    # -- lifecycle ----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        per = [w.stats() for w in self.wals]
        return {
            "shards": self.nshards,
            "records": sum(p["records"] for p in per),
            "fsync_total": sum(p["fsync_total"] for p in per),
            "fsync_s": round(sum(p["fsync_s"] for p in per), 4),
            "replayed_records": sum(p["replayed_records"] for p in per),
            "torn_tails": sum(p["torn_tails"] for p in per),
            "per_shard": per,
        }

    def sync_close(self) -> None:
        for w in self.wals:
            w.sync_close()

    def kill(self) -> None:
        for w in self.wals:
            w.kill()


def leftover_shard_dirs(wal_dir: str) -> List[str]:
    """Shard subdirectories left by a crashed partitioned WAL-on life
    (``<wal>/s00`` …) — the WAL-off absorb path scans these too, so
    dropping from a partitioned bus to interval persistence can't
    silently lose an acked tail."""
    try:
        names = os.listdir(wal_dir)
    except OSError:
        return []
    out = []
    for n in sorted(names):
        p = os.path.join(wal_dir, n)
        if (
            len(n) == 3 and n.startswith("s") and n[1:].isdigit()
            and os.path.isdir(p)
        ):
            out.append(p)
    return out
