"""vtrepl: WAL-shipping replication for the store bus.

The segment WAL (store/wal.py) is already a physical replication log:
every ACKed mutation is one CRC-framed wire record with seq/rv stamps.
This module ships those records to N follower replicas over a long-poll
``/repl/feed?from=seq`` endpoint and replays them through the SAME live
verb paths the leader ran — so a follower's columnar caches, watch
streams, and digest tables are byte-identical to the leader's, and the
read side (watch fan-out, ``vtctl top``, dashboards, ``/debug/*``)
scales horizontally while the single writer stays put.

Core invariants:

- **Group-commit watermark.**  A record ships only once its WAL shard's
  fsync watermark covers its append ticket (``synced_ticket``): an
  unfsynced record has been ACKed to nobody and must never leave the
  process — a leader crash may legitimately lose it, and a follower
  that replayed it would hold state the recovered leader cannot
  reproduce.
- **Same seq/rv line.**  Followers replay records verbatim (their own
  WAL appends keep the leader's seq/rv stamps), and digest beacons —
  which consume a seq but are never WAL'd — ship as synthetic
  ``{"op": "beacon"}`` feed records so the seq lines never drift.  The
  follower stamps its OWN digest at the beacon seq and compares roots
  against the leader's payload: continuous replication-divergence
  detection riding the existing vtaudit beacons.
- **Epoch fencing.**  Every leadership (boot or promotion) bumps an
  epoch that rides ``/healthz``, watch responses, and the feed.  An
  epoch change means the seq line may have forked: followers resync
  from a snapshot, and RemoteStore turns the change into ONE StaleWatch
  relist — the failover cursor-gap contract.
- **Failover rides LeaderElector.**  The leader renews a replicated
  ``vt-store`` Lease through its own mutation verbs (so renewals are
  WAL'd and shipped).  Followers watch their local copy expire; the
  highest-``(applied_seq, identity)`` reachable candidate takes the
  lease over via the stock ``LeaderElector`` CAS, bumps the epoch, and
  stamps a floored checkpoint.  ``--repl-ack sync`` makes every client
  2xx wait for >= 1 follower to append (and fsync) the record, so a
  promoted follower provably holds every acked mutation.
"""

from __future__ import annotations

import bisect
import http.client
import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Any, Dict, List, Optional

from volcano_tpu import effectsan, trace, vtaudit
from volcano_tpu.backoff import Backoff
from volcano_tpu.chaos import InjectedCrash, crash_point
from volcano_tpu.leader import LeaderElector
from volcano_tpu.locksan import make_lock
from volcano_tpu.store.codec import decode_fields, decode_object, encode
from volcano_tpu.store.store import Conflict, PreconditionFailed

#: the replicated leadership lease (LeaderElector name)
LEASE_NAME = "vt-store"

#: cap on retained shippable records; a follower further behind resyncs
#: from a snapshot (the feed's "resourceVersion too old")
REPL_LOG_CAP = 50_000

#: max records per feed response (keeps one reply bounded; the follower
#: immediately re-polls for the rest)
FEED_BATCH = 512

#: hard ceiling on one feed long-poll
FEED_POLL_MAX = 30.0

#: transients the pump retries (decorrelated-jitter Backoff, never a
#: fixed sleep — the retry-backoff lint contract)
_TRANSIENT = (OSError, http.client.HTTPException, ValueError)


class ReplicationAckTimeout(RuntimeError):
    """sync ack mode: no follower acked the record in time — the 2xx is
    withheld (the handler's wire boundary turns this into a 5xx)."""


def _http_json(url: str, timeout: float):
    """One GET, JSON-decoded: ``(status, body)``.  HTTP errors return
    their code/body like RemoteStore._request; connection errors raise."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read() or b"{}")
        except ValueError:
            body = {"error": str(e)}
        return e.code, body


class _ServerStore:
    """Store facade over the local StoreServer's mutation verbs, for the
    stock LeaderElector: lease create/renew/takeover go through the
    verbs (not raw Store calls) so they are WAL'd and replicated like
    any client write.  Lease traffic never waits on the sync-ack barrier
    (``_repl_sync=False``): the lease is soft state — blocking renewals
    on follower liveness would deadlock a leader whose followers are
    still booting."""

    def __init__(self, srv):
        self._srv = srv

    def get(self, kind: str, key: str):
        with self._srv.lock:
            obj = self._srv.store.get(kind, key)
        if obj is None:
            return None
        # wire round-trip copy: the elector mutates what it gets before
        # its CAS — handing it the live object would let a LOST race
        # leave an un-evented in-place edit behind
        return decode_object(kind, encode(obj))

    def create(self, kind: str, obj):
        code, body = self._srv.create(kind, {"object": encode(obj)})
        if code == 409:
            raise KeyError(body.get("error", "exists"))
        if code >= 400:
            raise RuntimeError(body.get("error", f"http {code}"))
        self._srv._commit_ack(_repl_sync=False)
        return obj

    def _update(self, kind: str, obj, expected_rv=None):
        code, body = self._srv.update(kind, {"object": encode(obj)},
                                      expected_rv=expected_rv)
        if code == 409 and body.get("conflict"):
            raise Conflict(body.get("error", "conflict"))
        if code == 404:
            raise KeyError(body.get("error", "not found"))
        if code >= 400:
            raise RuntimeError(body.get("error", f"http {code}"))
        self._srv._commit_ack(_repl_sync=False)
        return obj

    def update(self, kind: str, obj):
        return self._update(kind, obj)

    def update_cas(self, kind: str, obj, expected_rv: int):
        return self._update(kind, obj, expected_rv=expected_rv)


class Replicator:
    """Per-server replication state machine: the leader half (shippable
    record log + watermark + follower ack ledger + sync-ack barrier) and
    the follower half (feed pump, live-path replay, election/promotion).
    One instance per StoreServer; the role flips in place on promotion."""

    def __init__(self, srv, identity: Optional[str] = None,
                 peers: Optional[List[str]] = None,
                 leader_url: Optional[str] = None,
                 ack: str = "async",
                 lease_duration: float = 5.0,
                 ack_timeout: float = 10.0,
                 lease_name: Optional[str] = None):
        if srv.wal is None:
            raise ValueError("replication requires --wal (the WAL is the "
                             "replication log)")
        self.srv = srv
        #: identity doubles as the advertised URL (lease holder == the
        #: leader's base URL, so followers can follow the lease)
        self.identity = (identity or srv.url).rstrip("/")
        self.peers = [p.rstrip("/") for p in (peers or [])
                      if p.rstrip("/") != self.identity]
        self.role = "follower" if leader_url else "leader"
        self.leader_url = (leader_url or self.identity).rstrip("/")
        if ack not in ("async", "sync"):
            raise ValueError(f"unknown repl ack mode {ack!r}")
        self.ack = ack
        self.ack_timeout = ack_timeout
        self.lease_duration = lease_duration
        #: lease object name: one lease per replica GROUP.  A procmesh
        #: shard group must qualify it (vt-store-sNN) — every shard
        #: leader maintains its lease in its OWN shard store, and a
        #: shared name would make the merged list collapse N distinct
        #: leases onto one key while the shard-root rollup sums all N
        self.lease_name = lease_name or LEASE_NAME
        # epoch: one per leadership.  A booting leader bumps past the
        # snapshot's persisted epoch so followers of the previous life
        # (whose applied beacons may exceed the recovered WAL) resync.
        snap = int(getattr(srv, "_snap_repl_epoch", 0))
        self.epoch = snap + 1 if self.role == "leader" else max(snap, 0)
        # lock order: srv.lock may be held when taking _mu (log_append
        # under the mutation path); _mu is NEVER held across srv.lock
        self._mu = make_lock("Replicator._mu")
        self._cv = threading.Condition(self._mu)      # watermark advanced
        self._ack_cv = threading.Condition(self._mu)  # follower acks moved
        self._pending: deque = deque()   # (seq, rec, wal_shard, ticket)
        self._shipped_seqs: List[int] = []
        self._shipped: List[Dict[str, Any]] = []
        self._base_seq = srv.seq   # feedable horizon (same-epoch laggards)
        self._ship_seq = srv.seq
        self.acks: Dict[str, int] = {}
        self._ack_time: Dict[str, float] = {}
        self._tl = threading.local()
        self.applied = srv.seq
        self.divergence = 0
        self.shipped_total = 0
        self.snapshots_served = 0
        self.promotions = 0
        # promotion clock: wall time (lease stamps must compare across
        # processes), chaos-skewable at the repl.lease faultpoint.  The
        # plan is read per-call from srv.chaos so lease skew armed over
        # POST /chaos hits a LIVE replica, like every other faultpoint

        def _promo_clock() -> float:
            now = time.time()
            plan = self.srv.chaos
            if plan is not None:
                rule = plan.fire("repl.lease")
                if rule is not None and rule.action == "skew":
                    return now + rule.arg
            return now

        self._clock = _promo_clock
        self._elector = LeaderElector(
            _ServerStore(srv), self.lease_name, identity=self.identity,
            lease_duration=lease_duration, clock=self._clock,
        )
        self._last_feed_ok = time.time()
        self._caught_up_at = time.time()
        self._last_leader_seq = 0  # newest leader seq seen on the feed
        #: newest global-seq watermark the leader stamped on the feed —
        #: on a procmesh shard follower this tracks the MESH line, which
        #: runs ahead of the shard-local seq (sibling shards consume it)
        self._leader_hwm = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- leader half: the shippable record log -----------------------------

    def log_append(self, rec: Dict[str, Any], ticket: int) -> None:
        """Track one just-WAL'd record (caller holds the server lock,
        right after ``wal.append`` returned ``ticket``).  The record is
        NOT yet shippable — ``on_commit`` advances the watermark once
        its shard's fsync covers the ticket."""
        effectsan.note_ship("Replicator.log_append")
        from volcano_tpu.store.partition import wal_shard

        nshards = getattr(self.srv.wal, "nshards", 1)
        shard = wal_shard(rec, nshards) if nshards > 1 else 0
        with self._mu:
            self._pending.append((int(rec["seq"]), rec, shard, ticket))
        self._tl.last_seq = int(rec["seq"])

    def log_beacon(self, seq: int, payload: Dict[str, Any],
                   ts: float) -> None:
        """Ship a digest beacon as a synthetic feed record (caller holds
        the server lock).  Beacons consume a seq but are never WAL'd;
        without this, follower seq lines would drift one behind per
        beacon and every block row after it would misalign."""
        effectsan.note_beacon("Replicator.log_beacon")
        rec = {"op": "beacon", "seq": int(seq), "rv": self.srv.store._rv,
               "digest": payload, "when": ts}
        with self._mu:
            self._pending.append((int(seq), rec, 0, None))
            self._advance_locked(None)

    def _synced_tickets(self) -> List[int]:
        wal = self.srv.wal
        if hasattr(wal, "synced_tickets"):
            return wal.synced_tickets()
        return [wal.synced_ticket()]

    def _advance_locked(self, synced: Optional[List[int]]) -> None:
        """Move the pending->shipped boundary (caller holds _mu).  The
        shippable set is the longest PREFIX whose records are fsynced —
        a later synced record never ships over an earlier unsynced one,
        so followers always see a prefix of the ack history."""
        moved = False
        while self._pending:
            seq, rec, shard, ticket = self._pending[0]
            if ticket is not None:
                if synced is None or synced[shard] < ticket:
                    break
            self._pending.popleft()
            self._shipped_seqs.append(seq)
            self._shipped.append(rec)
            self._ship_seq = seq
            moved = True
        overflow = len(self._shipped) - REPL_LOG_CAP
        if overflow > 0:
            self._base_seq = self._shipped_seqs[overflow - 1]
            del self._shipped_seqs[:overflow]
            del self._shipped[:overflow]
        if moved:
            self._cv.notify_all()

    def on_commit(self) -> None:
        """Called after every successful group-commit fsync: recompute
        the shipping watermark and wake feed long-polls."""
        synced = self._synced_tickets()
        with self._mu:
            self._advance_locked(synced)

    def sync_wait(self) -> None:
        """The ``--repl-ack sync`` barrier, called between the WAL fsync
        and the 2xx: block until ANY follower has acked (applied +
        appended to its own WAL) the newest record this thread appended.
        Stale acks can never satisfy a new record — acks are seqs and
        new records always carry higher ones."""
        if self.ack != "sync" or self.role != "leader":
            return
        target = getattr(self._tl, "last_seq", None)
        if target is None:
            return
        self._tl.last_seq = None
        deadline = time.monotonic() + self.ack_timeout
        with self._mu:
            while True:
                if any(s >= target for s in self.acks.values()):
                    return
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ReplicationAckTimeout(
                        f"no follower acked seq {target} within "
                        f"{self.ack_timeout}s (sync ack mode)")
                self._ack_cv.wait(remaining)

    def feed(self, from_seq: int, follower_id: str, timeout: float,
             req_epoch: Optional[int]) -> Optional[Dict[str, Any]]:
        """Serve one ``/repl/feed`` request.  Returns None when this
        replica is not the leader (the handler 421s with a redirect).
        An epoch mismatch — or a cursor below the retained horizon —
        serves a full snapshot; otherwise the synced record tail after
        ``from_seq``, long-polling up to ``timeout`` for new records."""
        now = time.time()
        if follower_id:
            with self._mu:
                prev = self.acks.get(follower_id, -1)
                if from_seq > prev:
                    self.acks[follower_id] = from_seq
                    self._ack_cv.notify_all()
                self._ack_time[follower_id] = now
        if self.role != "leader":
            return None
        if req_epoch is not None and req_epoch != self.epoch:
            return self._feed_snapshot()
        deadline = time.monotonic() + min(max(timeout, 0.0), FEED_POLL_MAX)
        while True:
            with self._mu:
                if from_seq < self._base_seq:
                    break  # fell off the retained log: snapshot below
                lo = bisect.bisect_right(self._shipped_seqs, from_seq)
                recs = self._shipped[lo:lo + FEED_BATCH]
                if recs:
                    self.shipped_total += len(recs)
                    out = {
                        "records": recs,
                        "next": self._shipped_seqs[lo + len(recs) - 1],
                    }
                    self._stamp_feed(out)
                    from volcano_tpu.scheduler import metrics

                    metrics.register_repl_shipped(len(recs))
                    return out
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    out = {"records": [], "next": from_seq}
                    self._stamp_feed(out)
                    return out
                self._cv.wait(remaining)
        return self._feed_snapshot()

    def _stamp_feed(self, out: Dict[str, Any]) -> None:
        out["seq"] = self.srv.seq
        out["epoch"] = self.epoch
        out["leader"] = self.leader_url
        out["uid"] = self.srv.store.uid
        # per-shard watermark message (store/procmesh): on a procmesh
        # shard leader the feed stream carries the mesh's global-seq
        # high-water mark alongside the local tail, so followers (and
        # anything reading /repl/status) can tell replication lag from
        # sibling-shard seq gaps.  Dense leaders stamp hwm == seq.
        out["hwm"] = self.srv._seq_hwm()

    def _feed_snapshot(self) -> Dict[str, Any]:
        snap = self.srv.snapshot_payload()
        with self._mu:  # counter is read by status() under the same lock
            self.snapshots_served += 1
        out = {"snapshot": snap, "next": snap["seq"]}
        self._stamp_feed(out)
        return out

    def writable(self) -> bool:
        return self.role == "leader"

    def status(self) -> Dict[str, Any]:
        """``/repl/status`` payload — the election protocol's peer probe
        and ``vtctl replica list``'s row source."""
        now = time.time()
        with self._mu:
            followers = {
                fid: {"acked": s,
                      "lag_rows": max(self._ship_seq - s, 0),
                      "age_s": round(now - self._ack_time.get(fid, now), 3)}
                for fid, s in self.acks.items()
            }
            ship = self._ship_seq
            pending = len(self._pending)
        return {
            "identity": self.identity, "role": self.role,
            "epoch": self.epoch, "applied": self.srv.seq,
            "leader": self.leader_url, "ack": self.ack,
            "ship_seq": ship, "unsynced": pending,
            "followers": followers, "divergence": self.divergence,
            "shipped_total": self.shipped_total,
            "promotions": self.promotions,
            "uid": self.srv.store.uid,
            "leader_hwm": self._leader_hwm,
        }

    # -- follower half: pump / replay / election ---------------------------

    def start(self) -> "Replicator":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._mu:
            self._cv.notify_all()
            self._ack_cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        bo = Backoff(base=0.05, cap=2.0)
        while not self._stop.is_set():
            try:
                if self.role == "leader":
                    self._leader_tick()
                    bo.reset()
                    self._stop.wait(self.lease_duration / 3.0)
                else:
                    if self._follower_tick():
                        bo.reset()
                    else:
                        # transient redirect / empty poll: jittered pause
                        # (never a fixed sleep — retry-backoff contract)
                        self._stop.wait(bo.next())
            except InjectedCrash:
                raise  # an armed crash must kill the pump, not retry it
            except ReplicationAckTimeout:
                # a leader whose followers are all down cannot renew
                # under sync ack; pace the retry, don't die
                effectsan.abandon("Replicator._run")
                self._stop.wait(bo.next())
            except _TRANSIENT:
                # leader unreachable / malformed reply: pace with the
                # decorrelated-jitter backoff, then let the election
                # check decide whether to keep following or promote
                effectsan.abandon("Replicator._run")
                if self.role != "leader":
                    self._maybe_elect()
                self._stop.wait(bo.next())

    def _leader_tick(self) -> None:
        """Renew the replicated lease; demote if a higher epoch exists
        (a partitioned ex-leader rejoining after a promotion)."""
        self._elector.try_acquire()
        lease = self._elector.store.get("Lease", f"/{self.lease_name}")
        if lease is not None and lease.holder != self.identity:
            # someone took the lease over: follow them
            self._demote(lease.holder)
            return
        for peer in self.peers:
            try:
                code, st = _http_json(peer + "/repl/status", timeout=1.0)
            except _TRANSIENT:
                continue
            if (code == 200 and st.get("role") == "leader"
                    and int(st.get("epoch", 0)) > self.epoch):
                self._demote(st.get("identity", peer))
                return

    def _demote(self, leader: str) -> None:
        with self.srv.lock:
            self.role = "follower"
            self.leader_url = (leader or self.leader_url).rstrip("/")
            self.srv.cond.notify_all()

    def _follower_tick(self) -> bool:
        """One feed round: long-poll the leader, replay the batch, ack
        by advancing ``from``.  Returns whether progress was made."""
        if self._should_elect() and self._maybe_elect():
            return True
        url = (f"{self.leader_url}/repl/feed?from={self.applied}"
               f"&id={urllib.request.quote(self.identity, safe='')}"
               f"&timeout=10&epoch={self.epoch}")
        code, body = _http_json(url, timeout=20.0)
        if code == 421:
            # mid-election redirect: follow the hint next round
            hint = body.get("leader")
            if hint and hint.rstrip("/") != self.leader_url:
                self.leader_url = hint.rstrip("/")
                return True
            return False
        if code != 200:
            raise OSError(f"feed http {code}: {body.get('error')}")
        self._last_feed_ok = time.time()
        if "snapshot" in body:
            self._apply_snapshot(body)
            return True
        records = body.get("records") or []
        for rec in records:
            crash_point("crash.replica.apply")
            apply_record(self.srv, self, rec)
        if records:
            # the ack barrier: the batch is in OUR wal before the next
            # feed's ``from`` advances past it (sync-ack leaders count
            # that cursor as the follower-append acknowledgment)
            self.srv.wal.commit()
            self.on_commit()
        self.applied = self.srv.seq
        resp_epoch = int(body.get("epoch", self.epoch))
        if resp_epoch != self.epoch:
            # leader changed epochs between our request and its reply;
            # next round's epoch mismatch fetches the snapshot
            self.epoch = resp_epoch
        self._observe_lag(int(body.get("seq", self.applied)))
        hwm = int(body.get("hwm", 0))
        if hwm > self._leader_hwm:
            self._leader_hwm = hwm
        return bool(records)

    def lag_seconds(self) -> float:
        """Seconds since this follower was last caught up with the
        leader's seq (0.0 while caught up) — the `vtctl top` panel's
        follower cell; the gauge twin lives in _observe_lag."""
        if self.role == "leader":
            return 0.0
        return max(time.time() - self._caught_up_at, 0.0) \
            if self.applied < self._last_leader_seq else 0.0

    def _observe_lag(self, leader_seq: int) -> None:
        now = time.time()
        self._last_leader_seq = leader_seq
        if self.applied >= leader_seq:
            self._caught_up_at = now
            lag = 0.0
        else:
            lag = now - self._caught_up_at
        from volcano_tpu.scheduler import metrics

        metrics.update_repl_lag(lag)
        metrics.update_repl_applied_seq(self.applied)

    def _apply_snapshot(self, body: Dict[str, Any]) -> None:
        """Full resync: replace the local store with the leader's
        snapshot (epoch fence crossed, or we fell off the feed log).
        Local watchers relist once — the served epoch changes with the
        state, the same cursor-gap semantics as failover."""
        snap = body["snapshot"]
        srv = self.srv
        srv.reset_from_snapshot(snap)
        with srv.lock:
            self.epoch = int(body.get("epoch", self.epoch))
            with self._mu:
                self._pending.clear()
                del self._shipped[:]
                del self._shipped_seqs[:]
                self._base_seq = srv.seq
                self._ship_seq = srv.seq
            srv.cond.notify_all()
        self.applied = srv.seq
        # floored checkpoint: the snapshot is the new recovery basis —
        # stale WAL segments from the previous epoch must not replay
        # over it on restart
        srv.flush_state(force=True)
        self._observe_lag(int(body.get("seq", self.applied)))

    # -- election / promotion ---------------------------------------------

    def _should_elect(self) -> bool:
        with self.srv.lock:
            lease = self.srv.store.get("Lease", f"/{self.lease_name}")
        now = self._clock()
        if lease is not None:
            return now - lease.renewed_at > lease.duration
        # no lease replicated yet (fresh cluster): only feed silence
        # longer than a lease window counts as leader loss
        return now - self._last_feed_ok > self.lease_duration

    def _maybe_elect(self) -> bool:
        """Run one election round.  Promotion rule: among REACHABLE
        candidates (peer /repl/status probes + self), the max
        ``(applied_seq, identity)`` promotes — a strict total order, so
        two mutually-reachable candidates can never both pass; the CAS
        takeover on the replicated lease breaks any remaining race."""
        if not self._should_elect():
            return False
        statuses = []
        for peer in self.peers:
            try:
                code, st = _http_json(peer + "/repl/status", timeout=1.0)
            except _TRANSIENT:
                continue
            if code == 200:
                statuses.append(st)
        live = [st for st in statuses
                if st.get("role") == "leader"
                and int(st.get("epoch", 0)) >= self.epoch]
        if live:
            # a live leader exists: adopt it and let the caller proceed
            # to the feed — returning "promoted" here would skip the
            # fetch, and our local lease copy only freshens THROUGH the
            # feed (the election check would livelock on a stale lease)
            best = max(live, key=lambda st: int(st.get("epoch", 0)))
            self.leader_url = str(best.get("identity",
                                           self.leader_url)).rstrip("/")
            return False
        cands = [(int(st.get("applied", -1)), str(st.get("identity", "")))
                 for st in statuses]
        cands.append((self.applied, self.identity))
        if max(cands) != (self.applied, self.identity):
            return False  # a better candidate is live; it will promote
        seen_epochs = [int(st.get("epoch", 0)) for st in statuses]
        return self._promote(seen_epochs)

    def _promote(self, seen_epochs: List[int]) -> bool:
        """Take the lease over via the stock elector (CAS on our local
        replicated copy), bump the epoch, stamp a floored checkpoint.
        Watchers of this replica see the epoch change on their next
        poll and relist once (StaleWatch); followers of the dead leader
        find us through /repl/status and snapshot-resync."""
        if not self._elector.try_acquire():
            return False
        srv = self.srv
        with srv.lock:
            self.role = "leader"
            self.epoch = max([self.epoch] + seen_epochs) + 1
            self.leader_url = self.identity
            with self._mu:
                self._base_seq = min(self._base_seq, srv.seq)
            self.promotions += 1
            srv.cond.notify_all()
        # the floored checkpoint: promotion is a durability epoch — the
        # snapshot + rotate pins everything applied so far
        srv.flush_state(force=True)
        from volcano_tpu.scheduler import metrics

        metrics.update_repl_applied_seq(self.applied)
        return True


# -- follower replay (the live-path mirror) --------------------------------


def _apply_object_record(store, kind: str, op: str, obj) -> None:
    """Converge the store on one shipped create/update, crossed-lineage
    fallback included (a snapshot already holding a later life of the
    key replays the record's object either way)."""
    try:
        if op == "create":
            store.create(kind, obj)
        else:
            store.update(kind, obj)
    except KeyError:
        if op == "create":
            store.update(kind, obj)
        else:
            store.create(kind, obj)


def apply_record(srv, repl: Replicator, rec: Dict[str, Any]) -> None:
    """Replay one shipped record through the LIVE verb paths — unlike
    crash recovery's ``_replay_record``, this produces watch events, so
    follower-served watch streams are byte-identical to the leader's:
    the staged encoding hint is the leader's own restamped wire dict,
    segments reuse the recorded stamp, and rv/seq stamps restore the
    exact continuity line after every record."""
    op = rec.get("op")
    if op == "segment":
        # the segment path manages its own shard+server locking and
        # appends the record (with its leader stamps re-derived — the
        # follower's seq/rv line is aligned record-by-record) to our WAL
        srv._apply_segment(rec, stamp=rec.get("stamp"))
        _align(srv, rec)
        return
    if op == "beacon":
        _apply_beacon(srv, repl, rec)
        return
    kind = rec.get("kind", "")
    store = srv.store
    with srv.lock:
        if op in ("create", "update"):
            enc = rec["object"]
            obj = decode_object(kind, enc)
            rv = obj.meta.resource_version
            tid = "" if trace.TRACER is None else trace.gang_trace(obj.meta)
            if tid:
                # the replica leg of a gang's fleet timeline: join the
                # object's own trace so `vtctl trace last --fleet` shows
                # leader append -> follower apply in order (untraced
                # records open no span — the feed must not churn the
                # ring out from under the gang spans)
                with trace.span("replica.apply", trace_id=tid, op=op,
                                kind=kind, key=obj.meta.key):
                    _apply_object_record(store, kind, op, obj)
            else:
                _apply_object_record(store, kind, op, obj)
            obj.meta.resource_version = rv
            shadow = store._shadow[kind].get(obj.meta.key)
            if shadow is not None:
                shadow.meta.resource_version = rv
            srv._enc_hints[(kind, obj.meta.key)] = enc
        elif op == "patch":
            when = rec.get("when")
            try:
                store.patch(
                    kind, rec["key"],
                    decode_fields(kind, rec.get("fields") or {}),
                    when=decode_fields(kind, when) if when else None,
                )
            except (KeyError, PreconditionFailed):
                pass  # replays exactly as it resolved on the leader
        elif op == "patch_col":
            cols = rec.get("columns") or {}
            const_enc = rec.get("const") or {}
            when = rec.get("when")
            const = decode_fields(kind, const_enc) if const_enc else {}
            when_dec = decode_fields(kind, when) if when else None
            col_dec = srv._col_decoders(kind, cols)
            for i, key in enumerate(rec.get("keys") or []):
                fields = dict(const)
                for f, vals in cols.items():
                    fields[f] = col_dec[f](vals[i])
                try:
                    store.patch(kind, key, fields, when=when_dec)
                except (KeyError, PreconditionFailed):
                    pass
        elif op == "delete":
            store.delete(kind, rec.get("key", ""))
        else:
            return  # unknown op from a newer leader: skip, stay aligned
        if srv.wal is not None:
            effectsan.note_mutate("replica.apply_record")
        srv._pump_log()
        if srv.wal is not None:
            srv._wal_append(dict(rec))
        _align(srv, rec)
        srv.cond.notify_all()


def _align(srv, rec: Dict[str, Any]) -> None:
    """Pin the follower to the record's seq/rv stamps.  In the healthy
    case these are no-ops (the live replay advanced both identically);
    after a skipped/odd record they re-anchor the continuity line so
    the next record still applies at the right position."""
    if "seq" in rec:
        srv.seq = max(srv.seq, int(rec["seq"]))
    if "rv" in rec:
        srv.store._rv = max(srv.store._rv, int(rec["rv"]))


def _apply_beacon(srv, repl: Replicator, rec: Dict[str, Any]) -> None:
    """Mirror a leader digest beacon: consume the same seq, stamp OUR
    OWN digest at it (byte-identical to the leader's entry exactly when
    the states agree), and count a divergence when the roots differ —
    the replication integrity check riding the vtaudit beacon lane."""
    with srv.lock:
        srv._pump_log()
        seq = int(rec["seq"])
        if seq <= srv.seq:
            return  # replayed duplicate (reconnect overlap): drop
        own = srv.store.digest_payload(srv.shards)
        leader_payload = rec.get("digest") or {}
        payload = own if own is not None else leader_payload
        if own is not None and leader_payload:
            if own.get("root") != leader_payload.get("root"):
                repl.divergence += 1
                from volcano_tpu.scheduler import metrics

                metrics.register_audit_divergence()
        srv.seq = seq
        srv._log_rows += 1
        srv.log.append(vtaudit.beacon_entry(seq, payload,
                                            float(rec.get("when", 0.0))))
        srv._beacon_seq = srv.seq
        srv._beacon_mono = time.monotonic()
        srv._trim_log()
        if "rv" in rec:
            srv.store._rv = max(srv.store._rv, int(rec["rv"]))
        srv.cond.notify_all()
