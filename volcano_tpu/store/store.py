"""In-memory watchable object store — the "API server" bus of the framework.

The reference's components never talk to each other directly; they watch and
write CRDs through the Kubernetes API server (SURVEY.md section 1). This
store plays that role for the TPU framework: typed buckets keyed by
namespace/name, monotonically increasing resource versions, and watch
subscriptions that deliver add/update/delete events.

Unlike informers+goroutines, delivery is deterministic: events queue up and
subscribers drain them when pumped (tests and the simulator control the
interleaving explicitly; `Cluster.run_until_idle` is the scheduler's
equivalent of "wait for informer sync").
"""

from __future__ import annotations

import copy
import enum
from collections import OrderedDict, defaultdict, deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, Iterator, List, Optional

#: sentinel for "attribute absent" in patch's no-op field comparison
_MISSING = object()


class EventType(str, enum.Enum):
    ADDED = "Added"
    UPDATED = "Updated"
    DELETED = "Deleted"


class Conflict(Exception):
    """Optimistic-concurrency failure: the object changed since it was read
    (the API server's 409 on a stale resourceVersion)."""


class PreconditionFailed(Exception):
    """A patch's ``when`` clause did not match the stored object — the
    write was skipped entirely (the conditional-patch analogue of a CAS
    miss; callers that race benignly treat it as a no-op)."""


def _walk(obj: Any, dotted: str):
    """(parent, leaf_name) for a dotted attribute path; raises
    AttributeError on any missing hop."""
    parts = dotted.split(".")
    cur = obj
    for p in parts[:-1]:
        if not hasattr(cur, p):
            raise AttributeError(f"no field {p!r} on path {dotted!r}")
        cur = getattr(cur, p)
    if not hasattr(cur, parts[-1]):
        raise AttributeError(f"no field {parts[-1]!r} on path {dotted!r}")
    return cur, parts[-1]


@dataclass
class Event:
    kind: str
    type: EventType
    obj: Any
    old: Any = None
    #: for COW patch events: the (possibly dotted) field map that was
    #: applied — lets the store server maintain its encoded-object cache
    #: by delta instead of re-encoding the full object per bind/patch
    fields: Any = None
    #: remote transport only (RemoteStore.poll): the wire encoding of the
    #: post-state, attached for free from the watch entry — the mirror's
    #: digest auditor hashes it without re-encoding the decoded object
    enc: Any = None


class Store:
    """Typed object buckets + watch queues.

    Kinds used by the framework: "Job", "Pod", "PodGroup", "Queue", "Node",
    "Command", "ConfigMap", "Service", "PriorityClass", "PVC".
    """

    def __init__(self):
        import uuid

        from volcano_tpu.locksan import make_rlock

        #: lineage identity: survives pickling (vtctl state) and the store
        #: server's durable state file, so a mirror checkpoint can tell
        #: "same store restarted" from "different store with coincidentally
        #: aligned resource-version counters"
        self.uid = uuid.uuid4().hex
        self._objects: Dict[str, Dict[str, Any]] = defaultdict(dict)
        # deep-copied last-notified state per object, so Event.old reflects
        # the pre-update object even though callers mutate in place (the
        # informer local-cache pattern); populated only for watched kinds.
        self._shadow: Dict[str, Dict[str, Any]] = defaultdict(dict)
        self._watchers: Dict[str, List[Deque[Event]]] = defaultdict(list)
        # lazy columnar overlay (apply_segment_lazy): per-kind field
        # patches and object creates already ACKed but not yet applied to
        # the live objects — key -> (fields dict, rv) / (block, row).
        # Every read/write verb materializes the touched keys first, so
        # per-object work is paid on first read, not at segment apply.
        self._lazy_patch: Dict[str, Dict[str, Any]] = defaultdict(dict)
        self._lazy_create: Dict[str, Dict[str, Any]] = defaultdict(dict)
        self._rv = 0
        # procmesh (store/procmesh): when this store is one shard of a
        # multi-process mesh, resource versions come from a shared
        # cross-process allocator so every shard draws from ONE rv line.
        # Values gap locally but stay globally unique and per-object
        # monotone (each object lives on exactly one shard), so CAS and
        # epoch-cache semantics are unchanged.  None = local dense
        # counter, byte-for-byte the historical behavior.
        self._rv_alloc = None
        # (ev_token, ev_start) of recently applied decision segments: the
        # reserved-uid block identifies a segment, so a RESUBMIT (the
        # applier re-ships the same segment after a cut reply / a crash
        # whose WAL record survived) is recognized and its Event rows
        # dedupe against what already landed — resubmission is idempotent
        # (bind/evict rows are idempotent already via no-op suppression)
        self._applied_segments: OrderedDict = OrderedDict()
        # incremental state digest (volcano_tpu/vtaudit.py): per-object
        # 64-bit digests + (kind, namespace) bucket sums, maintained by
        # every mutating verb below under _mu — the store half of the
        # mirror/WAL divergence auditor.  None when auditing is disarmed.
        from volcano_tpu import vtaudit

        self._digest = vtaudit.DigestTable() if vtaudit.enabled() else None
        # mutation lock: the async applier writes from its own thread while
        # the owning thread reads/writes (StoreServer adds its own RLock on
        # top for multi-client HTTP, which nests fine: server.lock is
        # always taken before _mu, never the reverse — the store never
        # calls back into the server)
        self._mu = make_rlock("Store._mu")

    def __getstate__(self):
        # the mutation lock is process-local (vtctl pickles the simulated
        # cluster's store for persisted state); lazily pending segment
        # rows materialize first so the pickle is plain objects
        self.materialize_all()
        state = self.__dict__.copy()
        del state["_mu"]
        # the rv allocator is a handle into another process's shared
        # counter — never meaningful in a pickle
        state["_rv_alloc"] = None
        return state

    def __setstate__(self, state):
        from volcano_tpu.locksan import make_rlock

        self.__dict__.update(state)
        # state pickled before the columnar wire lacks the (always-empty-
        # at-pickle) lazy overlays
        self.__dict__.setdefault("_lazy_patch", defaultdict(dict))
        self.__dict__.setdefault("_lazy_create", defaultdict(dict))
        self.__dict__.setdefault("_applied_segments", OrderedDict())
        self.__dict__.setdefault("_rv_alloc", None)
        from volcano_tpu import vtaudit

        if not vtaudit.enabled():
            self.__dict__["_digest"] = None
        elif self.__dict__.get("_digest") is None:
            # state pickled before the auditor (or by a disarmed life):
            # rebuild the digest from the objects themselves
            self.__dict__["_digest"] = vtaudit.table_from_objects(
                (kind, obj)
                for kind, bucket in self._objects.items()
                for obj in bucket.values()
            )
        self._mu = make_rlock("Store._mu")

    def _watched(self, kind: str) -> bool:
        return bool(self._watchers[kind])

    @property
    def resource_version(self) -> int:
        """Monotonic global version; bumps on every create/update."""
        return self._rv

    def _advance_rv(self, n: int = 1) -> int:
        """Consume ``n`` resource versions and return the LAST one (the
        caller derives its block as ``last - n + 1 .. last``).  With a
        procmesh allocator armed the block comes from the mesh's shared
        rv line; otherwise the local dense counter — identical values,
        identical object stamps."""
        alloc = self._rv_alloc
        if alloc is not None:
            self._rv = int(alloc(n))
        else:
            self._rv += n
        return self._rv

    # -- lazy segment overlay -------------------------------------------------

    def _materialize(self, kind: str, key: str) -> None:
        """Fold any pending segment rows for ``key`` into the live object
        (and its no-op-suppression shadow) — called by every verb that
        reads or writes the key.  Must run under ``_mu``."""
        lp = self._lazy_patch.get(kind)
        if lp:
            entry = lp.pop(key, None)
            if entry is not None:
                fields, rv = entry
                obj = self._objects[kind][key]
                for name, v in fields.items():
                    setattr(obj, name, v)
                obj.meta.resource_version = rv
                shadow = self._shadow[kind].get(key)
                if shadow is not None:
                    from volcano_tpu.api.fastclone import deep_clone

                    new_shadow = copy.copy(shadow)
                    new_shadow.meta = copy.copy(shadow.meta)
                    new_shadow.meta.resource_version = rv
                    for name, v in fields.items():
                        setattr(new_shadow, name, deep_clone(v))
                    self._shadow[kind][key] = new_shadow
        lc = self._lazy_create.get(kind)
        if lc:
            entry = lc.pop(key, None)
            if entry is not None:
                block, i = entry
                self._objects[kind][key] = block.materialize(i)

    def _materialize_kind(self, kind: str) -> None:
        lp = self._lazy_patch.get(kind)
        lc = self._lazy_create.get(kind)
        if not lp and not lc:
            return
        for key in list(lp or ()):
            self._materialize(kind, key)
        for key in list(lc or ()):
            self._materialize(kind, key)

    def materialize_all(self) -> None:
        """Materialize every lazily pending segment row (pickling, state
        flushes)."""
        with self._mu:
            for kind in list(self._lazy_patch) + list(self._lazy_create):
                self._materialize_kind(kind)

    # -- CRUD ---------------------------------------------------------------

    def create(self, kind: str, obj: Any) -> Any:
        with self._mu:
            key = obj.meta.key
            lc = self._lazy_create.get(kind)
            if key in self._objects[kind] or (lc and key in lc):
                raise KeyError(f"{kind} {key} already exists")
            obj.meta.resource_version = self._advance_rv()
            if not obj.meta.creation_timestamp:
                import time

                obj.meta.creation_timestamp = time.time()
            self._objects[kind][key] = obj
            dg = self._digest
            if dg is not None:
                dg.set_obj(kind, key, obj)
            self._notify(Event(kind, EventType.ADDED, obj))
            return obj

    def update(self, kind: str, obj: Any) -> Any:
        with self._mu:
            key = obj.meta.key
            self._materialize(kind, key)
            if key not in self._objects[kind]:
                raise KeyError(f"{kind} {key} not found")
            old = self._shadow[kind].get(key)
            # no-op writes don't bump the version or fan out events — callers
            # (scheduler close_session, controller status writers) write
            # unconditionally each cycle and rely on this for quiescence
            if old is not None and old == obj:
                return obj
            obj.meta.resource_version = self._advance_rv()
            self._objects[kind][key] = obj
            dg = self._digest
            if dg is not None:
                dg.set_obj(kind, key, obj)
            self._notify(Event(kind, EventType.UPDATED, obj, old))
            return obj

    def update_cas(self, kind: str, obj: Any, expected_rv: int) -> Any:
        """Compare-and-swap update: succeeds only if the stored object's
        resource_version still equals ``expected_rv`` (read-modify-write
        safety for concurrent writers, e.g. leader leases and kubelets)."""
        with self._mu:
            self._materialize(kind, obj.meta.key)
            current = self._objects[kind].get(obj.meta.key)
            if current is None:
                raise KeyError(f"{kind} {obj.meta.key} not found")
            if current.meta.resource_version != expected_rv:
                raise Conflict(
                    f"{kind} {obj.meta.key}: expected rv {expected_rv}, "
                    f"have {current.meta.resource_version}"
                )
            return self.update(kind, obj)

    def patch(self, kind: str, key: str, fields: Dict[str, Any],
              when: Optional[Dict[str, Any]] = None) -> Any:
        """Apply field updates to the stored object in place (the API
        server's PATCH; Bind is a node_name patch). Attribute names must
        already exist on the object — typos fail loudly.  Names may be
        dotted paths ('status.phase': set one nested field, preserve its
        siblings).  ``when`` is an optional precondition map of dotted
        paths to expected values; any mismatch raises PreconditionFailed
        and nothing is written (the conditional read-modify-write the
        fast cycle's bulk enqueue shipping needs in ONE round trip).

        Hot path for the async applier's bind batches: when a shadow
        exists, only the patched fields are cloned into a copy-on-write
        shadow instead of re-cloning the whole object per write — the
        full-object deep_clone was 75% of drain time at 100k binds/cycle.
        """
        with self._mu:
            self._materialize(kind, key)
            obj = self._objects[kind].get(key)
            if obj is None:
                raise KeyError(f"{kind} {key} not found")
            if when:
                for k, expect in when.items():
                    parent, leaf = _walk(obj, k)
                    got = getattr(parent, leaf)
                    if got != expect:
                        raise PreconditionFailed(
                            f"{kind} {key}: {k} is {got!r}, wanted {expect!r}"
                        )
            # ONE copy-on-write implementation for flat and dotted fields —
            # a flat name is a one-segment path.  Validate every path
            # BEFORE mutating: a bad field must not leave earlier fields
            # silently applied with no event/version.
            paths = {k: k.split(".") for k in fields}
            for k in fields:
                _walk(obj, k)
            shadow = self._shadow[kind].get(key)
            if shadow is None or any(p[0] == "meta" for p in paths.values()):
                for k, v in fields.items():
                    parent, leaf = _walk(obj, k)
                    setattr(parent, leaf, v)
                return self.update(kind, obj)

            def _leaf(root, parts):
                for p in parts[:-1]:
                    root = getattr(root, p)
                return getattr(root, parts[-1], _MISSING)

            if all(
                _leaf(obj, paths[k]) == v and _leaf(shadow, paths[k]) == v
                for k, v in fields.items()
            ):
                return obj  # no-op: quiescence contract (see update())
            from volcano_tpu.api.fastclone import deep_clone

            dg = self._digest
            trips = [] if dg is not None else None
            for k, v in fields.items():
                parent, leaf = _walk(obj, k)
                if trips is not None:
                    # pre-setattr value: the digest delta's old leaf
                    trips.append((k, getattr(parent, leaf), v))
                setattr(parent, leaf, v)
            obj.meta.resource_version = self._advance_rv()
            if trips is not None:
                dg.apply_fields(kind, key, trips, obj=obj)
            # copy-on-write shadow: path hops are shallow-copied, so
            # unpatched fields/siblings share the old shadow's
            # (immutable-by-contract) values; the queued Event keeps the
            # old shadow object untouched as its pre-update view.  Full
            # update() here (a deep_clone + recursive __eq__ per write)
            # measured 75% of drain time at 100k binds/cycle and ~0.2 s of
            # the timed cycle for a 5k-group bulk enqueue shipping.
            new_shadow = copy.copy(shadow)
            new_shadow.meta = copy.copy(shadow.meta)
            new_shadow.meta.resource_version = self._rv
            for k, v in fields.items():
                parts = paths[k]
                cur = new_shadow
                for p in parts[:-1]:
                    child = copy.copy(getattr(cur, p))
                    setattr(cur, p, child)
                    cur = child
                setattr(cur, parts[-1], deep_clone(v))
            ev = Event(kind, EventType.UPDATED, obj, shadow, fields=fields)
            for q in self._watchers[kind]:
                q.append(ev)
            self._shadow[kind][key] = new_shadow
            return obj

    def bulk(self, ops: List[Dict[str, Any]]) -> List[Optional[str]]:
        """Apply N mutations in one call — the store-side half of batched
        side-effect application (one round trip for a cycle's binds over
        RemoteStore). Each op is a dict:

          {"op": "create"|"update", "kind": K, "object": obj}
          {"op": "patch",  "kind": K, "key": key, "fields": {...}}
          {"op": "delete", "kind": K, "key": key}

        Ops apply independently in order (no transaction — semantically N
        API calls); the result is one error string (or None) per op.
        """
        results: List[Optional[str]] = []
        for op in ops:
            try:
                verb = op["op"]
                kind = op["kind"]
                if verb == "create":
                    self.create(kind, op["object"])
                elif verb == "update":
                    self.update(kind, op["object"])
                elif verb == "patch":
                    self.patch(kind, op["key"], op["fields"],
                               when=op.get("when"))
                elif verb == "delete":
                    self.delete(kind, op["key"])
                else:
                    raise ValueError(f"unknown bulk op {verb!r}")
                results.append(None)
            except KeyError as e:
                # structured marker: callers that treat a vanished object
                # as success (evict of an already-deleted pod) match this
                # prefix instead of reverse-engineering exception reprs
                results.append(f"NotFound: {e}")
            except Exception as e:  # noqa: BLE001 — per-op isolation
                results.append(repr(e))
        return results

    # -- columnar segments ---------------------------------------------------

    #: recently-applied-segment memory (resubmit dedupe); far above the
    #: retry window's needs, far below anything that matters for memory
    SEGMENT_DEDUP_CAP = 1024

    def _note_segment(self, seg) -> bool:
        """Record ``seg``'s reserved-uid block as applied; returns whether
        this is a RESUBMIT (the block was seen before).  Must run under
        ``_mu``."""
        key = (seg.ev_token, seg.ev_start)
        resubmit = key in self._applied_segments
        self._applied_segments[key] = True
        self._applied_segments.move_to_end(key)
        while len(self._applied_segments) > self.SEGMENT_DEDUP_CAP:
            self._applied_segments.popitem(last=False)
        return resubmit

    def apply_segment(self, seg) -> Dict[str, Any]:
        """Eagerly apply one decision segment (store/segment.py): bind
        patches, evict patches, then one Scheduled/Evict Event per
        successful row — the same store mutations (and watch events) the
        per-object bulk path produced, minus the per-op dict plumbing.
        This is the IN-PROCESS transport: direct watchers (the scheduler's
        mirror, controllers) keep seeing ordinary per-object events.  The
        server's lazy transport is ``apply_segment_lazy``.  Returns
        ``{"binds": [[row, err], ...], "evicts": [...], "timings": {...}}``
        with sparse per-row errors, mirroring the bulk verb's isolation.
        """
        import time as _time

        from volcano_tpu.store import segment as segmod

        hosts = seg.bind_hosts
        reasons = seg.evict_reason_strs
        errs_b: List[List[Any]] = []
        errs_e: List[List[Any]] = []
        ev_rows: List[tuple] = []  # (uid slot, involved key, reason, message, type)
        with self._mu:
            resubmit = self._note_segment(seg)
        # per-row locking, like Store.bulk: concurrent readers interleave
        # between rows exactly as they did with the per-op path
        t0 = _time.perf_counter()
        for i, key in enumerate(seg.bind_keys):
            try:
                self.patch("Pod", key, {"node_name": hosts[i]})
            except KeyError as e:
                errs_b.append([i, f"NotFound: {e}"])
                continue
            except Exception as e:  # noqa: BLE001 — per-row isolation
                errs_b.append([i, repr(e)])
                continue
            ev_rows.append((seg.ev_start + i, key, segmod.BIND_REASON,
                            segmod.scheduled_message(key, hosts[i]),
                            segmod.NORMAL))
        t1 = _time.perf_counter()
        n_b = len(seg.bind_keys)
        for j, key in enumerate(seg.evict_keys):
            try:
                self.patch("Pod", key, {"deleting": True})
            except KeyError as e:
                errs_e.append([j, f"NotFound: {e}"])
                continue
            except Exception as e:  # noqa: BLE001
                errs_e.append([j, repr(e)])
                continue
            ev_rows.append((seg.ev_start + n_b + j, key,
                            segmod.EVICT_REASON,
                            segmod.evicted_message(reasons[j]),
                            segmod.WARNING))
        t2 = _time.perf_counter()
        events = self._objects["Event"]
        for slot, key, reason, message, type_ in ev_rows:
            name = segmod.event_name(seg.ev_token, slot)
            if resubmit and f"/{name}" in events:
                continue  # idempotent resubmit: this row already landed
            ev = segmod.materialize_event(
                name, key, reason, message, type_, rv=0, stamp=0.0,
            )
            ev.meta.creation_timestamp = 0.0  # create() stamps it
            try:
                self.create("Event", ev)
            except KeyError:
                # uid-block collision outside the dedupe window (e.g. a
                # pickle-restored store): the row already exists — skip,
                # same outcome as the resubmit check above
                continue
        t3 = _time.perf_counter()
        return {
            "binds": errs_b, "evicts": errs_e,
            "timings": {"binds_s": t1 - t0, "evicts_s": t2 - t1,
                        "events_s": t3 - t2},
        }

    def _stage_lazy_rows(self, keys: List[str], field: str,
                         values: Optional[List[Any]]):
        """Stage one segment section's scalar patches into the lazy
        overlay: per-row existence + pending-aware no-op check, a
        contiguous rv block for the changed rows, last-wins merge into
        any pending entry.  ``values`` is the per-row column, or None for
        the constant ``True`` (evict rows).  Returns
        ``(sparse errs, changed row idxs, event row idxs, rv0)``.
        Must run under ``_mu``."""
        pods = self._objects["Pod"]
        pend = self._lazy_patch["Pod"]
        errs: List[List[Any]] = []
        changed: List[int] = []
        old_vals: List[Any] = []  # pending-aware pre-values, parallel to changed
        ev_rows: List[int] = []
        for i, key in enumerate(keys):
            obj = pods.get(key)
            if obj is None:
                errs.append([i, "NotFound: " + repr(f"Pod {key} not found")])
                continue
            p = pend.get(key)
            cur = p[0].get(field, _MISSING) if p else _MISSING
            if cur is _MISSING:
                cur = getattr(obj, field)
            ev_rows.append(i)
            if cur == (True if values is None else values[i]):
                continue  # no-op write: Event only, no patch row
            changed.append(i)
            old_vals.append(cur)
        rv0 = self._advance_rv(len(changed)) - len(changed) + 1
        dg = self._digest
        for j, i in enumerate(changed):
            key = keys[i]
            value = True if values is None else values[i]
            if dg is not None:
                # staged rows digest NOW (one scalar-leaf delta each):
                # _materialize later folds exactly these values, so
                # materialization itself is digest-neutral
                dg.apply_fields("Pod", key, ((field, old_vals[j], value),))
            p = pend.get(key)
            if p is None:
                pend[key] = ({field: value}, rv0 + j)
            else:
                f = dict(p[0])
                f[field] = value
                pend[key] = (f, rv0 + j)
        return errs, changed, ev_rows, rv0

    def apply_segment_lazy(self, seg, stamp: Optional[float] = None
                           ) -> Dict[str, Any]:
        """The server-side half of the columnar wire: ACK a whole decision
        segment under ONE lock acquisition without touching a single live
        object.  Bind/evict rows stage into the lazy-patch overlay
        (resource versions assigned now, fields folded in on first read by
        ``_materialize``); Event rows stage as columnar
        ``EventLogBlock`` references that never become ClusterEvent
        objects unless an Event read asks (``_materialize``/``list``).
        No watcher events fan out — the StoreServer appends the blocks to
        its own log directly (columnar watch cache).  Returns the sparse
        per-row errors plus the block descriptions the server logs:

          bind_block:  (keys, hostnames, rv0) for rows that CHANGED state
          evict_block: (keys, rv0)
          event_blocks: (bind EventLogBlock, evict EventLogBlock)

        Rows whose write is a no-op (already bound to that node / already
        deleting) produce an Event but no patch row — exactly the per-
        object path's patch-quiescence + event behavior.

        ``stamp`` pins the Event creation timestamp (WAL replay passes the
        original apply time so a recovered store matches the live one);
        None = now.  A RESUBMIT of an already-applied segment — same
        reserved-uid block — is idempotent: its Event rows dedupe against
        the rows that already landed, so a cut reply or a crash-restart
        retry can never double-publish a cycle's Events.
        """
        import time as _time

        from volcano_tpu.store import segment as segmod

        with self._mu:
            t0 = _time.perf_counter()
            if stamp is None:
                stamp = _time.time()
            resubmit = self._note_segment(seg)
            hosts = seg.bind_hosts
            errs_b, changed_b, ev_b, rv_b0 = self._stage_lazy_rows(
                seg.bind_keys, "node_name", hosts
            )
            t1 = _time.perf_counter()
            errs_e, changed_e, ev_e, rv_e0 = self._stage_lazy_rows(
                seg.evict_keys, "deleting", None
            )
            t2 = _time.perf_counter()
            if resubmit:
                # drop Event rows the first submission already staged or
                # materialized (slot -> Metadata.key via the uid block);
                # rare path, never on the first-ship hot drain
                lc0 = self._lazy_create.get("Event") or {}
                events = self._objects["Event"]
                nb = len(seg.bind_keys)

                def _fresh(slot: int) -> bool:
                    k = f"/{segmod.event_name(seg.ev_token, slot)}"
                    return k not in lc0 and k not in events

                ev_b = [i for i in ev_b if _fresh(seg.ev_start + i)]
                ev_e = [j for j in ev_e if _fresh(seg.ev_start + nb + j)]

            # Event rows: rv block after every patch, the bulk-then-bulk
            # order of the per-object path
            n_ev = len(ev_b) + len(ev_e)
            rv_ev0 = self._advance_rv(n_ev) - n_ev + 1
            n_b = len(seg.bind_keys)
            ebind = segmod.EventLogBlock(
                segmod.BIND_REASON, seg.ev_token,
                [seg.ev_start + i for i in ev_b],
                [seg.bind_keys[i] for i in ev_b],
                [hosts[i] for i in ev_b],
                rv_ev0, stamp,
            )
            reasons = seg.evict_reason_strs
            eevict = segmod.EventLogBlock(
                segmod.EVICT_REASON, seg.ev_token,
                [seg.ev_start + n_b + j for j in ev_e],
                [seg.evict_keys[j] for j in ev_e],
                [reasons[j] for j in ev_e],
                rv_ev0 + len(ev_b), stamp,
            )
            lc = self._lazy_create["Event"]
            for blk in (ebind, eevict):
                for r in range(len(blk)):
                    lc[blk.key(r)] = (blk, r)
            t3 = _time.perf_counter()
            return {
                "binds": errs_b, "evicts": errs_e,
                "bind_block": (
                    [seg.bind_keys[i] for i in changed_b],
                    [hosts[i] for i in changed_b], rv_b0,
                ),
                "evict_block": (
                    [seg.evict_keys[j] for j in changed_e], rv_e0,
                ),
                "event_blocks": (ebind, eevict),
                "timings": {"binds_s": t1 - t0, "evicts_s": t2 - t1,
                            "events_s": t3 - t2},
            }

    def delete(self, kind: str, key: str) -> Optional[Any]:
        with self._mu:
            self._materialize(kind, key)
            obj = self._objects[kind].pop(key, None)
            if obj is not None:
                dg = self._digest
                if dg is not None:
                    dg.remove(kind, key)
                self._notify(Event(kind, EventType.DELETED, obj))  # drops the shadow too
            return obj

    def get(self, kind: str, key: str) -> Optional[Any]:
        lp = self._lazy_patch.get(kind)
        lc = self._lazy_create.get(kind)
        if (lp and key in lp) or (lc and key in lc):
            with self._mu:
                self._materialize(kind, key)
        return self._objects[kind].get(key)

    def list(self, kind: str) -> List[Any]:
        with self._mu:
            # lazily created objects (segment Events) materialize only
            # here — the "never exist unless listed" half of the lazy-
            # apply contract
            self._materialize_kind(kind)
            return list(self._objects[kind].values())

    def items(self, kind: str) -> Iterator[Any]:
        return iter(self.list(kind))

    # -- state digest (vtaudit) ---------------------------------------------

    def digest_payload(self, nshards: int = 1) -> Optional[Dict[str, Any]]:
        """Maintained digest rollup (root/shards/kinds, hex) — the store
        half of every /healthz, /debug/digest, and beacon surface.  None
        when auditing is disarmed."""
        with self._mu:
            dg = self._digest
            return None if dg is None else dg.payload(nshards)

    def digest_buckets(self, shard: Optional[int] = None,
                       nshards: int = 1) -> Dict[str, str]:
        """Maintained per-(kind, namespace) bucket digests — the
        localization walk's middle tier."""
        with self._mu:
            dg = self._digest
            return {} if dg is None else dg.bucket_payload(shard, nshards)

    def digest_objects(self, kind: str, namespace: str) -> Dict[str, str]:
        """Maintained per-object digests of one bucket — the walk's
        bottom tier."""
        with self._mu:
            dg = self._digest
            return {} if dg is None else dg.object_payload(kind, namespace)

    def recompute_digest(self):
        """Ground-truth digest: a fresh walk over every (materialized)
        object, independent of the incrementally maintained table — what
        ``vtctl audit`` compares the maintained digests against."""
        from volcano_tpu import vtaudit

        with self._mu:
            self.materialize_all()
            return vtaudit.table_from_objects(
                (kind, obj)
                for kind, bucket in self._objects.items()
                for obj in bucket.values()
            )

    # -- watch --------------------------------------------------------------

    def watch(self, kind: str) -> Deque[Event]:
        """Subscribe to a kind; returns the event queue to drain."""
        q: Deque[Event] = deque()
        self._watchers[kind].append(q)
        return q

    #: kinds that skip the shadow copy: fire-and-forget records nobody
    #: diff-suppresses (their rare count-bump patches take the full
    #: update() path) — a per-bind Scheduled Event otherwise pays a
    #: deep_clone per create, 100k per cycle drain
    SHADOWLESS_KINDS = frozenset({"Event"})

    def _notify(self, ev: Event) -> None:
        for q in self._watchers[ev.kind]:
            q.append(ev)
        # shadow every kind (not just watched ones): update() compares
        # against it to suppress no-op writes, which quiescence relies on;
        # deletions must drop the shadow or deleted objects leak forever
        if ev.type == EventType.DELETED:
            self._shadow[ev.kind].pop(ev.obj.meta.key, None)
        elif ev.kind not in self.SHADOWLESS_KINDS:
            from volcano_tpu.api.fastclone import deep_clone

            self._shadow[ev.kind][ev.obj.meta.key] = deep_clone(ev.obj)

    def pending_events(self) -> bool:
        return any(q for qs in self._watchers.values() for q in qs)
