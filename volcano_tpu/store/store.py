"""In-memory watchable object store — the "API server" bus of the framework.

The reference's components never talk to each other directly; they watch and
write CRDs through the Kubernetes API server (SURVEY.md section 1). This
store plays that role for the TPU framework: typed buckets keyed by
namespace/name, monotonically increasing resource versions, and watch
subscriptions that deliver add/update/delete events.

Unlike informers+goroutines, delivery is deterministic: events queue up and
subscribers drain them when pumped (tests and the simulator control the
interleaving explicitly; `Cluster.run_until_idle` is the scheduler's
equivalent of "wait for informer sync").
"""

from __future__ import annotations

import copy
import enum
from collections import defaultdict, deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, Iterator, List, Optional

#: sentinel for "attribute absent" in patch's no-op field comparison
_MISSING = object()


class EventType(str, enum.Enum):
    ADDED = "Added"
    UPDATED = "Updated"
    DELETED = "Deleted"


class Conflict(Exception):
    """Optimistic-concurrency failure: the object changed since it was read
    (the API server's 409 on a stale resourceVersion)."""


class PreconditionFailed(Exception):
    """A patch's ``when`` clause did not match the stored object — the
    write was skipped entirely (the conditional-patch analogue of a CAS
    miss; callers that race benignly treat it as a no-op)."""


def _walk(obj: Any, dotted: str):
    """(parent, leaf_name) for a dotted attribute path; raises
    AttributeError on any missing hop."""
    parts = dotted.split(".")
    cur = obj
    for p in parts[:-1]:
        if not hasattr(cur, p):
            raise AttributeError(f"no field {p!r} on path {dotted!r}")
        cur = getattr(cur, p)
    if not hasattr(cur, parts[-1]):
        raise AttributeError(f"no field {parts[-1]!r} on path {dotted!r}")
    return cur, parts[-1]


@dataclass
class Event:
    kind: str
    type: EventType
    obj: Any
    old: Any = None
    #: for COW patch events: the (possibly dotted) field map that was
    #: applied — lets the store server maintain its encoded-object cache
    #: by delta instead of re-encoding the full object per bind/patch
    fields: Any = None


class Store:
    """Typed object buckets + watch queues.

    Kinds used by the framework: "Job", "Pod", "PodGroup", "Queue", "Node",
    "Command", "ConfigMap", "Service", "PriorityClass", "PVC".
    """

    def __init__(self):
        import uuid

        from volcano_tpu.locksan import make_rlock

        #: lineage identity: survives pickling (vtctl state) and the store
        #: server's durable state file, so a mirror checkpoint can tell
        #: "same store restarted" from "different store with coincidentally
        #: aligned resource-version counters"
        self.uid = uuid.uuid4().hex
        self._objects: Dict[str, Dict[str, Any]] = defaultdict(dict)
        # deep-copied last-notified state per object, so Event.old reflects
        # the pre-update object even though callers mutate in place (the
        # informer local-cache pattern); populated only for watched kinds.
        self._shadow: Dict[str, Dict[str, Any]] = defaultdict(dict)
        self._watchers: Dict[str, List[Deque[Event]]] = defaultdict(list)
        self._rv = 0
        # mutation lock: the async applier writes from its own thread while
        # the owning thread reads/writes (StoreServer adds its own RLock on
        # top for multi-client HTTP, which nests fine: server.lock is
        # always taken before _mu, never the reverse — the store never
        # calls back into the server)
        self._mu = make_rlock("Store._mu")

    def __getstate__(self):
        # the mutation lock is process-local (vtctl pickles the simulated
        # cluster's store for persisted state)
        state = self.__dict__.copy()
        del state["_mu"]
        return state

    def __setstate__(self, state):
        from volcano_tpu.locksan import make_rlock

        self.__dict__.update(state)
        self._mu = make_rlock("Store._mu")

    def _watched(self, kind: str) -> bool:
        return bool(self._watchers[kind])

    @property
    def resource_version(self) -> int:
        """Monotonic global version; bumps on every create/update."""
        return self._rv

    # -- CRUD ---------------------------------------------------------------

    def create(self, kind: str, obj: Any) -> Any:
        with self._mu:
            key = obj.meta.key
            if key in self._objects[kind]:
                raise KeyError(f"{kind} {key} already exists")
            self._rv += 1
            obj.meta.resource_version = self._rv
            if not obj.meta.creation_timestamp:
                import time

                obj.meta.creation_timestamp = time.time()
            self._objects[kind][key] = obj
            self._notify(Event(kind, EventType.ADDED, obj))
            return obj

    def update(self, kind: str, obj: Any) -> Any:
        with self._mu:
            key = obj.meta.key
            if key not in self._objects[kind]:
                raise KeyError(f"{kind} {key} not found")
            old = self._shadow[kind].get(key)
            # no-op writes don't bump the version or fan out events — callers
            # (scheduler close_session, controller status writers) write
            # unconditionally each cycle and rely on this for quiescence
            if old is not None and old == obj:
                return obj
            self._rv += 1
            obj.meta.resource_version = self._rv
            self._objects[kind][key] = obj
            self._notify(Event(kind, EventType.UPDATED, obj, old))
            return obj

    def update_cas(self, kind: str, obj: Any, expected_rv: int) -> Any:
        """Compare-and-swap update: succeeds only if the stored object's
        resource_version still equals ``expected_rv`` (read-modify-write
        safety for concurrent writers, e.g. leader leases and kubelets)."""
        with self._mu:
            current = self._objects[kind].get(obj.meta.key)
            if current is None:
                raise KeyError(f"{kind} {obj.meta.key} not found")
            if current.meta.resource_version != expected_rv:
                raise Conflict(
                    f"{kind} {obj.meta.key}: expected rv {expected_rv}, "
                    f"have {current.meta.resource_version}"
                )
            return self.update(kind, obj)

    def patch(self, kind: str, key: str, fields: Dict[str, Any],
              when: Optional[Dict[str, Any]] = None) -> Any:
        """Apply field updates to the stored object in place (the API
        server's PATCH; Bind is a node_name patch). Attribute names must
        already exist on the object — typos fail loudly.  Names may be
        dotted paths ('status.phase': set one nested field, preserve its
        siblings).  ``when`` is an optional precondition map of dotted
        paths to expected values; any mismatch raises PreconditionFailed
        and nothing is written (the conditional read-modify-write the
        fast cycle's bulk enqueue shipping needs in ONE round trip).

        Hot path for the async applier's bind batches: when a shadow
        exists, only the patched fields are cloned into a copy-on-write
        shadow instead of re-cloning the whole object per write — the
        full-object deep_clone was 75% of drain time at 100k binds/cycle.
        """
        with self._mu:
            obj = self._objects[kind].get(key)
            if obj is None:
                raise KeyError(f"{kind} {key} not found")
            if when:
                for k, expect in when.items():
                    parent, leaf = _walk(obj, k)
                    got = getattr(parent, leaf)
                    if got != expect:
                        raise PreconditionFailed(
                            f"{kind} {key}: {k} is {got!r}, wanted {expect!r}"
                        )
            # ONE copy-on-write implementation for flat and dotted fields —
            # a flat name is a one-segment path.  Validate every path
            # BEFORE mutating: a bad field must not leave earlier fields
            # silently applied with no event/version.
            paths = {k: k.split(".") for k in fields}
            for k in fields:
                _walk(obj, k)
            shadow = self._shadow[kind].get(key)
            if shadow is None or any(p[0] == "meta" for p in paths.values()):
                for k, v in fields.items():
                    parent, leaf = _walk(obj, k)
                    setattr(parent, leaf, v)
                return self.update(kind, obj)

            def _leaf(root, parts):
                for p in parts[:-1]:
                    root = getattr(root, p)
                return getattr(root, parts[-1], _MISSING)

            if all(
                _leaf(obj, paths[k]) == v and _leaf(shadow, paths[k]) == v
                for k, v in fields.items()
            ):
                return obj  # no-op: quiescence contract (see update())
            from volcano_tpu.api.fastclone import deep_clone

            for k, v in fields.items():
                parent, leaf = _walk(obj, k)
                setattr(parent, leaf, v)
            self._rv += 1
            obj.meta.resource_version = self._rv
            # copy-on-write shadow: path hops are shallow-copied, so
            # unpatched fields/siblings share the old shadow's
            # (immutable-by-contract) values; the queued Event keeps the
            # old shadow object untouched as its pre-update view.  Full
            # update() here (a deep_clone + recursive __eq__ per write)
            # measured 75% of drain time at 100k binds/cycle and ~0.2 s of
            # the timed cycle for a 5k-group bulk enqueue shipping.
            new_shadow = copy.copy(shadow)
            new_shadow.meta = copy.copy(shadow.meta)
            new_shadow.meta.resource_version = self._rv
            for k, v in fields.items():
                parts = paths[k]
                cur = new_shadow
                for p in parts[:-1]:
                    child = copy.copy(getattr(cur, p))
                    setattr(cur, p, child)
                    cur = child
                setattr(cur, parts[-1], deep_clone(v))
            ev = Event(kind, EventType.UPDATED, obj, shadow, fields=fields)
            for q in self._watchers[kind]:
                q.append(ev)
            self._shadow[kind][key] = new_shadow
            return obj

    def bulk(self, ops: List[Dict[str, Any]]) -> List[Optional[str]]:
        """Apply N mutations in one call — the store-side half of batched
        side-effect application (one round trip for a cycle's binds over
        RemoteStore). Each op is a dict:

          {"op": "create"|"update", "kind": K, "object": obj}
          {"op": "patch",  "kind": K, "key": key, "fields": {...}}
          {"op": "delete", "kind": K, "key": key}

        Ops apply independently in order (no transaction — semantically N
        API calls); the result is one error string (or None) per op.
        """
        results: List[Optional[str]] = []
        for op in ops:
            try:
                verb = op["op"]
                kind = op["kind"]
                if verb == "create":
                    self.create(kind, op["object"])
                elif verb == "update":
                    self.update(kind, op["object"])
                elif verb == "patch":
                    self.patch(kind, op["key"], op["fields"],
                               when=op.get("when"))
                elif verb == "delete":
                    self.delete(kind, op["key"])
                else:
                    raise ValueError(f"unknown bulk op {verb!r}")
                results.append(None)
            except KeyError as e:
                # structured marker: callers that treat a vanished object
                # as success (evict of an already-deleted pod) match this
                # prefix instead of reverse-engineering exception reprs
                results.append(f"NotFound: {e}")
            except Exception as e:  # noqa: BLE001 — per-op isolation
                results.append(repr(e))
        return results

    def delete(self, kind: str, key: str) -> Optional[Any]:
        with self._mu:
            obj = self._objects[kind].pop(key, None)
            if obj is not None:
                self._notify(Event(kind, EventType.DELETED, obj))  # drops the shadow too
            return obj

    def get(self, kind: str, key: str) -> Optional[Any]:
        return self._objects[kind].get(key)

    def list(self, kind: str) -> List[Any]:
        with self._mu:
            return list(self._objects[kind].values())

    def items(self, kind: str) -> Iterator[Any]:
        return iter(self.list(kind))

    # -- watch --------------------------------------------------------------

    def watch(self, kind: str) -> Deque[Event]:
        """Subscribe to a kind; returns the event queue to drain."""
        q: Deque[Event] = deque()
        self._watchers[kind].append(q)
        return q

    #: kinds that skip the shadow copy: fire-and-forget records nobody
    #: diff-suppresses (their rare count-bump patches take the full
    #: update() path) — a per-bind Scheduled Event otherwise pays a
    #: deep_clone per create, 100k per cycle drain
    SHADOWLESS_KINDS = frozenset({"Event"})

    def _notify(self, ev: Event) -> None:
        for q in self._watchers[ev.kind]:
            q.append(ev)
        # shadow every kind (not just watched ones): update() compares
        # against it to suppress no-op writes, which quiescence relies on;
        # deletions must drop the shadow or deleted objects leak forever
        if ev.type == EventType.DELETED:
            self._shadow[ev.kind].pop(ev.obj.meta.key, None)
        elif ev.kind not in self.SHADOWLESS_KINDS:
            from volcano_tpu.api.fastclone import deep_clone

            self._shadow[ev.kind][ev.obj.meta.key] = deep_clone(ev.obj)

    def pending_events(self) -> bool:
        return any(q for qs in self._watchers.values() for q in qs)
