"""JSON wire codec for the API objects — the serialization layer of the bus.

The reference's components exchange CRDs as JSON through the Kubernetes API
server (client-go encodes/decodes the generated types). This module is the
equivalent for the framework's dataclass object model: a generic
dataclass <-> JSON-dict codec driven by type hints, plus the kind registry
mapping the store's kind strings to their root classes.

Used by the store server (volcano_tpu/store/server.py) and the RemoteStore
client so the scheduler, controller, admission webhook, and CLI can run as
separate processes against one API server — the reference's process model
(SURVEY.md §1: three binaries + vkctl, all speaking to the API server).
"""

from __future__ import annotations

import dataclasses
import enum
import typing
from typing import Any, Dict, Optional, Tuple, Type, Union

from volcano_tpu.api.job import Job
from volcano_tpu.api.objects import (
    Command,
    ConfigMap,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    PodDisruptionBudget,
    StorageClass,
    Pod,
    PodGroup,
    PriorityClass,
    Queue,
    Service,
)
from volcano_tpu.api.resource import Resource
from volcano_tpu.events import ClusterEvent
from volcano_tpu.leader import Lease

#: store kind string -> root dataclass (the "scheme" in client-go terms)
KIND_CLASSES: Dict[str, type] = {
    "Job": Job,
    "Pod": Pod,
    "PodGroup": PodGroup,
    "Queue": Queue,
    "Node": Node,
    "Command": Command,
    "ConfigMap": ConfigMap,
    "Service": Service,
    "PriorityClass": PriorityClass,
    "PVC": PersistentVolumeClaim,
    "PV": PersistentVolume,
    "StorageClass": StorageClass,
    "PodDisruptionBudget": PodDisruptionBudget,
    "Lease": Lease,
    "Event": ClusterEvent,
}

_hints_cache: Dict[type, Dict[str, Any]] = {}


def _hints(cls: type) -> Dict[str, Any]:
    h = _hints_cache.get(cls)
    if h is None:
        h = typing.get_type_hints(cls)
        _hints_cache[cls] = h
    return h


# -- encode ------------------------------------------------------------------


def encode(obj: Any) -> Any:
    """Dataclass tree -> JSON-compatible value. Type-directed on decode, so
    encode is purely structural."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        # str enums pass through as their value via isinstance(str)
        if isinstance(obj, enum.Enum):
            return obj.value
        return obj
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, Resource):
        out: Dict[str, Any] = {"cpu": obj.milli_cpu, "mem": obj.memory}
        if obj.scalars:
            out["scalars"] = dict(obj.scalars)
        if obj.max_task_num is not None:
            out["max_task_num"] = obj.max_task_num
        return out
    if dataclasses.is_dataclass(obj):
        return {
            f.name: encode(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, (list, tuple)):
        return [encode(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): encode(v) for k, v in obj.items()}
    raise TypeError(f"cannot encode {type(obj).__name__}: {obj!r}")


# -- decode ------------------------------------------------------------------


def decode(tp: Any, data: Any) -> Any:
    """JSON value -> instance of type hint ``tp``."""
    origin = typing.get_origin(tp)
    if origin is Union:  # Optional[X] and friends
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if data is None:
            return None
        return decode(args[0], data)
    if tp is Any or tp is None:
        return data
    if origin in (list, typing.List):
        (item_tp,) = typing.get_args(tp) or (Any,)
        return [decode(item_tp, v) for v in data or []]
    if origin in (tuple, typing.Tuple):
        args = typing.get_args(tp)
        if data is None:
            return None
        if len(args) == 2 and args[1] is Ellipsis:
            return tuple(decode(args[0], v) for v in data)
        if not args:
            return tuple(data)
        return tuple(decode(a, v) for a, v in zip(args, data))
    if origin in (dict, typing.Dict):
        kt, vt = typing.get_args(tp) or (str, Any)
        return {decode(kt, k): decode(vt, v) for k, v in (data or {}).items()}
    if isinstance(tp, type):
        if tp is Resource:
            return Resource(
                milli_cpu=data.get("cpu", 0.0),
                memory=data.get("mem", 0.0),
                scalars=data.get("scalars"),
                max_task_num=data.get("max_task_num"),
            )
        if issubclass(tp, enum.Enum):
            return tp(data)
        if dataclasses.is_dataclass(tp):
            hints = _hints(tp)
            kwargs = {}
            for f in dataclasses.fields(tp):
                if f.name in data:
                    kwargs[f.name] = decode(hints[f.name], data[f.name])
            return tp(**kwargs)
        if tp in (int, float, str, bool):
            return tp(data) if data is not None else data
    return data


def encode_object(kind: str, obj: Any) -> Dict[str, Any]:
    return {"kind": kind, "object": encode(obj)}


def encode_fields(fields: Dict[str, Any]) -> Dict[str, Any]:
    """Patch-field map -> JSON-compatible values (field values may be
    nested dataclasses, e.g. a whole PodGroupStatus)."""
    return {k: encode(v) for k, v in fields.items()}


def _resolve_hint(cls: Any, dotted: str) -> Any:
    """Type hint at a dotted attribute path ('status.phase'), walking
    nested dataclass hints; None when any hop is unknown."""
    cur = cls
    for part in dotted.split("."):
        if cur is None or not dataclasses.is_dataclass(cur):
            return None
        cur = _hints(cur).get(part)
    return cur


def decode_fields(kind: str, fields: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of ``encode_fields``, type-directed by the kind's class
    hints so object-valued fields rebuild their dataclasses.  Dotted
    paths ('status.phase') resolve through nested dataclass hints.
    Unknown kinds/fields pass through (Store.patch validates attribute
    names)."""
    cls = KIND_CLASSES.get(kind)
    if cls is None or not dataclasses.is_dataclass(cls):
        return fields
    out = {}
    for k, v in fields.items():
        hint = _resolve_hint(cls, k)
        out[k] = decode(hint, v) if hint is not None else v
    return out


def decode_object(kind: str, data: Dict[str, Any]) -> Any:
    cls = KIND_CLASSES.get(kind)
    if cls is None:
        raise KeyError(f"unknown kind {kind!r}")
    return decode(cls, data)
