"""JSON wire codec for the API objects — the serialization layer of the bus.

The reference's components exchange CRDs as JSON through the Kubernetes API
server (client-go encodes/decodes the generated types). This module is the
equivalent for the framework's dataclass object model: a generic
dataclass <-> JSON-dict codec driven by type hints, plus the kind registry
mapping the store's kind strings to their root classes.

Used by the store server (volcano_tpu/store/server.py) and the RemoteStore
client so the scheduler, controller, admission webhook, and CLI can run as
separate processes against one API server — the reference's process model
(SURVEY.md §1: three binaries + vkctl, all speaking to the API server).
"""

from __future__ import annotations

import dataclasses
import enum
import typing
from typing import Any, Dict, Optional, Tuple, Type, Union

from volcano_tpu.api.job import Job
from volcano_tpu.api.objects import (
    Command,
    ConfigMap,
    Node,
    NodePool,
    PersistentVolume,
    PersistentVolumeClaim,
    PodDisruptionBudget,
    StorageClass,
    Pod,
    PodGroup,
    PriorityClass,
    Queue,
    Service,
)
from volcano_tpu.api.resource import Resource
from volcano_tpu.events import ClusterEvent
from volcano_tpu.leader import Lease

#: store kind string -> root dataclass (the "scheme" in client-go terms)
KIND_CLASSES: Dict[str, type] = {
    "Job": Job,
    "Pod": Pod,
    "PodGroup": PodGroup,
    "Queue": Queue,
    "Node": Node,
    "NodePool": NodePool,
    "Command": Command,
    "ConfigMap": ConfigMap,
    "Service": Service,
    "PriorityClass": PriorityClass,
    "PVC": PersistentVolumeClaim,
    "PV": PersistentVolume,
    "StorageClass": StorageClass,
    "PodDisruptionBudget": PodDisruptionBudget,
    "Lease": Lease,
    "Event": ClusterEvent,
}

_hints_cache: Dict[type, Dict[str, Any]] = {}


def _hints(cls: type) -> Dict[str, Any]:
    h = _hints_cache.get(cls)
    if h is None:
        h = typing.get_type_hints(cls)
        _hints_cache[cls] = h
    return h


# -- encode ------------------------------------------------------------------

#: per-class field-name tuples (dataclasses.fields() re-derives the list on
#: every call — at 100k objects/cycle through the event log that was the
#: single hottest line of the whole HTTP path)
_fields_cache: Dict[type, Tuple[str, ...]] = {}
#: scalar leaf types that pass through unchanged (str enums are handled
#: first — their value IS the wire form)
_SCALARS = (bool, int, float, str)


def encode(obj: Any) -> Any:
    """Dataclass tree -> JSON-compatible value. Type-directed on decode, so
    encode is purely structural.  Dispatches on exact class via caches —
    this function dominates the wire path's profile."""
    if obj is None:
        return None
    cls = obj.__class__
    names = _fields_cache.get(cls)
    if names is not None:  # cached dataclass: the overwhelmingly common case
        return {name: encode(getattr(obj, name)) for name in names}
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, _SCALARS):
        return obj
    if isinstance(obj, Resource):
        out: Dict[str, Any] = {"cpu": obj.milli_cpu, "mem": obj.memory}
        if obj.scalars:
            out["scalars"] = dict(obj.scalars)
        if obj.max_task_num is not None:
            out["max_task_num"] = obj.max_task_num
        return out
    if dataclasses.is_dataclass(obj):
        names = tuple(f.name for f in dataclasses.fields(cls))
        _fields_cache[cls] = names
        return {name: encode(getattr(obj, name)) for name in names}
    if isinstance(obj, (list, tuple)):
        return [encode(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): encode(v) for k, v in obj.items()}
    raise TypeError(f"cannot encode {type(obj).__name__}: {obj!r}")


# -- decode ------------------------------------------------------------------


#: compiled decoder per type hint — decode is the client half of the wire
#: hot path (a 100k-object list/watch drain calls it per field), so the
#: origin/args introspection happens once per hint, not once per value
_decoders: Dict[Any, Any] = {}


def _decoder(tp: Any):
    d = _decoders.get(tp)
    if d is None:
        d = _build_decoder(tp)
        _decoders[tp] = d
    return d


def _build_decoder(tp: Any):
    origin = typing.get_origin(tp)
    if origin is Union:  # Optional[X] and friends
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        inner = _decoder(args[0])
        return lambda data: None if data is None else inner(data)
    if tp is Any or tp is None:
        return lambda data: data
    if origin in (list, typing.List):
        (item_tp,) = typing.get_args(tp) or (Any,)
        item = _decoder(item_tp)
        return lambda data: [item(v) for v in data or []]
    if origin in (tuple, typing.Tuple):
        args = typing.get_args(tp)
        if len(args) == 2 and args[1] is Ellipsis:
            item = _decoder(args[0])
            return lambda data: (
                None if data is None else tuple(item(v) for v in data)
            )
        if not args:
            return lambda data: None if data is None else tuple(data)
        items = [_decoder(a) for a in args]
        return lambda data: (
            None if data is None
            else tuple(d(v) for d, v in zip(items, data))
        )
    if origin in (dict, typing.Dict):
        kt, vt = typing.get_args(tp) or (str, Any)
        kd, vd = _decoder(kt), _decoder(vt)
        return lambda data: {
            kd(k): vd(v) for k, v in (data or {}).items()
        }
    if isinstance(tp, type):
        if tp is Resource:
            return lambda data: Resource(
                milli_cpu=data.get("cpu", 0.0),
                memory=data.get("mem", 0.0),
                scalars=data.get("scalars"),
                max_task_num=data.get("max_task_num"),
            )
        if issubclass(tp, enum.Enum):
            return tp
        if dataclasses.is_dataclass(tp):
            # field plan built lazily on first use so self-referential
            # dataclass hints cannot recurse during decoder construction
            plan: list = []

            def dec(data, tp=tp, plan=plan):
                if not plan:
                    hints = _hints(tp)
                    plan.extend(
                        (f.name, _decoder(hints[f.name]))
                        for f in dataclasses.fields(tp)
                    )
                kwargs = {}
                for name, d in plan:
                    if name in data:
                        kwargs[name] = d(data[name])
                return tp(**kwargs)

            return dec
        if tp in (int, float, str, bool):
            return lambda data: tp(data) if data is not None else data
    return lambda data: data


def decode(tp: Any, data: Any) -> Any:
    """JSON value -> instance of type hint ``tp``."""
    return _decoder(tp)(data)


def encode_object(kind: str, obj: Any) -> Dict[str, Any]:
    return {"kind": kind, "object": encode(obj)}


def encode_fields(fields: Dict[str, Any]) -> Dict[str, Any]:
    """Patch-field map -> JSON-compatible values (field values may be
    nested dataclasses, e.g. a whole PodGroupStatus)."""
    return {k: encode(v) for k, v in fields.items()}


def _resolve_hint(cls: Any, dotted: str) -> Any:
    """Type hint at a dotted attribute path ('status.phase'), walking
    nested dataclass hints; None when any hop is unknown."""
    cur = cls
    for part in dotted.split("."):
        if cur is None or not dataclasses.is_dataclass(cur):
            return None
        cur = _hints(cur).get(part)
    return cur


def decode_fields(kind: str, fields: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of ``encode_fields``, type-directed by the kind's class
    hints so object-valued fields rebuild their dataclasses.  Dotted
    paths ('status.phase') resolve through nested dataclass hints.
    Unknown kinds/fields pass through (Store.patch validates attribute
    names)."""
    cls = KIND_CLASSES.get(kind)
    if cls is None or not dataclasses.is_dataclass(cls):
        return fields
    out = {}
    for k, v in fields.items():
        hint = _resolve_hint(cls, k)
        out[k] = decode(hint, v) if hint is not None else v
    return out


def decode_object(kind: str, data: Dict[str, Any]) -> Any:
    cls = KIND_CLASSES.get(kind)
    if cls is None:
        raise KeyError(f"unknown kind {kind!r}")
    return decode(cls, data)
