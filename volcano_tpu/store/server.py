"""The store server: an HTTP "API server" hosting the watchable Store.

The reference's process model is three binaries plus a CLI that never talk
to each other — they watch and write CRDs through the Kubernetes API
server, which also calls the admission webhook inline on Job writes
(SURVEY.md §1, §3.3: API server -> vk-admission -> persist -> informers).
This server reproduces that boundary over HTTP so the scheduler,
controller, and vtctl can each run as separate OS processes:

  GET    /apis/<kind>                 list
  GET    /apis/<kind>/obj?key=<k>     get
  POST   /apis/<kind>                 create   (Jobs pass admission first)
  PUT    /apis/<kind>                 update   (Job spec frozen, as admit_job.go)
  DELETE /apis/<kind>/obj?key=<k>     delete
  GET    /watch?since=<seq>&kinds=a,b&timeout=<s>   long-poll event log
  GET    /healthz

Watch semantics mirror list+watch: every mutation appends to a global
ordered event log; clients resume from their last sequence number, so a
restarted client rebuilds state with a list then watches from "now" — the
same rebuild-from-the-bus property the reference gets from etcd.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from volcano_tpu import effectsan, timeseries, trace, vtaudit, vtprof
from volcano_tpu.chaos import ChaosPlanError, FaultPlan, env_plan, fire_crash
from volcano_tpu.locksan import make_lock, make_rlock
from volcano_tpu.store.codec import (
    KIND_CLASSES,
    decode_fields,
    decode_object,
    encode,
)
from volcano_tpu.store.store import PreconditionFailed, Store

#: cap on buffered events; a client further behind than this must relist
#: (the reference's "resourceVersion too old" watch error)
LOG_CAP = 100_000


def _traced(verb: str):
    """Continue the client's ``X-Volcano-Trace`` context around one
    request verb: the request span parents to the caller's span across
    the process boundary.  Disarmed = one attribute check per request
    (the chaos-guard discipline); the ``/chaos`` and ``/debug/*``
    admin endpoints are never traced (reading the flight recorder must
    not write to it)."""

    def deco(fn):
        def handler(self):
            if trace.TRACER is None:
                return fn(self)
            path = self.path
            if path.startswith("/chaos") or path.startswith("/debug/") \
                    or path.startswith("/metrics"):
                return fn(self)
            header = self.headers.get(trace.HEADER, "")
            if not header:
                # an uncontexted request (steady-state polling) would root
                # a pointless single-span trace per poll and churn the
                # ring out from under the gang spans operators care about
                return fn(self)
            trace.set_component("apiserver")
            with trace.request_context(
                header, f"store.{verb}", path=path.split("?", 1)[0],
            ):
                return fn(self)

        return handler

    return deco


class StoreServer:
    def __init__(
        self,
        store: Optional[Store] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        admission: bool = True,
        state_path: Optional[str] = None,
        save_interval: float = 0.25,
        wal=None,
        shards: int = 1,
        repl: Optional[Dict[str, Any]] = None,
        seq_bus=None,
        proc_shard: Optional[tuple] = None,
    ):
        self.store = store or Store()
        self.admission = admission
        # lock-order contract (enforced statically by vtlint `lock-order`
        # and at runtime by the env-gated sanitizer, `make sanitize`):
        # _flush_lock is always taken BEFORE lock, never the reverse;
        # a shard apply lock is always taken BEFORE lock, never the reverse
        self.lock = make_rlock("StoreServer.lock")
        self.cond = threading.Condition(self.lock)
        # partitioned decision bus (store/partition.py): shard count for
        # the segment stream / WAL / watch fan-out.  shards == 1 is the
        # unpartitioned server, byte-for-byte.  Each shard gets an apply
        # lock serializing ITS sub-segments (ship order per shard) while
        # different shards' sub-segments overlap everywhere outside the
        # short global seq/rv critical section.
        self.shards = max(1, int(shards))
        self._shard_locks = [
            make_rlock("StoreServer.shard_apply")
            for _ in range(self.shards)
        ]
        # ordered event log: plain per-event dict entries, or columnar
        # block entries {"seq": <last row's seq>, "n": rows, "kind": K,
        # "block": PatchLogBlock|EventLogBlock, "start": first block row}
        # appended by the segment verb — one entry per segment section, so
        # log append cost scales with segments, not objects; watch_since
        # expands block rows lazily (memoized once, shared by watchers)
        self.log: List[Dict[str, Any]] = []
        #: total event rows currently buffered (block entries count their
        #: rows) — the relist horizon is ``seq - _log_rows``
        self._log_rows = 0
        self.seq = 0
        # procmesh (store/procmesh): this server is ONE shard of a
        # multi-process mesh.  ``seq_bus`` is the shared cross-process
        # seq/rv allocator (None = local dense counters, byte-for-byte
        # the historical server); ``proc_shard`` is ``(index, count)``
        # within the mesh, advertised on /healthz for routers/clients.
        # With a bus armed, local seqs GAP (siblings consume the line
        # too), so the relist horizon tracks an explicit _log_floor and
        # watch replies stamp the global high-water mark (_seq_hwm).
        self._seq_bus = seq_bus
        self.proc_shard = (
            (int(proc_shard[0]), int(proc_shard[1]))
            if proc_shard is not None else None
        )
        self._gapped = seq_bus is not None or proc_shard is not None
        #: seq watermark at/below the newest TRIMMED (or never-buffered)
        #: log row — the relist horizon for gapped seq lines; dense
        #: servers keep using ``seq - _log_rows`` (identical value)
        self._log_floor = 0
        #: newest seq that touched each shard (untagged/cross-shard
        #: entries advance every shard) — the /healthz skew surface
        self._shard_seq = [0] * self.shards
        # digest beacon cadence state (vtaudit): seq of the last stamped
        # beacon and the monotonic stamp time.  Starting the clock at
        # boot means a short-lived server never stamps one spontaneously.
        self._beacon_seq = 0
        self._beacon_mono = time.monotonic()
        # durability (the etcd analogue): objects + sequence persist to
        # ``state_path`` so a restarted server resumes with all CRDs; the
        # event log is NOT persisted — clients behind the restart relist,
        # the same recovery the reference gets from a compacted etcd watch
        self.state_path = state_path
        self.save_interval = save_interval
        # Durability contract: with save_interval > 0 mutations are ACKed
        # before persistence — up to one interval of acked writes can be
        # lost on a crash (weaker than etcd, which fsyncs before acking;
        # watchers relist on restart either way). Pass save_interval <= 0
        # for sync-on-mutate: every ACKed mutation is flushed to the state
        # file first, the etcd contract, at per-request full-store cost.
        # Segment WAL (store/wal.py): ``wal`` truthy turns on the etcd
        # contract at group-commit cost — every mutation appends its wire
        # form to an append-only CRC-framed log and the 2xx waits on an
        # fsync shared by every request in flight (a decision segment is
        # ONE record, so a 102k-bind cycle pays one fsync, not 102k).
        # The state file becomes the CHECKPOINT: flush_state rotates the
        # log, snapshots, and truncates the covered segments; recovery =
        # snapshot + torn-tail-tolerant replay (_load_state).
        self.wal = None
        if wal:
            if state_path is None:
                raise ValueError(
                    "wal requires state_path (the WAL checkpoints into "
                    "the state file)")
            wal_dir = wal if isinstance(wal, str) else state_path + ".wal"
            if self.shards > 1:
                # partitioned bus: one WAL per shard with independent
                # group-commit fsync (store/partition.py)
                from volcano_tpu.store.partition import ShardedWAL

                self.wal = ShardedWAL(wal_dir, self.shards)
            else:
                from volcano_tpu.store.wal import WriteAheadLog

                self.wal = WriteAheadLog(wal_dir)
        self._sync_persist = (state_path is not None and save_interval <= 0
                              and self.wal is None)
        self._dirty_kinds: set = set()
        # serializes concurrent flushes end-to-end (saver thread vs the
        # shutdown flush): encode+write happen under this lock so a stale
        # snapshot can never overwrite a fresher one, and the shared tmp
        # path is never written by two threads at once
        self._flush_lock = make_lock("StoreServer._flush_lock")
        # per-kind encoded cache: only kinds dirtied since the last flush
        # re-encode, so steady-state lease renewals don't pay a full-store
        # serialization under the server lock every interval
        self._enc_cache: Dict[str, List[Any]] = {}
        # per-object encoded cache, maintained by event delta in _pump_log:
        # list responses and the event log serve from it instead of
        # re-encoding (memory: one encoded dict per live object, the same
        # order as the store's own shadow copies)
        self._obj_enc: Dict[tuple, Dict[str, Any]] = {}
        # lazy half of the encoded cache: (kind, key) -> (log block, row)
        # for objects whose newest state lives in an unexpanded columnar
        # segment — the segment IS the cache entry until a read resolves
        # it through _enc_of (first read materializes, memoized on the
        # block)
        self._enc_pending: Dict[tuple, tuple] = {}
        # create/update handlers already HOLD the wire encoding of the
        # object they decoded — they stage it here (meta re-stamped) so
        # _pump_log seeds the cache without re-encoding; cleared after
        # every pump (a suppressed no-op write must not leave a stale hint
        # for the key's next event)
        self._enc_hints: Dict[tuple, Dict[str, Any]] = {}
        # chaos middleware (volcano_tpu/chaos.py): None = disarmed, and
        # every faultpoint below is a single attribute check — the hot
        # cycle pays nothing.  Armed at boot from VOLCANO_TPU_CHAOS (so
        # subprocess daemons can be tortured) or at runtime via /chaos.
        self.chaos: Optional[FaultPlan] = env_plan()
        self._saver_stop = threading.Event()
        #: set by kill(): refuse further flushes — a crashed process
        #: cannot checkpoint, and its saver must not overwrite the state
        #: a successor is recovering from
        self._killed = False
        self._saver: Optional[threading.Thread] = None
        # replication (store/replica.py): built AFTER recovery + the
        # listening socket below (the identity defaults to the URL); the
        # epoch a snapshot carries is captured during _load_snapshot
        self.repl = None
        self._snap_repl_epoch = 0
        # placeholder until the real watch queues register below: recovery
        # may checkpoint (the wal_floor stamp) and flush pumps this map
        self._queues: Dict[str, Any] = {}
        if state_path is not None:
            self._load_state()
            # background saver: snapshots are encoded under the lock but
            # written outside it, OFF the mutation path — a synchronous
            # save inside _pump_log would stall every API request for the
            # duration of a full-store serialization. (Sync-persist mode
            # flushes inline in the handlers instead; no saver thread.)
            if not self._sync_persist:
                self._saver = threading.Thread(target=self._saver_loop, daemon=True)
                self._saver.start()
        # nothing recovered is buffered in the log: the relist horizon
        # starts at the recovered seq (0 on a fresh boot)
        self._log_floor = self.seq
        if seq_bus is not None:
            # join the mesh's shared seq/rv line: CAS the counters up to
            # what recovery produced (a restarted shard rejoins a line
            # its siblings kept advancing — max, never a reset), then arm
            # the store's rv allocator.  Armed strictly AFTER recovery so
            # replay never burns shared rvs for records that already own
            # their stamps.
            seq_bus.advance_to(self.seq, self.store._rv)
            self.store._rv_alloc = seq_bus.alloc_rv
        self._queues = {kind: self.store.watch(kind) for kind in KIND_CLASSES}

        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _reply(self, code: int, payload: Dict[str, Any]) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_text(self, code: int, body: bytes,
                            ctype: str = "text/plain; version=0.0.4"
                            ) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> Dict[str, Any]:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def _chaos_request(self, plan) -> bool:
                """server.request faultpoint: returns True when the fault
                consumed the request (a reply was already written).  The
                caller snapshots ``server.chaos`` ONCE and passes it in, so
                a concurrent disarm can never turn the armed check into a
                None dereference mid-request."""
                rule = plan.fire(
                    "server.request", method=self.command, path=self.path
                )
                if rule is None:
                    return False
                if rule.action == "truncate_log":
                    # drop the whole buffered log (seq preserved): every
                    # watcher whose cursor is behind head now falls off the
                    # buffer and must relist — the "resourceVersion too
                    # old" event compaction the reference gets from etcd
                    with server.lock:
                        del server.log[:]
                        server._log_rows = 0
                        server._log_floor = server.seq
                    return False
                return self._fault_reply(rule)

            def _fault_reply(self, rule) -> bool:
                """The request-shaped fault actions (delay / http_500 /
                cut_body), shared by ``server.request`` and ``repl.feed``
                — a replication feed cut mid-segment exercises the same
                torn-reply machinery as a client watch cut.  Returns True
                when the fault consumed the request."""
                if rule is None:
                    return False
                if rule.action == "delay":
                    time.sleep(rule.arg)
                    return False
                if rule.action == "http_500":
                    # an unread request body would corrupt the next
                    # keep-alive request on this connection; just drop it
                    self.close_connection = True
                    self._reply(503, {"error": "chaos: injected 5xx"})
                    return True
                if rule.action == "cut_body":
                    # advertise the full length, send half, slam the
                    # connection: the client's read raises IncompleteRead
                    payload = json.dumps(
                        {"error": "chaos: response cut mid-body"}
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload) * 2))
                    self.end_headers()
                    self.wfile.write(payload)
                    self.wfile.flush()
                    self.close_connection = True
                    return True
                return False

            @_traced("GET")
            def do_GET(self):
                u = urlparse(self.path)
                q = parse_qs(u.query)
                parts = [p for p in u.path.split("/") if p]
                if u.path == "/chaos":  # admin: always exempt from injection
                    return self._reply(200, server.chaos_status())
                if u.path == "/debug/trace":
                    # flight-recorder admin endpoint: exempt from chaos
                    # (forensics must work mid-storm) and never traced
                    return self._reply(200, trace.debug_payload())
                if u.path == "/debug/timeseries":
                    # per-cycle/per-flush time-series ring (vtctl top):
                    # chaos-exempt like /debug/trace
                    return self._reply(200, timeseries.debug_payload())
                if u.path == "/debug/prof":
                    # vtprof critical-path profile (vtctl profile):
                    # chaos-exempt like /debug/trace
                    return self._reply(200, vtprof.debug_payload())
                if u.path == "/debug/digest":
                    # vtaudit state digests (vtctl audit): chaos-exempt —
                    # auditing a diverged store must work mid-storm
                    return self._reply(200, server.digest_debug(q))
                if u.path == "/metrics":
                    # Prometheus exposition of THIS process's series —
                    # the vtfleet federation harvests each shard process
                    # here; chaos-exempt like the /debug surfaces
                    from volcano_tpu.scheduler import metrics

                    return self._reply_text(
                        200, metrics.expose_text().encode())
                if u.path == "/repl/status":
                    # chaos-exempt: the election protocol probes peers
                    # through this mid-storm — a faulted probe would read
                    # as a dead peer and skew the promotion vote
                    repl = server.repl
                    if repl is None:
                        return self._reply(
                            404, {"error": "replication not armed"})
                    return self._reply(200, repl.status())
                if u.path == "/repl/feed":
                    return self._repl_feed(q)
                chaos_plan = server.chaos
                if chaos_plan is not None and self._chaos_request(chaos_plan):
                    return
                if u.path == "/healthz":
                    payload = {"ok": True, "uid": server.store.uid,
                               "shards": server.shards}
                    if server.proc_shard is not None:
                        # one shard of a multi-process mesh: advertise
                        # position so routers/supervisors can verify the
                        # map, and the shared-line hwm for skew reads
                        payload["proc_shard"] = server.proc_shard[0]
                        payload["proc_shards"] = server.proc_shard[1]
                        payload["hwm"] = server._seq_hwm()
                    if server.repl is not None:
                        # replicated servers advertise role/epoch so
                        # wait_healthy(require_leader=True) can resolve
                        # the writer and watchers can fence on failover
                        payload["role"] = server.repl.role
                        payload["epoch"] = server.repl.epoch
                        payload["leader"] = server.repl.leader_url
                    with server.lock:
                        server._pump_log()
                        dg = server.store.digest_payload(server.shards)
                        if dg is not None:
                            # per-shard digest/seq: shard skew at a glance
                            payload["digest"] = {
                                "root": dg["root"], "seq": server.seq,
                                "shards": [
                                    {"digest": d, "seq": s}
                                    for d, s in zip(dg["shards"],
                                                    server._shard_seq)
                                ],
                            }
                    if server.wal is not None:
                        # durability observability for operators/bench:
                        # record/fsync totals, cumulative fsync seconds,
                        # recovery replay counts
                        payload["wal"] = server.wal.stats()
                    return self._reply(200, payload)
                if u.path == "/watch":
                    since = int(q.get("since", ["0"])[0])
                    kinds = set(q.get("kinds", [""])[0].split(",")) - {""}
                    timeout = float(q.get("timeout", ["0"])[0])
                    shard_q = q.get("shard", [None])[0]
                    return self._reply(200, server.watch_since(
                        since, kinds, timeout,
                        shard=int(shard_q) if shard_q is not None else None,
                    ))
                if len(parts) == 2 and parts[0] == "apis":
                    kind = parts[1]
                    with server.lock:
                        # drain queued events first: a write that bypassed
                        # the handlers (direct srv.store seeding) must not
                        # leave a stale cached encoding in the response
                        server._pump_log()
                        enc_of = server._enc_of
                        items = [
                            enc_of(kind, o.meta.key) or encode(o)
                            for o in server.store.list(kind)
                        ]
                    return self._reply(200, {"items": items, "seq": server.seq})
                if len(parts) == 3 and parts[0] == "apis" and parts[2] == "obj":
                    key = q.get("key", [""])[0]
                    with server.lock:
                        obj = server.store.get(parts[1], key)
                    if obj is None:
                        return self._reply(404, {"error": "not found"})
                    return self._reply(200, {"object": encode(obj)})
                return self._reply(404, {"error": f"no route {u.path}"})

            def _repl_feed(self, q) -> None:
                """``/repl/feed``: the replication shipping endpoint.
                Carries its OWN faultpoint family (``repl.feed``) instead
                of the generic request middleware, so a chaos plan can cut
                the feed mid-segment or delay shipping without touching
                client traffic on the same server."""
                repl = server.repl
                if repl is None:
                    return self._reply(
                        404, {"error": "replication not armed"})
                plan = server.chaos
                if plan is not None and self._fault_reply(
                    plan.fire("repl.feed", method="GET", path=self.path)
                ):
                    return
                out = repl.feed(
                    int(q.get("from", ["-1"])[0]),
                    q.get("id", [""])[0],
                    float(q.get("timeout", ["0"])[0]),
                    int(q["epoch"][0]) if "epoch" in q else None,
                )
                if out is None:
                    return self._reply(421, {
                        "error": "NotLeader", "leader": repl.leader_url})
                return self._reply(200, out)

            def _reject_writes(self) -> bool:
                """NotLeader guard on every mutation verb: a follower
                replica redirects writers to the leader with a 421 +
                hint (RemoteStore._refollow chases it).  Runs AFTER the
                chaos middleware — a fault plan targeting writes still
                fires on a follower, same as any request."""
                repl = server.repl
                if repl is None or repl.writable():
                    return False
                from volcano_tpu.scheduler import metrics

                metrics.register_repl_redirect()
                self._reply(421, {
                    "error": "NotLeader", "leader": repl.leader_url})
                return True

            @_traced("POST")
            def do_POST(self):
                u = urlparse(self.path)
                parts = [p for p in u.path.split("/") if p]
                if u.path == "/chaos":  # arm/replace the fault plan
                    try:
                        plan = FaultPlan.from_dict(self._body())
                    except (ChaosPlanError, ValueError) as e:
                        return self._reply(422, {"error": str(e)})
                    server.arm_chaos(plan)
                    return self._reply(200, server.chaos_status())
                chaos_plan = server.chaos
                if chaos_plan is not None and self._chaos_request(chaos_plan):
                    return
                if self._reject_writes():
                    return
                if u.path == "/bulk":
                    try:
                        body = self._body()
                        results = server.bulk(body.get("ops") or [])
                        code, payload = 200, {"results": results}
                    except Exception as e:  # noqa: BLE001 — wire boundary
                        effectsan.abandon("Handler.500")
                        code, payload = 500, {"error": repr(e)}
                    return self._reply(code, payload)
                if len(parts) == 2 and parts[0] == "apis":
                    try:
                        code, payload = server.create(parts[1], self._body())
                        if code < 400:  # failed verbs wrote nothing
                            server._commit_ack()
                    except Exception as e:  # noqa: BLE001 — wire boundary
                        effectsan.abandon("Handler.500")
                        code, payload = 500, {"error": repr(e)}
                    return self._reply(code, payload)
                return self._reply(404, {"error": "no route"})

            @_traced("PATCH")
            def do_PATCH(self):
                u = urlparse(self.path)
                parts = [p for p in u.path.split("/") if p]
                q = parse_qs(u.query)
                chaos_plan = server.chaos
                if chaos_plan is not None and self._chaos_request(chaos_plan):
                    return
                if self._reject_writes():
                    return
                if len(parts) == 3 and parts[0] == "apis" and parts[2] == "obj":
                    key = q.get("key", [""])[0]
                    try:
                        body = self._body()
                        code, payload = server.patch(
                            parts[1], key, body.get("fields") or {},
                            when=body.get("when"),
                        )
                        if code < 400:
                            server._commit_ack()
                    except Exception as e:  # noqa: BLE001
                        effectsan.abandon("Handler.500")
                        code, payload = 500, {"error": repr(e)}
                    return self._reply(code, payload)
                return self._reply(404, {"error": "no route"})

            @_traced("PUT")
            def do_PUT(self):
                u = urlparse(self.path)
                parts = [p for p in u.path.split("/") if p]
                q = parse_qs(u.query)
                chaos_plan = server.chaos
                if chaos_plan is not None and self._chaos_request(chaos_plan):
                    return
                if self._reject_writes():
                    return
                if len(parts) == 2 and parts[0] == "apis":
                    cas = q.get("cas", [None])[0]
                    try:
                        code, payload = server.update(
                            parts[1], self._body(),
                            expected_rv=int(cas) if cas is not None else None,
                        )
                        if code < 400:
                            server._commit_ack()
                    except Exception as e:  # noqa: BLE001
                        effectsan.abandon("Handler.500")
                        code, payload = 500, {"error": repr(e)}
                    return self._reply(code, payload)
                return self._reply(404, {"error": "no route"})

            @_traced("DELETE")
            def do_DELETE(self):
                u = urlparse(self.path)
                parts = [p for p in u.path.split("/") if p]
                q = parse_qs(u.query)
                if u.path == "/chaos":  # disarm
                    server.arm_chaos(None)
                    return self._reply(200, server.chaos_status())
                chaos_plan = server.chaos
                if chaos_plan is not None and self._chaos_request(chaos_plan):
                    return
                if self._reject_writes():
                    return
                if len(parts) == 3 and parts[0] == "apis" and parts[2] == "obj":
                    key = q.get("key", [""])[0]
                    with server.lock:
                        obj = server.store.delete(parts[1], key)
                        if obj is not None and server.wal is not None:
                            effectsan.note_mutate("Handler.do_DELETE")
                        server._pump_log()
                        if obj is not None and server.wal is not None:
                            server._wal_append({"op": "delete",
                                                "kind": parts[1],
                                                "key": key})
                    try:
                        server._commit_ack()
                    except Exception as e:  # noqa: BLE001 — wire boundary
                        effectsan.abandon("Handler.500")
                        return self._reply(500, {"error": repr(e)})
                    return self._reply(200, {"deleted": obj is not None})
                return self._reply(404, {"error": "no route"})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        if repl is not None:
            from volcano_tpu.store.replica import Replicator

            self.repl = Replicator(
                self,
                identity=repl.get("identity"),
                peers=repl.get("peers"),
                leader_url=repl.get("leader"),
                ack=repl.get("ack", "async"),
                lease_duration=float(repl.get("lease_duration", 5.0)),
                lease_name=repl.get("lease_name"),
            )
        self._thread: Optional[threading.Thread] = None

    # -- chaos admin (volcano_tpu/chaos.py) ------------------------------------

    def arm_chaos(self, plan: Optional[FaultPlan]) -> None:
        """Arm (or, with None, disarm) a fault plan.  Counters restart
        with the new plan; the middleware reads ``self.chaos`` once per
        faultpoint, so in-flight requests finish under whichever plan they
        started with."""
        with self.lock:
            self.chaos = plan

    def chaos_status(self) -> Dict[str, Any]:
        plan = self.chaos
        if plan is None:
            return {"armed": False, "plan": None, "stats": []}
        return {"armed": True, "plan": plan.to_dict(), "stats": plan.stats()}

    # -- mutations (called from handler threads, locked) ----------------------

    def _maybe_flush(self) -> None:
        """Sync-persist flush, called by the HTTP handlers (and bulk) AFTER
        the mutation verb returns — never from inside the verbs, so no code
        path can hold ``self.lock`` while taking ``_flush_lock``.  The
        saver/shutdown flusher takes ``_flush_lock`` BEFORE ``self.lock``;
        flushing under the server lock would be an ABBA deadlock, and the
        vtlint ``lock-order`` rule now proves the order structurally
        instead of by caller convention."""
        if self._sync_persist:
            self.flush_state()

    def _wal_append(self, rec: Dict[str, Any]) -> None:
        """Append one mutation record (wire form) to the WAL, stamped with
        the post-op seq/rv so recovery resumes the exact continuity line.
        Must be called under the server lock AFTER the op's ``_pump_log``
        (so the stamps reflect the op) — append order is then apply
        order.  On a partitioned bus the record routes to its namespace
        shard's WAL (partition.wal_shard; segments carry their shard
        explicitly), and recovery merges the shard tails back into one
        ordered replay by these seq stamps.  The fsync happens later, in
        ``_commit_ack``, outside the lock."""
        rec["seq"] = self.seq
        rec["rv"] = self.store._rv
        ticket = self.wal.append(rec)
        effectsan.note_append("StoreServer._wal_append")
        if self.repl is not None:
            # replication log entry (store/replica.py): shippable once
            # this shard's fsync watermark covers the ticket (followers
            # run the same path, building their own post-promotion log)
            self.repl.log_append(rec, ticket)
            # the record is in the ship queue — NOW a due beacon may
            # stamp: it enqueues behind the record, so followers apply
            # the mutations first and the digests compare at the same
            # state (stamping before the ship, as pre-repl _pump_log
            # did, made every segment-adjacent beacon a false divergence)
            self._maybe_beacon()
        from volcano_tpu.scheduler import metrics

        metrics.register_wal_append()

    def _commit_ack(self, _repl_sync: bool = True) -> None:
        effectsan.note_ack("StoreServer._commit_ack")
        """The durability barrier between a successful mutation and its
        2xx reply: group-commit fsync the WAL tail (ACK-after-append —
        the etcd contract), then any sync-persist snapshot flush.  The
        ``crash.server.{pre,post}_fsync`` faultpoints bracket the fsync:
        a pre-fsync kill may lose the (never-ACKed) record, a post-fsync
        kill must lose nothing.  With replication armed, the fsync also
        advances the shipping watermark, and in ``--repl-ack sync`` mode
        the reply additionally waits for >= 1 follower append
        (``_repl_sync=False`` exempts internal lease traffic)."""
        if self.wal is not None:
            plan = self.chaos
            if plan is not None:
                fire_crash(plan, "crash.server.pre_fsync")
            self.wal.commit()
            if plan is not None:
                fire_crash(plan, "crash.server.post_fsync")
            if self.repl is not None:
                self.repl.on_commit()
                if _repl_sync:
                    self.repl.sync_wait()
        self._maybe_flush()

    def create(self, kind: str, data: Dict[str, Any],
               _encode_response: bool = True):
        obj = decode_object(kind, data.get("object", {}))
        if kind == "Job" and self.admission:
            from volcano_tpu.admission import mutate_job, validate_job

            obj = mutate_job(obj)
            ok, msg = validate_job(obj)
            if not ok:
                return 422, {"error": msg}
        with self.lock:
            if self.store.get(kind, obj.meta.key) is not None:
                return 409, {"error": f"{kind} {obj.meta.key} already exists"}
            self.store.create(kind, obj)
            if self.wal is not None:
                effectsan.note_mutate("StoreServer.create")
            if kind != "Job":  # admission may have mutated a Job
                self._stage_enc_hint(kind, obj, data.get("object"))
            self._pump_log()
            if self.wal is not None:
                self._wal_append({
                    "op": "create", "kind": kind,
                    "object": self._restamped_enc(
                        obj, data.get("object") if kind != "Job" else None),
                })
        # bulk discards per-op bodies — a full object encode per op was a
        # third of the server-side cost of a 100k-op batch
        return 201, {"object": encode(obj)} if _encode_response else {}

    def update(self, kind: str, data: Dict[str, Any],
               expected_rv: Optional[int] = None):
        obj = decode_object(kind, data.get("object", {}))
        with self.lock:
            old = self.store.get(kind, obj.meta.key)
            if old is None:
                return 404, {"error": f"{kind} {obj.meta.key} not found"}
            if expected_rv is not None and old.meta.resource_version != expected_rv:
                return 409, {
                    "error": f"{kind} {obj.meta.key}: stale resource_version "
                             f"(expected {expected_rv}, have "
                             f"{old.meta.resource_version})",
                    "conflict": True,
                }
            if kind == "Job" and self.admission:
                from volcano_tpu.admission import validate_job_update

                ok, msg = validate_job_update(obj, old)
                if not ok:
                    return 422, {"error": msg}
            self.store.update(kind, obj)
            if self.wal is not None:
                effectsan.note_mutate("StoreServer.update")
            self._stage_enc_hint(kind, obj, data.get("object"))
            self._pump_log()
            if self.wal is not None:
                self._wal_append({
                    "op": "update", "kind": kind,
                    "object": self._restamped_enc(obj, data.get("object")),
                })
        return 200, {"object": encode(obj)}

    def patch(self, kind: str, key: str, fields: Dict[str, Any],
              when: Dict[str, Any] = None,
              _encode_response: bool = True):
        if kind == "Job" and self.admission:
            # spec-freeze admission compares whole objects; field patches
            # would bypass it — Jobs must go through PUT
            return 422, {"error": "patch is not supported on Job; use update"}
        with self.lock:
            try:
                obj = self.store.patch(
                    kind, key, decode_fields(kind, fields),
                    when=decode_fields(kind, when) if when else None,
                )
            except KeyError as e:
                # NotFound: prefix = the structured vanished-object marker
                # bulk callers match (same contract as Store.bulk)
                return 404, {"error": f"NotFound: {e}"}
            except PreconditionFailed as e:
                return 409, {"error": repr(e)}
            if self.wal is not None:
                effectsan.note_mutate("StoreServer.patch")
            self._pump_log()
            if self.wal is not None:
                rec = {"op": "patch", "kind": kind, "key": key,
                       "fields": fields}
                if when:
                    rec["when"] = when
                self._wal_append(rec)
        return 200, {"object": encode(obj)} if _encode_response else {}

    def bulk(self, ops: List[Dict[str, Any]]) -> List[Optional[str]]:
        """Batched mutations: one HTTP round trip for N ops (the server half
        of async decision application — see Store.bulk for the op shapes;
        objects arrive encoded). Per-op admission still applies. The lock is
        reentrant, so holding it across the batch while delegating to
        create/update keeps the batch contiguous in the event log."""
        if len(ops) == 1 and ops[0].get("op") == "segment":
            # the partitioned bus's hot shape (the applier ships each
            # sub-segment as its own single-op bulk): skip the batch
            # wrapper's global lock — the apply manages its own
            # shard-then-server locking (see _apply_segment for the
            # honest concurrency model: applies still serialize on the
            # server lock; the overlap is decode/encode/fsync)
            try:
                results = [self._apply_segment(ops[0])]
            except Exception as e:  # noqa: BLE001 — per-op isolation
                results = [repr(e)]
            self._commit_ack()
            return results
        results: List[Optional[str]] = []
        with self.lock:
            for op in ops:
                try:
                    verb = op.get("op")
                    kind = op.get("kind", "")
                    if verb == "create":
                        code, payload = self.create(
                            kind, {"object": op.get("object", {})},
                            _encode_response=False,
                        )
                        ok = code == 201
                    elif verb == "update":
                        code, payload = self.update(
                            kind, {"object": op.get("object", {})},
                            expected_rv=op.get("cas"),
                        )
                        ok = code == 200
                    elif verb == "patch":
                        code, payload = self.patch(
                            kind, op.get("key", ""), op.get("fields") or {},
                            when=op.get("when"),
                            _encode_response=False,
                        )
                        ok = code == 200
                    elif verb == "patch_col":
                        # columnar patch run (RemoteStore._compress_patch_runs):
                        # result is a per-key LIST the client re-flattens
                        results.append(self._patch_col(op))
                        continue
                    elif verb == "segment":
                        # columnar decision segment (store/segment.py):
                        # result is the sparse per-row error dict.  The
                        # batch already holds the server lock, which
                        # covers every shard — skip the shard lock so
                        # the lock ORDER (shard before server) stays
                        # acyclic (single-op segment bulks take the
                        # fast path above instead)
                        results.append(self._apply_segment(op, _in_bulk=True))
                        continue
                    elif verb == "delete":
                        self._bulk_delete(kind, op.get("key", ""))
                        ok, payload = True, {}
                    else:
                        ok, payload = False, {"error": f"unknown bulk op {verb!r}"}
                    results.append(None if ok else payload.get("error", "failed"))
                except Exception as e:  # noqa: BLE001 — per-op isolation
                    results.append(repr(e))
        self._commit_ack()
        return results

    def _bulk_delete(self, kind: str, key: str) -> None:
        """One bulk delete op, mutation through WAL append in a single
        call frame: the batch loop's per-op isolation swallows exceptions
        and then acks the batch, so the mutate→append window must not
        straddle statements of the loop body (wal-effect-order) — inlined
        there, a `_pump_log` failure would leave the delete in memory,
        unlogged, and acked."""
        deleted = self.store.delete(kind, key)
        if deleted is not None and self.wal is not None:
            effectsan.note_mutate("StoreServer._bulk_delete")
        self._pump_log()
        if deleted is not None and self.wal is not None:
            self._wal_append({"op": "delete", "kind": kind, "key": key})

    def _patch_col(self, op: Dict[str, Any]) -> List[Optional[str]]:
        """Expand one columnar patch op: shared kind/field-shape/when, a
        keys array, per-field value columns and/or constants.  Field
        decoders resolve ONCE for the whole run; values are scalars by the
        client's compression contract (enums decode to immutable members),
        so no decoded object is ever shared across rows."""
        kind = op.get("kind", "")
        keys = op.get("keys") or []
        if kind == "Job" and self.admission:
            return ["patch is not supported on Job; use update"] * len(keys)
        cols = op.get("columns") or {}
        const_enc = op.get("const") or {}
        when = op.get("when")
        const = decode_fields(kind, const_enc) if const_enc else {}
        when_dec = decode_fields(kind, when) if when else None
        col_dec = self._col_decoders(kind, cols)
        out: List[Optional[str]] = []
        with self.lock:
            for i, key in enumerate(keys):
                try:
                    fields = dict(const)
                    for f, vals in cols.items():
                        fields[f] = col_dec[f](vals[i])
                    self.store.patch(kind, key, fields, when=when_dec)
                    if self.wal is not None:
                        effectsan.note_mutate("StoreServer._patch_col")
                    out.append(None)
                except KeyError as e:
                    out.append(f"NotFound: {e}")
                except Exception as e:  # noqa: BLE001 — per-key isolation
                    out.append(repr(e))
            self._pump_log()
            if self.wal is not None:
                # ONE record for the whole columnar run, wire-form
                # verbatim; per-key failures replay to the same outcome
                self._wal_append({
                    k: op[k]
                    for k in ("op", "kind", "keys", "columns", "const",
                              "when") if k in op
                })
        return out

    @staticmethod
    def _col_decoders(kind: str, cols) -> Dict[str, Any]:
        """Per-field decoders for a columnar patch run, resolved once
        (shared by the live ``patch_col`` verb and WAL replay)."""
        from volcano_tpu.store.codec import _decoder, _resolve_hint

        cls = KIND_CLASSES.get(kind)
        col_dec: Dict[str, Any] = {}
        for f in cols:
            hint = _resolve_hint(cls, f) if cls is not None else None
            col_dec[f] = _decoder(hint) if hint is not None else (lambda v: v)
        return col_dec

    def _apply_segment(self, op: Dict[str, Any],
                       _in_bulk: bool = False,
                       stamp: Optional[float] = None) -> Dict[str, Any]:
        """Apply one columnar decision segment: the whole cycle's binds,
        evicts, and their Events land under ONE lock acquisition, with no
        per-object store write, object encode, or log entry.  The store
        stages the rows lazily (Store.apply_segment_lazy); this side
        appends one log BLOCK per segment section — the block is both the
        watch encoding (expanded lazily, shared by all watchers) and the
        encoded-object cache entry for every key it covers (_enc_of).
        Atomicity: the segment applies entirely inside the lock or not at
        all — chaos faults on the request fire before dispatch, so a cut
        reply can never leave a half-applied segment.  Never flushes
        inline (the bulk wrapper's _maybe_flush runs outside the lock,
        preserving the _flush_lock-before-lock order)."""
        from contextlib import nullcontext

        from volcano_tpu.store.segment import DecisionSegment, PatchLogBlock

        seg = DecisionSegment.from_wire(op)
        # an UNTAGGED segment on a partitioned server (a pre-partition
        # client, or an applier whose /healthz probe transiently failed)
        # spans shards: it routes to shard 0 for locking/WAL durability,
        # but its log entries stay untagged so shard-scoped watchers of
        # EVERY shard receive its rows (over-delivery is safe; a
        # shard-0-only tag would leave the other shards' watchers
        # permanently stale with no relist signal)
        shard_tag = op.get("shard")
        shard = (int(shard_tag) % self.shards) if shard_tag is not None else 0
        # per-shard apply lock (partitioned bus): sub-segments of ONE
        # shard apply atomically in ship order.  Honest concurrency
        # model: the staging below still runs under the GLOBAL server
        # lock (seq/rv assignment, the shared enc caches, the log), so
        # different shards' APPLIES serialize — cross-shard overlap
        # happens in what is OUTSIDE both locks: each handler thread's
        # request decode/reply encode, socket I/O, and the per-shard
        # group-commit fsync in _commit_ack (independent WAL files).
        # The shard lock is the seam for narrowing the global section
        # later without changing callers.  Order: shard lock strictly
        # BEFORE the server lock (lock-order contract); a multi-op bulk
        # already holds the server lock — which covers every shard — so
        # it skips the shard lock (``_in_bulk``) rather than inverting
        # the order.
        shard_lock = (
            nullcontext() if _in_bulk else self._shard_locks[shard]
        )
        with shard_lock, self.lock:
            # queued per-object events must keep their place in the order
            self._pump_log()
            # stamp override: a follower replaying a shipped segment
            # reuses the leader's recorded stamp, so its Events (and the
            # watch stream built from them) are byte-identical
            if stamp is None:
                stamp = time.time()
            res = self.store.apply_segment_lazy(seg, stamp=stamp)
            if self.wal is not None:
                effectsan.note_mutate("StoreServer._apply_segment")
            plan = self.chaos
            if plan is not None:
                # seeded kill between store apply and log/WAL append: the
                # in-memory half dies with the process, the WAL never saw
                # the record, the client never saw a reply — recovery must
                # show NO trace of the segment (atomicity under crash)
                fire_crash(plan, "crash.server.segment_apply")
            bkeys, bvals, rv_b0 = res.pop("bind_block")
            ekeys, rv_e0 = res.pop("evict_block")
            ebind, eevict = res.pop("event_blocks")
            pend = self._enc_pending
            if bkeys:
                pre = [self._enc_pre("Pod", k) for k in bkeys]
                blk = PatchLogBlock("node_name", bkeys, bvals, pre, rv_b0)
                self._append_block(blk, shard_tag)
                for i, k in enumerate(bkeys):
                    pend[("Pod", k)] = (blk, i)
                self._dirty_kinds.add("Pod")
            if ekeys:
                pre = [self._enc_pre("Pod", k) for k in ekeys]
                blk = PatchLogBlock(
                    "deleting", ekeys, [True] * len(ekeys), pre, rv_e0
                )
                self._append_block(blk, shard_tag)
                for i, k in enumerate(ekeys):
                    pend[("Pod", k)] = (blk, i)
                self._dirty_kinds.add("Pod")
            for blk in (ebind, eevict):
                if len(blk):
                    self._append_block(blk, shard_tag)
                    for i in range(len(blk)):
                        pend[("Event", blk.key(i))] = (blk, i)
                    self._dirty_kinds.add("Event")
            if self.repl is None:
                # repl leaders beacon AFTER the ship (_wal_append below):
                # stamped here the beacon's digest already covers the
                # segment but ships ahead of its record — a guaranteed
                # false divergence on every follower
                self._maybe_beacon()
            self._trim_log()
            if self.wal is not None:
                # the WHOLE cycle is one WAL record — the wire op verbatim
                # plus the Event stamp, so replay reproduces the exact
                # lazy apply (group commit then amortizes one fsync over
                # 100k binds in _commit_ack); the shard tag rides along so
                # a partitioned bus appends it to that shard's WAL
                rec = dict(op)
                rec["stamp"] = stamp
                rec["shard"] = shard
                self._wal_append(rec)
            self.cond.notify_all()
        return res

    def _append_block(self, blk, shard=None) -> None:
        """One log entry for a whole columnar block; rows occupy the seq
        range (blk.seq0 .. entry["seq"]).  On a partitioned server the
        entry carries its shard so ``/watch?shard=i`` fan-out serves (and
        expands) only that shard's blocks; ``shard=None`` (an untagged,
        cross-shard segment) leaves the entry untagged — served to every
        shard-scoped watcher."""
        n = len(blk)
        seq = self._alloc_seq(n)
        blk.seq0 = seq - n + 1
        self._log_rows += n
        entry = {"seq": seq, "n": n, "kind": blk.kind,
                 "block": blk, "start": 0}
        if self.shards > 1 and shard is not None:
            entry["shard"] = int(shard) % self.shards
            self._note_watermark(entry["shard"], seq)
        else:
            # untagged (cross-shard) block: every shard's stream carries
            # it, so each stream receives a watermark record — "your
            # stream is complete through seq".  The record set is the
            # broadcast protocol itself: a procmesh shard process hosts
            # exactly ONE stream (the set degenerates to its own mark;
            # siblings' marks live in the router's aggregation, fed by
            # the hwm stamp on each shard's watch/feed replies), while
            # the in-process bus hosts all of them and delivers locally.
            for mark in self._watermark_records(seq):
                self._note_watermark(mark["shard"], mark["seq"])
        self.log.append(entry)

    def _watermark_records(self, seq: int):
        """Per-shard watermark records broadcast by an untagged
        (cross-shard) log entry: one message per shard stream THIS
        process hosts, each meaning "shard's stream is complete through
        ``seq``"."""
        return [{"shard": s, "seq": seq} for s in range(self.shards)]

    def _note_watermark(self, shard: int, seq: int) -> None:
        """Process one per-shard watermark record (monotone max — a
        record may be re-delivered or arrive late).  The /healthz skew
        surface and digest_debug read the resulting marks."""
        marks = self._shard_seq
        s = int(shard) % len(marks)
        if seq > marks[s]:
            marks[s] = seq

    def _alloc_seq(self, n: int) -> int:
        """Consume ``n`` log sequence numbers and return the LAST one.
        Callers hold ``self.lock``, so allocation and the log append it
        covers are atomic per shard process: once a procmesh sibling
        observes the shared counter at S, every seq <= S owned by THIS
        shard is already appended here — the invariant that makes
        ``_seq_hwm``-stamped watch replies a sound completeness
        watermark."""
        bus = self._seq_bus
        if bus is not None:
            self.seq = bus.alloc_seq(n)
        else:
            self.seq += n
        return self.seq

    def _seq_hwm(self) -> int:
        """The global-seq high-water mark this server can stamp on a
        watch reply as "my stream is complete through here".  Dense
        servers: the local tail.  Procmesh shards: the shared counter's
        current value — seqs between the local tail and the counter
        belong to sibling shards (see ``_alloc_seq``)."""
        hwm = self.seq
        bus = self._seq_bus
        if bus is not None:
            peek = bus.peek_seq()
            if peek > hwm:
                hwm = peek
        return hwm

    # -- digest beacons / audit surface (vtaudit) --------------------------

    def _maybe_beacon(self) -> bool:
        """Stamp a digest beacon if one is due (caller holds the server
        lock).  Preconditions keep the beacon coherent with the log:
        auditing armed, seq advanced since the last beacon, the cadence
        interval elapsed, and every store watch queue already drained —
        a beacon stamped ahead of unpumped events would pin a digest the
        log cannot yet reproduce, a false divergence for every verifier."""
        if self.store._digest is None:
            return False
        if self.repl is not None and self.repl.role != "leader":
            # followers never stamp their own beacons: the leader's ship
            # as feed records and the follower mirrors them at the SAME
            # seq — a locally stamped one would fork the seq line
            return False
        if self.seq == self._beacon_seq:
            return False
        if time.monotonic() - self._beacon_mono < vtaudit.beacon_interval_s():
            return False
        if any(self._queues.values()):
            return False
        return self.stamp_beacon()

    def stamp_beacon(self) -> bool:
        """Append a seq-pinned digest beacon entry to the event log
        (caller holds the server lock; lock order server.lock -> _mu is
        the contract, so reading the store digest here is safe).  The
        beacon consumes one seq and one log row like any entry, so watch
        cursors move past it normally.  It is deliberately NOT WAL'd:
        after a crash the digest is re-derivable from recovered state,
        and watch_since's ``since > seq`` relist check absorbs the seq
        regression a lost beacon leaves behind."""
        payload = self.store.digest_payload(self.shards)
        if payload is None:
            return False
        self._alloc_seq(1)
        self._log_rows += 1
        ts = time.time()
        self.log.append(vtaudit.beacon_entry(self.seq, payload, ts))
        if self.repl is not None:
            # ship the beacon as a synthetic feed record: it consumed a
            # seq, so followers must consume the same one — and mirror
            # the digest for divergence detection (store/replica.py)
            self.repl.log_beacon(self.seq, payload, ts)
        self._beacon_seq = self.seq
        self._beacon_mono = time.monotonic()
        self.cond.notify_all()
        return True

    def digest_debug(self, q: Dict[str, List[str]]) -> Dict[str, Any]:
        """``/debug/digest`` payload (chaos-exempt).  Default: root +
        per-shard rollups pinned to the server seq.  ``?detail=buckets``
        (optionally ``&shard=i``): per-``(kind, namespace)`` bucket
        digests — the localization walk's second rung.  ``?kind=K&
        namespace=NS``: per-object digests for one bucket — the final
        rung, naming the exact diverged objects.  ``recompute=1`` on any
        tier serves a ground-truth re-encode of the RAW objects instead
        of the incrementally maintained table — the auditor's reference
        for localizing corruption that bypassed the mutation verbs (a
        flipped byte in object state never updates the maintained
        digest, so maintained-vs-recompute names the exact object)."""
        with self.lock:
            self._pump_log()
            rec = (q.get("recompute") or [None])[0] not in (None, "", "0")
            t = self.store.recompute_digest() if rec else None
            kind = (q.get("kind") or [None])[0]
            if kind is not None:
                ns = (q.get("namespace") or [""])[0]
                objs = (t.object_payload(kind, ns) if t is not None
                        else self.store.digest_objects(kind, ns))
                return {"seq": self.seq, "kind": kind, "namespace": ns,
                        "recompute": rec, "objects": objs}
            sh = (q.get("shard") or [None])[0]
            if (q.get("detail") or [None])[0] == "buckets" or sh is not None:
                shard = int(sh) if sh is not None else None
                buckets = (
                    t.bucket_payload(shard, self.shards) if t is not None
                    else self.store.digest_buckets(shard, self.shards)
                )
                return {"seq": self.seq, "recompute": rec,
                        "buckets": buckets}
            payload = (t.payload(self.shards) if t is not None
                       else self.store.digest_payload(self.shards))
            out: Dict[str, Any] = {
                "enabled": self.store._digest is not None,
                "seq": self.seq,
                "recompute": rec,
                "shard_seq": list(self._shard_seq),
            }
            if payload is not None:
                out.update(payload)
            return out

    def _enc_of(self, kind: str, key: str) -> Optional[Dict[str, Any]]:
        """The object's current encoding, resolving the lazy columnar half
        of the cache on first read (memoized on the block, so N readers
        pay one materialization)."""
        ck = (kind, key)
        p = self._enc_pending.pop(ck, None)
        if p is not None:
            blk, i = p
            self._obj_enc[ck] = blk.materialize_enc(i)
        return self._obj_enc.get(ck)

    def _enc_pre(self, kind: str, key: str) -> Dict[str, Any]:
        """Pre-segment encoding of ``key`` — the delta basis (and the
        ``old`` reference) for a block row about to cover it.  Reads the
        raw store object on a cache miss (never Store.get: that would
        fold the very rows this segment just staged)."""
        enc = self._enc_of(kind, key)
        if enc is None:
            enc = encode(self.store._objects[kind][key])
            self._obj_enc[(kind, key)] = enc
        return enc

    def _trim_log(self) -> None:
        """Evict the oldest rows past LOG_CAP.  A block straddling the
        horizon is kept with its ``start``/``n`` advanced (a shallow copy
        of the entry — the block itself is shared with any slower
        reader mid-expansion)."""
        overflow = self._log_rows - LOG_CAP
        if overflow <= 0:
            return
        k = 0
        log = self.log
        while overflow > 0 and k < len(log):
            e = log[k]
            n = e.get("n", 1)
            if n <= overflow:
                overflow -= n
                self._log_rows -= n
                self._log_floor = e["seq"]
                k += 1
            else:
                e2 = dict(e)
                e2["n"] = n - overflow
                e2["start"] = e.get("start", 0) + overflow
                log[k] = e2
                self._log_rows -= overflow
                # block rows are seq-dense ending at e["seq"]: the newest
                # trimmed row is first_row + overflow - 1
                self._log_floor = e["seq"] - n + overflow
                overflow = 0
        if k:
            del log[:k]

    # -- persistence -----------------------------------------------------------

    def _load_state(self) -> None:
        """Recovery: load the snapshot, then replay the WAL tail on top
        (torn-tail tolerant — see store/wal.py).  Emits a ``store.recover``
        span when tracing is armed so crash_dump artifacts carry the
        recovery timeline."""
        if trace.TRACER is None:
            self._recover()
            return
        with trace.span("store.recover", path=self.state_path) as sp:
            replayed, skipped = self._recover()
            sp.annotate(
                replayed=replayed, skipped=skipped,
                torn_tails=self.wal.torn_tails if self.wal else 0,
            )

    def _recover(self):
        import os

        data = {}
        if os.path.exists(self.state_path):
            with open(self.state_path) as f:
                data = json.load(f)
        self._load_snapshot(data)
        replayed = skipped = 0
        if self.wal is not None:
            if data and "wal_floor" not in data:
                # lineage guard: a WAL-ON life always stamps a floored
                # checkpoint before serving (below), so a snapshot
                # WITHOUT a floor was written by a WAL-OFF life — any
                # leftover segments predate it, and replaying them would
                # resurrect old field values and deleted objects on top
                # of the newer state
                self.wal.drop_all()
                # ... including segments in layouts this life's WAL does
                # not own (a shard-count change ago): they predate the
                # WAL-off snapshot too and must not be absorbed later
                self._drop_foreign_wal(data)
            else:
                replayed, skipped = self._replay_wal(data)
                if replayed:
                    from volcano_tpu.scheduler import metrics

                    metrics.register_wal_recovery(replayed)
            if data and "wal_floor" not in data:
                # stamp the floor NOW, before any request is served, so
                # "snapshot without wal_floor + segments present" stays a
                # definitive staleness signal even if this life crashes
                # before its first interval flush (forced: an inherited
                # snapshot whose kinds are all empty still needs the
                # floor, or its crash window would drop_all acked
                # segments on the next boot)
                self._dirty_kinds.update(data.get("kinds", {}))
                self.flush_state(force=True)
        elif self.state_path is not None:
            replayed, skipped = self._absorb_leftover_wal(data)
        return replayed, skipped

    def _wal_floor_of(self, data):
        """The snapshot's WAL floor in the shape THIS life's WAL speaks:
        an int for the single log, a per-shard list for the partitioned
        bus.  A floor stamped by a life with a different shard count is
        coerced conservatively (floor 0 = replay everything; records
        replay idempotently over the snapshot, same as the absorb path)."""
        floor = data.get("wal_floor", 0)
        sharded_wal = getattr(self.wal, "nshards", 1) > 1
        if sharded_wal:
            return floor if isinstance(floor, list) else 0
        if isinstance(floor, list):
            # partitioned-life snapshot booted unsharded: this life's
            # fresh single log has no covered segments — replay all
            return 0
        return int(floor)

    def _absorb_leftover_wal(self, data):
        """WAL-OFF boot with leftover WAL segments beside the state file:
        a previous WAL-on life crashed with acked-but-uncheckpointed
        records in its tail, and dropping to interval persistence must
        not silently lose them.  Replay the tail (same torn-tail
        semantics), snapshot immediately so the absorbed records are
        durable again, then retire the segments — a later WAL-on boot
        starts from a clean directory."""
        import os

        from volcano_tpu.store import wal as walmod
        from volcano_tpu.store.partition import leftover_shard_dirs

        wal_dir = self.state_path + ".wal"
        floor_raw = data.get("wal_floor", 0)
        floors = floor_raw if isinstance(floor_raw, list) else []
        flat_floor = int(floor_raw) if not isinstance(floor_raw, list) else 0
        # a crashed PARTITIONED WAL-on life leaves per-shard subdirs; a
        # single-log life leaves *.wal at the top level — absorb both,
        # merging shard tails into global order by their seq stamps
        shard_dirs = leftover_shard_dirs(wal_dir)
        sources = [(wal_dir, flat_floor)] + [
            (d, int(floors[i]) if i < len(floors) else 0)
            for i, d in enumerate(shard_dirs)
        ]
        pending = []  # (seq, tiebreak, rec)
        tie = 0
        seg_paths = []  # every leftover segment file (reaped below)
        for src_dir, floor in sources:
            indices = walmod.list_segment_indices(src_dir)
            for idx in indices:
                path = os.path.join(src_dir, f"{idx:08d}.wal")
                seg_paths.append((src_dir, path))
                if idx < floor:
                    continue  # covered by the snapshot: reap, don't replay
                records, _torn = walmod.read_records(path)
                for rec in records:
                    tie += 1
                    pending.append((int(rec.get("seq", 0)), tie, rec))
        if not seg_paths:
            return 0, 0
        pending.sort(key=lambda t: (t[0], t[1]))
        replayed = skipped = 0
        for seq, _, rec in pending:
            replayed += 1
            try:
                self._replay_record(rec)
            except Exception:  # noqa: BLE001 — recovery must not die
                skipped += 1
            if "seq" in rec:
                self.seq = max(self.seq, int(rec["seq"]))
            if "rv" in rec:
                self.store._rv = max(self.store._rv, int(rec["rv"]))
        if replayed:
            from volcano_tpu.scheduler import metrics

            metrics.register_wal_recovery(replayed)
        # make the absorbed tail durable BEFORE the segments die; a crash
        # in between re-absorbs idempotently on the next boot
        self.flush_state(force=True)
        touched = set()
        for src_dir, path in seg_paths:
            try:
                os.unlink(path)
            except OSError:
                pass
            touched.add(src_dir)
        for src_dir in touched:
            walmod.fsync_dir(src_dir)
        return replayed, skipped

    def _load_snapshot(self, data) -> None:
        max_rv = 0
        for kind, items in data.get("kinds", {}).items():
            if kind not in KIND_CLASSES:
                continue  # state written by a newer version; skip unknown
            # seed the encoded cache with the loaded payload: the
            # incremental flush only re-encodes dirtied kinds and builds
            # the file from this cache, so an unseeded kind would be
            # DROPPED from the state file by the first post-restart flush
            self._enc_cache[kind] = list(items)
            for enc in items:
                obj = decode_object(kind, enc)
                # seed the per-object cache too: the first post-restart
                # segment captures its delta bases here (_enc_pre) — an
                # unseeded key would pay a full encode() per object under
                # the server lock, the per-object cliff the segment path
                # exists to avoid
                self._obj_enc[(kind, obj.meta.key)] = enc
                rv = obj.meta.resource_version
                self.store.create(kind, obj)
                # create stamps a fresh rv; restore the persisted one on
                # BOTH the live object and the store's no-op-suppression
                # shadow copy, or the first unchanged write-back after a
                # restart would fan out a phantom UPDATED event
                obj.meta.resource_version = rv
                shadow = self.store._shadow[kind].get(obj.meta.key)
                if shadow is not None:
                    shadow.meta.resource_version = rv
                max_rv = max(max_rv, rv)
        # future writes continue the persisted version sequence so CAS
        # (leases) and epoch caches stay monotonic across restarts; the
        # explicit "rv" stamp (newer snapshots) is exact even when deleted
        # objects consumed the highest versions
        self.store._rv = max(self.store._rv, max_rv, int(data.get("rv", 0)))
        self.seq = int(data.get("seq", 0))
        # a restarted server IS the same store lineage: restore the uid so
        # mirror checkpoints taken before the restart stay valid
        uid = data.get("store_uid")
        if uid:
            self.store.uid = uid
        # replication epoch continuity (store/replica.py): a booting
        # leader bumps past this; a follower resumes its feed under it
        self._snap_repl_epoch = int(data.get("repl_epoch", 0))
        # note: the reload happens before any watch queue is registered, so
        # the synthetic creations produce no events — clients relist

    def _foreign_wal_sources(self, data):
        """``[(dir, floor)]`` for WAL segment locations a SHARD-COUNT
        CHANGE orphaned: acked records this life's WAL layout does not
        own.  A single-log life owns the top level and orphans every
        shard subdir; an N-shard life owns ``s00..s{N-1}`` and orphans
        the top level plus any higher-indexed shard dirs from a wider
        previous life.  Floors come from the snapshot's ``wal_floor`` in
        the shape the ORPHANING life stamped them (list entry i for
        ``s{i}``, the scalar for the top level); an orphaned location
        with no matching floor entry replays from 0 — its records apply
        over the snapshot exactly like the absorb path's."""
        import os

        from volcano_tpu.store.partition import leftover_shard_dirs

        wal_dir = self.wal.dir
        nshards_now = getattr(self.wal, "nshards", 1)
        floor_raw = data.get("wal_floor", 0) if data else 0
        floors = floor_raw if isinstance(floor_raw, list) else []
        flat = int(floor_raw) if not isinstance(floor_raw, list) else 0
        sources = []
        shard_dirs = leftover_shard_dirs(wal_dir)
        if nshards_now == 1:
            for d in shard_dirs:
                i = int(os.path.basename(d)[1:])
                sources.append((d, int(floors[i]) if i < len(floors) else 0))
        else:
            sources.append((wal_dir, flat))
            for d in shard_dirs:
                i = int(os.path.basename(d)[1:])
                if i >= nshards_now:
                    sources.append(
                        (d, int(floors[i]) if i < len(floors) else 0))
        return sources

    def _drop_foreign_wal(self, data) -> None:
        """Unlink orphaned-layout segments wholesale (the WAL-off-
        snapshot lineage rule: they predate the newest snapshot)."""
        import os

        from volcano_tpu.store import wal as walmod

        for src_dir, _floor in self._foreign_wal_sources(data):
            dropped = False
            for idx in walmod.list_segment_indices(src_dir):
                try:
                    os.unlink(os.path.join(src_dir, f"{idx:08d}.wal"))
                    dropped = True
                except OSError:
                    pass
            if dropped:
                walmod.fsync_dir(src_dir)

    def _replay_wal(self, data):
        """Replay the WAL tail through the store verbs: this life's own
        layout (segments >= the snapshot's floor) MERGED by seq stamp
        with any orphaned-layout tail a shard-count change left behind
        (a ``--shards 4`` life's acked records must survive a
        ``--shards 1`` reboot and vice versa — the zero-acked-loss
        contract does not care how the operator re-partitioned).
        Orphaned segments are absorbed: replayed, snapshotted durable,
        then retired.  Runs before any watch queue exists, so like the
        snapshot load it produces no events — clients behind the crash
        relist.  Returns (replayed, skipped): a record that cannot
        apply (version-drift field, vanished key) is skipped and
        counted, never fatal — recovery must always come up."""
        import os

        from volcano_tpu.store import wal as walmod

        floor = self._wal_floor_of(data)
        pending = []  # (seq, tiebreak, rec)
        tie = 0
        for rec in self.wal.replay(floor):
            tie += 1
            pending.append((int(rec.get("seq", 0)), tie, rec))
        foreign_files = []
        for src_dir, src_floor in self._foreign_wal_sources(data):
            for idx in walmod.list_segment_indices(src_dir):
                path = os.path.join(src_dir, f"{idx:08d}.wal")
                foreign_files.append((src_dir, path))
                if idx < src_floor:
                    continue  # covered by the snapshot: reap, don't replay
                records, _torn = walmod.read_records(path)
                for rec in records:
                    tie += 1
                    pending.append((int(rec.get("seq", 0)), tie, rec))
        pending.sort(key=lambda t: (t[0], t[1]))
        replayed = skipped = 0
        for _, _, rec in pending:
            replayed += 1
            try:
                self._replay_record(rec)
            except Exception:  # noqa: BLE001 — recovery must never crash
                skipped += 1
            # continuity stamps: the recovered server resumes the exact
            # seq/rv line the record was ACKed under, so pre-crash watch
            # cursors relist (seq moved past them -> empty-log relist)
            # and CAS holders keep working
            if "seq" in rec:
                self.seq = max(self.seq, int(rec["seq"]))
            if "rv" in rec:
                self.store._rv = max(self.store._rv, int(rec["rv"]))
        if foreign_files:
            # make the absorbed foreign tail durable, then retire it —
            # a crash in between re-absorbs idempotently next boot
            self.flush_state(force=True)
            touched = set()
            for src_dir, path in foreign_files:
                try:
                    os.unlink(path)
                except OSError:
                    pass
                touched.add(src_dir)
            for src_dir in touched:
                walmod.fsync_dir(src_dir)
        return replayed, skipped

    def _replay_record(self, rec: Dict[str, Any]) -> None:
        """Apply one WAL record — the wire form of the op, replayed with
        the recorded server-stamped meta (same dance as the snapshot
        load: rv restored on the object AND its no-op-suppression
        shadow)."""
        op = rec.get("op")
        kind = rec.get("kind", "")
        store = self.store
        if op in ("create", "update"):
            enc = rec["object"]
            obj = decode_object(kind, enc)
            rv = obj.meta.resource_version
            try:
                if op == "create":
                    store.create(kind, obj)
                else:
                    store.update(kind, obj)
            except KeyError:
                # a create landing on an existing key (or update on a
                # vanished one) can only mean the snapshot already
                # reflects a later life of this key; converge on the
                # record's object either way
                if op == "create":
                    store.update(kind, obj)
                else:
                    store.create(kind, obj)
            obj.meta.resource_version = rv
            shadow = store._shadow[kind].get(obj.meta.key)
            if shadow is not None:
                shadow.meta.resource_version = rv
            self._obj_enc[(kind, obj.meta.key)] = enc
            self._dirty_kinds.add(kind)
        elif op == "patch":
            when = rec.get("when")
            try:
                store.patch(
                    kind, rec["key"],
                    decode_fields(kind, rec.get("fields") or {}),
                    when=decode_fields(kind, when) if when else None,
                )
            except (KeyError, PreconditionFailed):
                pass  # replays exactly as it resolved live
            self._obj_enc.pop((kind, rec["key"]), None)
            self._dirty_kinds.add(kind)
        elif op == "patch_col":
            cols = rec.get("columns") or {}
            const_enc = rec.get("const") or {}
            when = rec.get("when")
            const = decode_fields(kind, const_enc) if const_enc else {}
            when_dec = decode_fields(kind, when) if when else None
            col_dec = self._col_decoders(kind, cols)
            for i, key in enumerate(rec.get("keys") or []):
                fields = dict(const)
                for f, vals in cols.items():
                    fields[f] = col_dec[f](vals[i])
                try:
                    store.patch(kind, key, fields, when=when_dec)
                except (KeyError, PreconditionFailed):
                    pass
                self._obj_enc.pop((kind, key), None)
            self._dirty_kinds.add(kind)
        elif op == "delete":
            store.delete(kind, rec["key"])
            self._obj_enc.pop((kind, rec["key"]), None)
            self._dirty_kinds.add(kind)
        elif op == "segment":
            from volcano_tpu.store.segment import DecisionSegment

            seg = DecisionSegment.from_wire(rec)
            store.apply_segment_lazy(seg, stamp=rec.get("stamp"))
            # snapshot-seeded encodings for the touched keys are now
            # stale: drop them so reads re-encode post-segment truth
            for k in seg.bind_keys:
                self._obj_enc.pop(("Pod", k), None)
            for k in seg.evict_keys:
                self._obj_enc.pop(("Pod", k), None)
            self._dirty_kinds.update(("Pod", "Event"))

    def snapshot_payload(self) -> Dict[str, Any]:
        """Full-state snapshot for a follower resync (``/repl/feed``
        epoch mismatch or a cursor below the retained feed horizon) —
        the same shape ``flush_state`` persists, built from the live
        encoded caches without touching the flush's dirty-kind
        bookkeeping (serving a snapshot must not affect checkpoints)."""
        with self.lock:
            self._pump_log()
            kinds: Dict[str, List[Any]] = {}
            enc_of = self._enc_of
            for kind in KIND_CLASSES:
                items = self.store.list(kind)
                if items:
                    kinds[kind] = [
                        enc_of(kind, o.meta.key) or encode(o)  # vtlint: disable=columnar-publish
                        for o in items
                    ]
            payload = {"seq": self.seq, "rv": self.store._rv,
                       "store_uid": self.store.uid, "kinds": kinds}
            if self.repl is not None:
                payload["repl_epoch"] = self.repl.epoch
            return payload

    def reset_from_snapshot(self, snap: Dict[str, Any]) -> None:
        """Follower resync: replace the entire store with the leader's
        snapshot.  Every cache, queue, and log entry belongs to the
        abandoned seq line, so everything resets; local watchers relist
        (their cursors are from another epoch) and the caller stamps a
        floored checkpoint so stale WAL segments never replay over the
        adopted state."""
        with self.lock:
            rv_alloc = self.store._rv_alloc
            self.store = Store()
            self.store._rv_alloc = rv_alloc
            self._queues = {}
            self.log = []
            self._log_rows = 0
            self.seq = 0
            self._enc_cache.clear()
            self._obj_enc.clear()
            self._enc_pending.clear()
            self._enc_hints.clear()
            self._dirty_kinds.clear()
            self._load_snapshot(snap)
            # everything the snapshot carries is dirty relative to the
            # state file: the next flush must persist every kind
            self._dirty_kinds.update(snap.get("kinds", {}))
            self._shard_seq = [self.seq] * self.shards
            self._log_floor = self.seq
            if self._seq_bus is not None:
                self._seq_bus.advance_to(self.seq, self.store._rv)
            self._beacon_seq = self.seq
            self._beacon_mono = time.monotonic()
            self._queues = {
                kind: self.store.watch(kind) for kind in KIND_CLASSES
            }
            self.cond.notify_all()

    def _saver_loop(self) -> None:
        interval = max(self.save_interval, 0.05)
        while not self._saver_stop.wait(interval):
            try:
                self.flush_state()
            except (OSError, ValueError):
                # a flush racing kill() (closed WAL/descriptor) or a
                # transient IO failure: the next interval retries — the
                # saver must not die and silently stop checkpointing
                continue

    def flush_state(self, force: bool = False) -> None:
        """Persist the store if dirty. Only kinds dirtied since the last
        flush re-encode (under the server lock); the file write happens
        outside it. The flush lock serializes whole flushes so concurrent
        saver/shutdown calls can neither interleave on the tmp file nor
        overwrite a fresher snapshot with a staler one.  ``force`` writes
        the snapshot even with nothing dirty — recovery uses it to stamp
        a ``wal_floor`` onto an inherited floorless (possibly empty)
        snapshot before any request is served."""
        if self.state_path is None or self._killed:
            return
        chaos = self.chaos
        if chaos is not None:
            rule = chaos.fire("server.flush")
            if rule is not None and rule.action == "drop_flush":
                # injected durability gap: acked writes stay dirty until
                # the next interval — the crash window the state-file
                # contract already documents, now testable on demand
                return
        with self._flush_lock:
            with self.lock:
                # drain any watch events queued by writes that bypassed the
                # API handlers (direct srv.store mutations, e.g. seeding a
                # default Queue at startup) so their kinds are dirtied and
                # persisted too
                self._pump_log()
                if not self._dirty_kinds and not force:
                    return
                # WAL checkpoint: rotate to a fresh segment INSIDE the
                # lock — every record appended so far lives below the
                # returned floor and is covered by the snapshot encoded
                # in this same critical section; records racing in after
                # the lock drops land at/above the floor and replay on
                # top of it
                floor = self.wal.rotate() if self.wal is not None else None
                for kind in self._dirty_kinds:
                    items = self.store.list(kind)  # materializes lazy rows
                    if items:
                        enc_of = self._enc_of
                        # encode(o) is the cache-MISS fallback only
                        # (direct-seeded objects); wire-fed objects all
                        # resolve through the columnar/encoded cache
                        self._enc_cache[kind] = [
                            enc_of(kind, o.meta.key) or encode(o)  # vtlint: disable=columnar-publish
                            for o in items
                        ]
                    else:
                        self._enc_cache.pop(kind, None)
                self._dirty_kinds.clear()
                payload = {"seq": self.seq, "rv": self.store._rv,
                           "store_uid": self.store.uid,
                           "kinds": dict(self._enc_cache)}
                if floor is not None:
                    payload["wal_floor"] = floor
                # persist the replication epoch (falling back to the
                # loaded stamp while recovery flushes run before the
                # Replicator exists); unreplicated snapshots stay
                # byte-compatible — no key at epoch 0
                repl_epoch = (self.repl.epoch if self.repl is not None
                              else self._snap_repl_epoch)
                if repl_epoch:
                    payload["repl_epoch"] = repl_epoch
            import os

            # crash-safe state write: temp file, fsync, atomic rename —
            # a crash at any instant leaves either the old snapshot or
            # the new one, never a torn file (vtlint: crash-safe-io)
            tmp = f"{self.state_path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.state_path)
            if floor is not None:
                from volcano_tpu.store.wal import fsync_dir

                # the rename itself must be durable before the covered
                # WAL segments die — a power loss must find either the
                # old snapshot + old segments or the new snapshot
                fsync_dir(os.path.dirname(os.path.abspath(self.state_path)))
                self.wal.drop_below(floor)
        if timeseries.RECORDER is not None:
            # store-side time-series sample, one per flush: event-log
            # position + WAL accounting, the server half of `vtctl top`
            repl_sample = None
            if self.repl is not None:
                st = self.repl.status()
                repl_sample = {"role": st["role"], "epoch": st["epoch"],
                               "applied": st["applied"]}
                if st["role"] == "leader":
                    fol = st["followers"]
                    repl_sample["followers"] = len(fol)
                    repl_sample["max_lag_rows"] = max(
                        (f["lag_rows"] for f in fol.values()), default=0)
                else:
                    repl_sample["lag_s"] = round(self.repl.lag_seconds(), 3)
            timeseries.record(
                "store", log_seq=self.seq, log_rows=self._log_rows,
                wal=self.wal.stats() if self.wal is not None else None,
                repl=repl_sample,
            )

    def _stage_enc_hint(self, kind: str, obj, wire: Optional[dict]) -> None:
        """Stage the request's own wire dict as the object's encoding for
        the imminent pump — the client's encode() output IS the canonical
        encoding of the decoded object, only the server-stamped meta
        fields differ.  Must be called under the server lock, after the
        store verb succeeded and before _pump_log."""
        if not wire:
            return
        self._enc_hints[(kind, obj.meta.key)] = self._restamped_enc(obj, wire)

    @staticmethod
    def _restamped_enc(obj, wire: Optional[dict]) -> Dict[str, Any]:
        """The post-verb canonical encoding of ``obj``: the request's own
        wire dict with the server-stamped meta fields overlaid, or a
        fresh encode when no wire dict applies (admission-mutated Jobs,
        direct-seeded objects).  Shared by the encoded-cache hints and
        the WAL create/update records."""
        if not wire:
            return encode(obj)
        enc = dict(wire)
        meta = dict(enc.get("meta") or {})
        meta["resource_version"] = obj.meta.resource_version
        meta["creation_timestamp"] = obj.meta.creation_timestamp
        meta["uid"] = obj.meta.uid
        enc["meta"] = meta
        return enc

    def _encode_event_obj(self, kind: str, ev) -> tuple:
        """(encoded_obj, encoded_old) for a store event, via the per-object
        encoded cache.  COW patch events (ev.fields set) apply the field
        delta onto the cached encoding — path hops shallow-copied, exactly
        the store's own shadow discipline — instead of re-encoding the full
        object: the full encode was 70%+ of the server-side cost of a
        100k-bind drain.  The pre-patch cache entry doubles as the event's
        ``old`` encoding (it is the shadow's encoding by construction)."""
        key = ev.obj.meta.key
        ck = (kind, key)
        cache = self._obj_enc
        if ev.type.value == "Deleted":
            self._enc_of(kind, key)  # resolve any lazy half first
            enc = cache.pop(ck, None)
            if enc is None:
                enc = encode(ev.obj)
            return enc, None
        if ev.fields is not None:
            enc_old = self._enc_of(kind, key)
            if enc_old is not None:
                try:
                    enc = dict(enc_old)
                    # the patch bumped the resource version on meta
                    meta = dict(enc["meta"])
                    meta["resource_version"] = ev.obj.meta.resource_version
                    enc["meta"] = meta
                    for k, v in ev.fields.items():
                        parts = k.split(".")
                        cur = enc
                        for p in parts[:-1]:
                            child = dict(cur[p])
                            cur[p] = child
                            cur = child
                        cur[parts[-1]] = encode(v)
                except (KeyError, TypeError):
                    # cached encoding lacks a path hop (e.g. seeded from a
                    # hand-built client dict omitting an optional subtree):
                    # fall back to a full re-encode rather than losing the
                    # event
                    pass
                else:
                    cache[ck] = enc
                    return enc, enc_old
        hint = self._enc_hints.pop(ck, None)
        if hint is not None:
            enc_old = self._enc_of(kind, key)
            cache[ck] = hint
            return hint, enc_old
        enc = encode(ev.obj)
        self._enc_pending.pop(ck, None)  # full re-encode supersedes lazy
        cache[ck] = enc
        return enc, encode(ev.old) if ev.old is not None else None

    def _pump_log(self) -> None:
        """Drain the store's watch queues into the global ordered log.
        Partitioned servers tag each entry with its namespace shard
        (served shard-scoped by ``/watch?shard=``, stripped from the
        wire); single-shard servers append exactly the historical entry
        shape."""
        moved = False
        sharded = self.shards > 1
        for kind, q in self._queues.items():
            while q:
                ev = q.popleft()
                self._dirty_kinds.add(kind)
                self._alloc_seq(1)
                self._log_rows += 1
                enc_obj, enc_old = self._encode_event_obj(kind, ev)
                entry = {
                    "seq": self.seq,
                    "kind": kind,
                    "type": ev.type.value,
                    "object": enc_obj,
                    "old": enc_old,
                }
                if sharded:
                    from volcano_tpu.store.partition import shard_of_key

                    entry["shard"] = shard_of_key(
                        ev.obj.meta.key, self.shards
                    )
                self.log.append(entry)
                self._note_watermark(entry.get("shard", 0), self.seq)
                moved = True
        # with replication armed, beacons must NOT stamp here: _pump_log
        # runs between a verb's store mutation and its _wal_append, so a
        # beacon stamped now would ship BEFORE the record whose mutations
        # its digest already covers — the follower, applying in ship
        # order, would digest without those mutations and flag a false
        # divergence.  Repl leaders stamp post-ship (_wal_append) and on
        # the quiescent watch path instead.
        beaconed = self._maybe_beacon() if self.repl is None else False
        self._trim_log()
        # unconsumed hints (a no-op write that produced no event) must not
        # survive to describe some LATER mutation of the key
        if self._enc_hints:
            self._enc_hints.clear()
        if moved or beaconed:
            self.cond.notify_all()

    def watch_since(self, since: int, kinds, timeout: float,
                    shard: Optional[int] = None) -> Dict[str, Any]:
        """``shard`` (partitioned servers): serve only that shard's
        entries — the per-shard watch fan-out.  A shard-scoped watcher
        pays block expansion only for its own shard's segments, so
        fan-out cost divides by the shard count instead of every watcher
        expanding every cycle's blocks."""
        deadline = time.monotonic() + timeout
        strip = self.shards > 1
        with self.lock:
            # a quiescent server still beacons on the poll path, so a
            # watcher that drained a burst gets its seq-pinned checkpoint
            # without waiting for the next mutation to pump the log
            self._maybe_beacon()
            # gapped seq lines (procmesh shards) track the trim horizon
            # explicitly; dense servers keep the arithmetic horizon
            # (identical value, zero bookkeeping risk on the hot path)
            floor = (self._log_floor if self._gapped
                     else self.seq - self._log_rows)
            if since < floor or since > self._seq_hwm():
                # fell off the buffer — or the client's cursor is from
                # before a server restart: tell it to relist
                return self._watch_payload(
                    {"events": None, "next": self._seq_hwm(),
                     "relist": True})
            while True:
                log = self.log
                # entries' seq fields (a block entry carries its LAST
                # row's seq) are strictly increasing: binary-search the
                # first entry past the cursor instead of scanning
                lo, hi = 0, len(log)
                while lo < hi:
                    mid = (lo + hi) // 2
                    if log[mid]["seq"] > since:
                        hi = mid
                    else:
                        lo = mid + 1
                evs = []
                for e in log[lo:]:
                    # untagged entries (cross-shard segments from
                    # pre-partition clients) deliver to EVERY shard-
                    # scoped watcher — over-delivery, never a silent gap
                    if shard is not None and e.get("shard", shard) != shard:
                        continue
                    blk = e.get("block")
                    if blk is None:
                        # digest beacons bypass the kind filter: every
                        # watcher gets its verification checkpoints no
                        # matter which kinds it subscribed to
                        if (e["kind"] == vtaudit.BEACON_KIND
                                or not kinds or e["kind"] in kinds):
                            evs.append(
                                {k: v for k, v in e.items() if k != "shard"}
                                if strip else e
                            )
                        continue
                    if kinds and e["kind"] not in kinds:
                        continue
                    # columnar block: expand only the rows past the
                    # cursor (the expansion itself is memoized on the
                    # block — N watchers share one materialization)
                    n = e["n"]
                    first_seq = e["seq"] - n + 1
                    skip = since - first_seq + 1 if since >= first_seq else 0
                    start = e["start"]
                    evs.extend(blk.wire_rows(start + skip, start + n))
                if evs or timeout <= 0:
                    # ``next`` is the completeness watermark: dense
                    # servers stamp the local tail; procmesh shards stamp
                    # the global hwm — the per-shard watermark message
                    # that lets a merged cursor advance past seqs owned
                    # by sibling shards
                    return self._watch_payload(
                        {"events": evs, "next": self._seq_hwm()})
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return self._watch_payload(
                        {"events": [], "next": self._seq_hwm()})
                self.cond.wait(remaining)

    def _watch_payload(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Stamp the serving epoch onto a watch response — replicated
        servers only (unreplicated responses stay byte-identical).  The
        client fences on it: an epoch change mid-stream means the seq
        line may have forked (failover, snapshot resync), and the ONLY
        safe continuation is a relist (client.py turns it into one
        StaleWatch)."""
        if self.repl is not None:
            payload["epoch"] = self.repl.epoch
        return payload

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "StoreServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        if self.repl is not None:
            self.repl.start()
        return self

    def stop(self) -> None:
        if self.repl is not None:
            self.repl.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        self._saver_stop.set()
        if self._saver is not None:
            self._saver.join(timeout=5)
        self.flush_state()
        if self.wal is not None:
            # graceful shutdown fsyncs the tail even though the flush
            # above already checkpointed: a flush skipped by drop_flush
            # chaos (or an all-no-op dirty set) must still leave every
            # ACKed record durable
            self.wal.sync_close()

    def kill(self) -> None:
        """Crash-harness hook: die like SIGKILL.  Stop serving and drop
        every in-memory structure with NO final flush, NO saver drain,
        NO WAL fsync — what the next boot recovers is exactly what a
        killed process leaves behind: the last durable snapshot plus the
        synced WAL tail.  (The in-process crash storms in
        tests/test_crash_recovery.py pair this with a fresh StoreServer
        on the same state/wal paths and port.)"""
        self._killed = True
        self._saver_stop.set()
        if self.repl is not None:
            self.repl.stop()
        # drain any flush already past the _killed guard: its os.replace
        # must land BEFORE a successor boots on these paths, or a dead
        # life's older snapshot (older wal_floor) could clobber the
        # successor's checkpoint after it dropped the covered segments
        with self._flush_lock:
            pass
        if self._thread is not None:
            self.httpd.shutdown()
            self._thread.join(timeout=5)
        self.httpd.server_close()
        if self.wal is not None:
            self.wal.kill()

    def serve_forever(self) -> None:
        if self.repl is not None:
            self.repl.start()
        self.httpd.serve_forever()
