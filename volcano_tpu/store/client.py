"""RemoteStore: a Store-compatible client for the HTTP store server.

Every framework component takes a Store and uses exactly six verbs
(create/update/delete/get/list/watch), so pointing a SchedulerCache,
JobController, LeaderElector, or the CLI at a RemoteStore moves it into its
own OS process with no other changes — the client-go clientset+informer
role from the reference (SURVEY.md §2.2 "Generated clients"), collapsed
onto the same interface the in-process Store exposes.

Watch queues buffer locally and refill from the server's ordered event log
on demand (``popleft``/truthiness trigger a non-blocking poll), preserving
the deterministic drain-when-pumped model the controller and tests rely
on. A client that falls off the server's log buffer raises StaleWatch —
callers relist, the reference's "resourceVersion too old" recovery.
"""

from __future__ import annotations

import http.client
import json
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Any, Dict, List, Optional
from urllib.parse import quote

from volcano_tpu import trace, vtaudit
from volcano_tpu.admission import AdmissionError
from volcano_tpu.chaos import FaultPlan, env_plan
from volcano_tpu.store.codec import decode_object, encode, encode_fields
from volcano_tpu.store.store import Conflict, Event, EventType


class StaleWatch(RuntimeError):
    """The server dropped events this client never saw; relist required."""


class RemoteStoreError(RuntimeError):
    pass


class _RemoteWatchQueue:
    """deque façade over the client's event buffer for one kind."""

    def __init__(self, client: "RemoteStore", kind: str):
        self._client = client
        self._kind = kind
        self._buf: deque = deque()

    def popleft(self) -> Event:
        if not self._buf:
            self._client.poll()
        return self._buf.popleft()  # IndexError when empty, like deque

    def __len__(self) -> int:
        if not self._buf:
            self._client.poll()
        return len(self._buf)

    def __bool__(self) -> bool:
        return len(self) > 0

    def append(self, ev: Event) -> None:
        self._buf.append(ev)


def _connection_cut(e: BaseException) -> bool:
    """A connection-level transient — the request either never reached the
    server (refused/reset on connect) or the reply was cut mid-body — for
    which re-issuing an idempotent GET is always safe."""
    if isinstance(e, urllib.error.URLError) and not isinstance(
            e, urllib.error.HTTPError):
        reason = e.reason
        if isinstance(reason, BaseException):
            e = reason
    return isinstance(e, (
        ConnectionResetError, ConnectionRefusedError, BrokenPipeError,
        http.client.RemoteDisconnected, http.client.IncompleteRead,
        http.client.BadStatusLine,
    ))


def _never_sent(e: BaseException) -> bool:
    """True when the request provably never reached a server (connection
    refused on connect): the ONLY transient after which re-issuing a
    MUTATION is safe — anything cut later may have committed server-side,
    and blind re-issue would double-apply."""
    if isinstance(e, urllib.error.URLError) and not isinstance(
            e, urllib.error.HTTPError):
        reason = e.reason
        if isinstance(reason, BaseException):
            e = reason
    return isinstance(e, ConnectionRefusedError)


class RemoteStore:
    def __init__(self, url: str, timeout: float = 30.0,
                 chaos: Optional[FaultPlan] = None,
                 shard: Optional[int] = None,
                 peers: Optional[List[str]] = None):
        self.url = url.rstrip("/")
        self.timeout = timeout
        # replica set membership (store/replica.py): on a NotLeader
        # redirect or a dead endpoint, _refollow re-resolves the leader
        # across these URLs instead of failing the caller's cycle
        self.peers = [p.rstrip("/") for p in (peers or [])]
        #: serving epoch fence: adopted from watch responses; a change
        #: mid-stream (failover / follower resync) raises one StaleWatch
        self._epoch: Optional[int] = None
        # client-side fault injection (volcano_tpu/chaos.py): defaults to
        # the process-wide VOLCANO_TPU_CHAOS plan so daemon subprocesses
        # are torturable; None (the ambient case) costs one attribute
        # check per request
        self.chaos = chaos if chaos is not None else env_plan()
        self._watches: Dict[str, List[_RemoteWatchQueue]] = {}
        self._cursor = 0
        # shard-scoped watcher (partitioned servers): poll only that
        # shard's slice of the log — the per-shard watch fan-out consumer
        self.shard = shard
        #: partitioned-bus shard count advertised by /healthz, fetched
        #: lazily once (1 = unpartitioned, incl. pre-partition servers)
        self._segment_shards: Optional[int] = None
        #: procmesh shard map (leader URL per shard) advertised by a
        #: router's /healthz — lets this client ship each sub-segment
        #: STRAIGHT to its shard's process, skipping the router hop
        self._proc_map: Optional[List[str]] = None
        #: newest digest beacon seen on the watch stream (vtaudit): the
        #: seq-pinned checkpoint payload a mirror verifies against
        self.last_beacon: Optional[Dict[str, Any]] = None
        #: True iff that beacon was the FINAL event of the last non-empty
        #: poll batch — the quiescence signal a verifier needs (a beacon
        #: mid-batch pins a digest the consumer has already moved past)
        self.beacon_is_tail = False

    # -- http ----------------------------------------------------------------

    def _request(self, method: str, path: str, payload: Optional[dict] = None):
        """One verb round trip with leader re-resolution: a NotLeader
        421 (this endpoint is a follower replica) chases the redirect
        hint; a dead endpoint re-resolves across ``peers`` — GETs
        always, mutations only when the request provably never went out
        (connection refused).  ``resolve_leader`` inside ``_refollow``
        owns the decorrelated-jitter pacing."""
        try:
            code, body = self._request_once(method, path, payload)
        except (OSError, http.client.HTTPException) as e:
            if not self.peers or not (method == "GET" or _never_sent(e)):
                raise
            self._refollow(None)
            return self._request_once(method, path, payload)
        if (code == 421 and isinstance(body, dict)
                and body.get("error") == "NotLeader"
                and (self.peers or body.get("leader"))):
            self._refollow(body.get("leader"))
            return self._request_once(method, path, payload)
        return code, body

    def _refollow(self, hint: Optional[str]) -> None:
        """Point this client at the current leader: hint first (the 421's
        redirect), then every known peer.  Clears the cached shard count
        — the new endpoint may be partitioned differently."""
        urls = [hint.rstrip("/")] if hint else []
        urls += [u for u in (self.peers + [self.url]) if u not in urls]
        self.url = resolve_leader(urls, timeout=self.timeout)
        self._segment_shards = None
        self._proc_map = None

    def _request_once(self, method: str, path: str,
                      payload: Optional[dict] = None,
                      base: Optional[str] = None):
        data = json.dumps(payload).encode() if payload is not None else None
        headers = {"Content-Type": "application/json"} if data else {}
        if trace.TRACER is not None:
            # cross-daemon propagation: the active span context rides the
            # request so the server's request span continues this trace
            tid, sid = trace.current()
            if tid:
                headers[trace.HEADER] = trace.format_header(tid, sid)
        # idempotent verbs (GET: get/list/watch poll) retry ONCE on a
        # connection cut before surfacing the transient — the reference's
        # client-go does the same for safe verbs.  Mutations never retry
        # here: a cut PUT/POST may have committed server-side, and blind
        # re-issue would double-apply; their callers own that decision.
        attempts = 2 if method == "GET" else 1
        for attempt in range(attempts):
            try:
                if self.chaos is not None:
                    rule = self.chaos.fire("client.request", method=method,
                                           path=path)
                    if rule is not None:
                        if rule.action == "os_error":
                            raise ConnectionResetError(
                                "chaos: injected connection reset")
                        if rule.action == "delay":
                            time.sleep(rule.arg)
                req = urllib.request.Request(
                    (base or self.url) + path, data=data, method=method,
                    headers=headers,
                )
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    return resp.status, json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as e:
                try:
                    body = json.loads(e.read() or b"{}")
                except Exception:  # noqa: BLE001
                    body = {"error": str(e)}
                return e.code, body
            except (OSError, http.client.HTTPException) as e:
                if attempt + 1 < attempts and _connection_cut(e):
                    continue
                raise

    @staticmethod
    def _err(code: int, body: dict) -> str:
        return body.get("error", f"http {code}")

    # -- CRUD (Store interface) ------------------------------------------------

    def create(self, kind: str, obj: Any) -> Any:
        code, body = self._request("POST", f"/apis/{kind}", {"object": encode(obj)})
        if code == 422:
            raise AdmissionError(self._err(code, body))
        if code == 409:
            raise KeyError(self._err(code, body))
        if code != 201:
            raise RemoteStoreError(self._err(code, body))
        new = decode_object(kind, body["object"])
        # propagate server-stamped fields into the caller's object, which
        # stays live (Store.create mutates in place the same way)
        obj.meta.resource_version = new.meta.resource_version
        obj.meta.creation_timestamp = new.meta.creation_timestamp
        obj.meta.uid = new.meta.uid
        if kind == "Job":  # admission mutation (default queue/task names)
            obj.spec = new.spec
        return obj

    def update(self, kind: str, obj: Any, cas: Optional[int] = None) -> Any:
        path = f"/apis/{kind}" + (f"?cas={cas}" if cas is not None else "")
        code, body = self._request("PUT", path, {"object": encode(obj)})
        if code == 422:
            raise AdmissionError(self._err(code, body))
        if code == 404:
            raise KeyError(self._err(code, body))
        if code == 409 and body.get("conflict"):
            raise Conflict(self._err(code, body))
        if code != 200:
            raise RemoteStoreError(self._err(code, body))
        new = decode_object(kind, body["object"])
        obj.meta.resource_version = new.meta.resource_version
        return obj

    def update_cas(self, kind: str, obj: Any, expected_rv: int) -> Any:
        """Compare-and-swap update (Store.update_cas over the wire)."""
        return self.update(kind, obj, cas=expected_rv)

    def patch(self, kind: str, key: str, fields: Dict[str, Any],
              when: Optional[Dict[str, Any]] = None) -> Any:
        payload = {"fields": encode_fields(fields)}
        if when:
            payload["when"] = encode_fields(when)
        code, body = self._request(
            "PATCH", f"/apis/{kind}/obj?key={quote(key, safe='')}",
            payload,
        )
        if code == 404:
            raise KeyError(self._err(code, body))
        if code == 409:
            from volcano_tpu.store.store import PreconditionFailed

            raise PreconditionFailed(self._err(code, body))
        if code == 422:
            raise AdmissionError(self._err(code, body))
        if code != 200:
            raise RemoteStoreError(self._err(code, body))
        return decode_object(kind, body["object"])

    #: minimum consecutive same-shape patches worth a columnar op
    _COL_MIN_RUN = 16
    _COL_SCALARS = (str, int, float, bool, type(None))

    @classmethod
    def _compress_patch_runs(cls, wire: List[dict]) -> List[dict]:
        """Collapse runs of same-shape scalar-valued patch ops into ONE
        columnar ``patch_col`` op — keys + per-field value columns (or a
        single const for all-equal columns).  A cycle's bind batch
        ({"node_name": host} x 100k) and the bulk enqueue shipping (5k
        identical conditional phase flips) shrink to a keys array plus a
        column/const, cutting both wire bytes and the server's per-op
        dispatch.  Object-valued patches (whole status writes) stay per-op
        so the server never shares one decoded object across rows."""
        out: List[dict] = []
        i, n = 0, len(wire)
        while i < n:
            w = wire[i]
            fields = w.get("fields")
            if w["op"] != "patch" or not fields or not all(
                isinstance(v, cls._COL_SCALARS) for v in fields.values()
            ):
                out.append(w)
                i += 1
                continue
            names = tuple(sorted(fields))
            when = w.get("when")
            run = [w]
            j = i + 1
            while j < n:
                x = wire[j]
                xf = x.get("fields")
                if (
                    x["op"] != "patch" or x["kind"] != w["kind"]
                    or not xf or tuple(sorted(xf)) != names
                    or x.get("when") != when
                    or not all(
                        isinstance(v, cls._COL_SCALARS) for v in xf.values()
                    )
                ):
                    break
                run.append(x)
                j += 1
            if len(run) >= cls._COL_MIN_RUN:
                cols: Dict[str, list] = {}
                const: Dict[str, Any] = {}
                for f in names:
                    vals = [x["fields"][f] for x in run]
                    if all(v == vals[0] for v in vals):
                        const[f] = vals[0]
                    else:
                        cols[f] = vals
                cop: Dict[str, Any] = {
                    "op": "patch_col", "kind": w["kind"],
                    "keys": [x["key"] for x in run],
                }
                if cols:
                    cop["columns"] = cols
                if const:
                    cop["const"] = const
                if when is not None:
                    cop["when"] = when
                out.append(cop)
            else:
                out.extend(run)
            i = j
        return out

    def bulk(self, ops: List[Dict[str, Any]]) -> List[Optional[str]]:
        """Store.bulk over the wire: ONE round trip for N mutations (async
        decision application batches a cycle's binds/evicts through this).
        Ops carry live objects; they are encoded here, and homogeneous
        patch runs ship columnar (see _compress_patch_runs). Returns one
        error string (or None) per op, like Store.bulk."""
        # generic per-op encode: NON-decision traffic only (status/config
        # objects, conditional enqueue flips — themselves patch_col-
        # compressed below).  Cycle binds/evicts/Events never pass here:
        # they ship as one columnar segment via apply_segment, and the
        # columnar-publish lint keeps new decision loops out.
        wire = []
        for op in ops:
            w = {"op": op["op"], "kind": op["kind"]}
            if "object" in op:
                w["object"] = encode(op["object"])  # vtlint: disable=columnar-publish
            if "key" in op:
                w["key"] = op["key"]
            if "fields" in op:
                w["fields"] = encode_fields(op["fields"])  # vtlint: disable=columnar-publish
            if "when" in op:
                w["when"] = encode_fields(op["when"])  # vtlint: disable=columnar-publish
            if "cas" in op:
                w["cas"] = op["cas"]
            wire.append(w)
        wire = self._compress_patch_runs(wire)
        code, body = self._request("POST", "/bulk", {"ops": wire})
        if code != 200:
            raise RemoteStoreError(self._err(code, body))
        raw = body.get("results") or []
        results: List[Optional[str]] = []
        for w, r in zip(wire, raw):
            if w["op"] == "patch_col":
                if isinstance(r, list):
                    results.extend(r)  # per-key result list
                else:
                    # op-level failure (malformed const/when): one error
                    # string for the whole run — replicate per key, never
                    # iterate the string itself
                    results.extend([r] * len(w["keys"]))
            else:
                results.append(r)
        if len(raw) != len(wire) or len(results) != len(ops):
            raise RemoteStoreError(
                f"bulk returned {len(results)} results for {len(ops)} ops"
            )
        return results

    @property
    def segment_shards(self) -> int:
        """The server's partitioned-bus shard count (``/healthz``
        ``shards``), cached after the first read.  The async applier
        splits each cycle's segment by namespace shard and ships the
        sub-segments concurrently when this is > 1."""
        if self._segment_shards is None:
            code, body = self._request("GET", "/healthz")
            if code != 200:
                raise RemoteStoreError(self._err(code, body))
            self._segment_shards = max(1, int(body.get("shards", 1)))
            pm = body.get("shard_map") or []
            self._proc_map = ([str(u).rstrip("/") for u in pm]
                              if len(pm) == self._segment_shards else None)
        return self._segment_shards

    @property
    def proc_shard_map(self) -> Optional[List[str]]:
        """Leader URL per shard when this client points at a procmesh
        router (``/healthz`` ``shard_map``); None against in-process
        servers.  Cached with ``segment_shards`` and cleared together on
        a refollow — a new endpoint may be a different topology."""
        if self._segment_shards is None:
            _ = self.segment_shards  # primes both caches
        return self._proc_map

    def apply_segment(self, seg, shard: Optional[int] = None
                      ) -> Dict[str, Any]:
        """Ship one columnar decision segment (store/segment.py) in ONE
        request — the whole cycle's binds + evicts + their Events as
        parallel columns over interned string tables, no per-object op
        dicts and no per-object encode.  The server applies it under one
        lock with lazy materialization; on a partitioned server
        ``shard`` routes a sub-segment to its shard's apply lock, WAL,
        and watch log.  Returns the sparse per-row error dict
        ``{"binds": [[row, err], ...], "evicts": [...]}``; raises on
        transport failure (the caller never retries a mutation blindly —
        same contract as ``bulk``)."""
        op = seg.to_wire()
        if shard is not None:
            op["shard"] = int(shard)
        code, body = None, None
        if shard is not None:
            pm = self.proc_shard_map
            if pm and 0 <= int(shard) < len(pm):
                # procmesh: ship straight to the shard's own process —
                # the router hop buys nothing for an already-split
                # sub-segment.  A dead/demoted shard endpoint falls back
                # to the routed path ONLY when the direct attempt
                # provably never went out (connection refused) or came
                # back NotLeader — a cut mid-flight must surface, same
                # no-blind-retry contract as ``bulk``.
                try:
                    code, body = self._request_once(
                        "POST", "/bulk", {"ops": [op]}, base=pm[int(shard)])
                except (OSError, http.client.HTTPException) as e:
                    if not _never_sent(e):
                        raise
                    code = None
                if code == 421:
                    code = None
        if code is None:
            code, body = self._request("POST", "/bulk", {"ops": [op]})
        if code != 200:
            raise RemoteStoreError(self._err(code, body))
        res = (body.get("results") or [None])[0]
        if not isinstance(res, dict):
            # op-level failure: one error string for the whole segment
            raise RemoteStoreError(str(res) if res else "segment op dropped")
        return res

    def delete(self, kind: str, key: str) -> Optional[Any]:
        before = self.get(kind, key)
        code, body = self._request(
            "DELETE", f"/apis/{kind}/obj?key={quote(key, safe='')}"
        )
        if code != 200:
            raise RemoteStoreError(self._err(code, body))
        return before if body.get("deleted") else None

    def get(self, kind: str, key: str) -> Optional[Any]:
        code, body = self._request(
            "GET", f"/apis/{kind}/obj?key={quote(key, safe='')}"
        )
        if code == 404:
            return None
        if code != 200:
            raise RemoteStoreError(self._err(code, body))
        return decode_object(kind, body["object"])

    def list(self, kind: str) -> List[Any]:
        code, body = self._request("GET", f"/apis/{kind}")
        if code != 200:
            raise RemoteStoreError(self._err(code, body))
        return [decode_object(kind, item) for item in body["items"]]

    def items(self, kind: str):
        return iter(self.list(kind))

    @property
    def resource_version(self) -> int:
        """The server's event sequence — monotonic like Store.resource_version."""
        code, body = self._request("GET", "/watch?since=-1&timeout=0")
        if code != 200:
            raise RemoteStoreError(self._err(code, body))
        return body["next"]

    @property
    def uid(self) -> Optional[str]:
        """The backing store's lineage id (Store.uid over the wire) — used
        by the mirror checkpoint to reject foreign-store restores."""
        code, body = self._request("GET", "/healthz")
        if code != 200:
            raise RemoteStoreError(self._err(code, body))
        return body.get("uid")

    # -- watch -----------------------------------------------------------------

    def watch(self, kind: str) -> _RemoteWatchQueue:
        if not self._watches:
            # informer semantics: watches deliver events from now on; the
            # subscriber lists current state itself (list+watch). Pinning
            # the cursor here keeps the server's historical log from being
            # replayed into a fresh client.
            self._cursor = self.resource_version
        q = _RemoteWatchQueue(self, kind)
        if kind not in self._watches:
            self._watches[kind] = []
        self._watches[kind].append(q)
        return q

    def poll(self, timeout: float = 0.0) -> int:
        """Fetch events after the cursor and fan out to local queues.
        Returns the number of events received."""
        if not self._watches:
            return 0
        kinds = ",".join(sorted(self._watches))
        shard_arg = f"&shard={self.shard}" if self.shard is not None else ""
        code, body = self._request(
            "GET",
            f"/watch?since={self._cursor}&kinds={kinds}&timeout={timeout}"
            f"{shard_arg}",
        )
        if code != 200:
            raise RemoteStoreError(self._err(code, body))
        # serving-epoch fence (replicated servers stamp one): an epoch
        # change mid-stream — failover promotion, or this follower
        # snapshot-resyncing under us — means the seq line may have
        # forked, so the cursor is meaningless: ONE StaleWatch relist,
        # then the stream continues incrementally under the new epoch
        ep = body.get("epoch")
        epoch_changed = (ep is not None and self._epoch is not None
                         and ep != self._epoch)
        if ep is not None:
            self._epoch = ep
        if body.get("relist") or epoch_changed:
            self._cursor = body["next"]
            raise StaleWatch("watch cursor fell off the server log; relist")
        events = body.get("events") or []
        for i, e in enumerate(events):
            if e["kind"] == vtaudit.BEACON_KIND:
                # digest beacon: a seq-pinned audit checkpoint, not an
                # object event — intercept before decode_object (which
                # has no class for it) and record whether it closed the
                # batch (the verifier's quiescence gate)
                self.last_beacon = e.get("digest")
                self.beacon_is_tail = i == len(events) - 1
                continue
            ev = Event(
                kind=e["kind"],
                type=EventType(e["type"]),
                obj=decode_object(e["kind"], e["object"]),
                old=decode_object(e["kind"], e["old"]) if e.get("old") else None,
                # the wire encoding rides along so an audit consumer can
                # fold it into its digest table without re-encoding
                enc=e["object"],
            )
            for q in self._watches.get(e["kind"], []):
                q.append(ev)
            self.beacon_is_tail = False
        self._cursor = max(self._cursor, body.get("next", self._cursor))
        return len(events)

    def pending_events(self) -> bool:
        self.poll()
        return any(q._buf for qs in self._watches.values() for q in qs)


def wait_healthy(url: str, timeout: float = 30.0,
                 request_timeout: float = 2.0,
                 require_leader: bool = False) -> bool:
    """Deadline-bounded readiness probe: poll ``GET /healthz`` with
    jittered backoff until the server answers or ``timeout`` passes.
    Returns whether the server came up — the one health-wait the daemons
    and tests share instead of ad-hoc polling loops.  With
    ``require_leader``, a healthy FOLLOWER replica keeps the poll going
    (its role can flip to leader mid-wait on a promotion); servers that
    advertise no role (unreplicated) count as leaders."""
    from volcano_tpu.backoff import Backoff

    store = RemoteStore(url, timeout=request_timeout)
    deadline = time.monotonic() + timeout
    bo = Backoff(base=0.05, cap=1.0)
    while True:
        try:
            code, body = store._request_once("GET", "/healthz")
            if code == 200 and (
                not require_leader
                or body.get("role", "leader") == "leader"
            ):
                return True
        except (OSError, http.client.HTTPException):
            pass
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return False
        time.sleep(min(bo.next(), remaining))


def resolve_leader(urls: List[str], timeout: float = 30.0,
                   request_timeout: float = 2.0) -> str:
    """The URL currently serving as leader among ``urls``: short
    per-candidate ``wait_healthy(require_leader=True)`` probes in order,
    decorrelated-jitter pacing between rounds (an election takes a lease
    window to settle — every redirected writer re-probing in lockstep is
    the herd the Backoff contract exists to break)."""
    from volcano_tpu.backoff import Backoff

    deadline = time.monotonic() + max(timeout, request_timeout)
    bo = Backoff(base=0.05, cap=1.0)
    while True:
        for u in urls:
            if wait_healthy(u, timeout=request_timeout,
                            request_timeout=request_timeout,
                            require_leader=True):
                return u.rstrip("/")
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise RemoteStoreError(f"no leader among {urls}")
        time.sleep(min(bo.next(), remaining))
