"""SeqBus — the mesh's shared seq/rv line, one counter pair for N
shard processes.

The partitioned bus (PR 11) kept global ordering trivially: every shard
lived in one process and seq/rv assignment happened under one server
lock.  Splitting shards into their own OS processes removes that lock,
but the ordering contract survives because it never needed the lock —
it needs ONE monotone allocation line.  SeqBus is that line: two 64-bit
counters (log seq, store rv) in shared memory, advanced under a single
cross-process mutex.

The completeness invariant routers and merged watches build on:

* A shard server allocates (``alloc_seq``) and appends the covered log
  entry while holding ITS OWN server lock (server.py ``_alloc_seq``),
  so per shard, allocation and append are atomic.
* Therefore, when anyone observes the counter at S (``peek_seq``),
  every seq <= S is either (a) already appended on the shard that owns
  it, or (b) owned by a shard currently inside that atomic section —
  and reading a shard's stream UNDER its lock (any watch request) can
  never miss a seq <= the peek taken inside that same lock hold.  That
  peek is the watermark a shard stamps on its watch/feed replies.

Crash/restart: the counters only move forward.  A restarted shard CASes
the line up to whatever its recovery produced (``advance_to``) — if the
line already ran ahead (siblings kept allocating), its recovered tail
simply sits below the current mark, exactly like a shard that has been
idle.  The supervisor owns the shared memory, so shard deaths never
take the line with them.
"""

from __future__ import annotations

import multiprocessing
from typing import Tuple

_SEQ, _RV = 0, 1


class SeqBus:
    """Cross-process seq/rv allocator.  Picklable only via
    ``multiprocessing.Process`` argument inheritance (the shared array
    travels as an OS handle) — exactly how the supervisor hands it to
    shard processes."""

    def __init__(self, ctx=None):
        ctx = ctx or multiprocessing.get_context("spawn")
        # one synchronized array = one mutex guarding both counters
        self._line = ctx.Array("q", [0, 0])

    # -- allocation (shard servers, under their own server lock) -----------

    def alloc_seq(self, n: int) -> int:
        """Consume ``n`` seqs; returns the LAST of the block (the caller
        derives ``last - n + 1 .. last``).  ``n == 0`` reads the line."""
        with self._line.get_lock():
            self._line[_SEQ] += int(n)
            return self._line[_SEQ]

    def alloc_rv(self, n: int) -> int:
        """Consume ``n`` resource versions; returns the LAST one."""
        with self._line.get_lock():
            self._line[_RV] += int(n)
            return self._line[_RV]

    # -- observation --------------------------------------------------------

    def peek_seq(self) -> int:
        with self._line.get_lock():
            return self._line[_SEQ]

    def snapshot(self) -> Tuple[int, int]:
        """(seq, rv) — one consistent read of both counters."""
        with self._line.get_lock():
            return self._line[_SEQ], self._line[_RV]

    # -- recovery ------------------------------------------------------------

    def advance_to(self, seq: int, rv: int) -> None:
        """CAS the line forward to at least (seq, rv) — a recovering
        shard rejoining the mesh.  Never moves backward: siblings may
        have consumed past the recovered tail while this shard was
        down."""
        with self._line.get_lock():
            if int(seq) > self._line[_SEQ]:
                self._line[_SEQ] = int(seq)
            if int(rv) > self._line[_RV]:
                self._line[_RV] = int(rv)
