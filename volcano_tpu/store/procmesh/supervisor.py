"""Shard supervisor: one OS process per store shard, one logical bus.

The partitioned server (PR 11) proved the decision stream shards
cleanly by namespace hash; vtflow's interprocedural pass (PR 17) fenced
the last cross-shard writes behind an explicit watermark protocol.
This module takes the final step: each shard becomes its OWN
``StoreServer(shards=1)`` process, reusing its existing per-shard WAL
directory (``partition.shard_wal_dir`` — the exact layout ShardedWAL
appends), its own state snapshot slice, and the vtrepl feed machinery
unchanged (a shard leader is just a replica group of size >= 1).

The supervisor owns the pieces the shards must share:

* the ``SeqBus`` — the cross-process seq/rv line (seqbus.py), created
  here so shard deaths never take the counters with them;
* stable ports — allocated up front, so a restarted shard rebinds the
  SAME endpoint and the router/shard map stays valid across crashes;
* the monitor thread — respawns any dead member on the same config
  (same state file, same WAL dir, same port); recovery replays the
  shard's WAL tail and CASes the line forward (``advance_to``), so a
  SIGKILLed shard rejoins with zero acked loss while its siblings keep
  allocating.

Replication composes per shard: ``replicas >= 2`` gives every shard a
sync follower (its own state/WAL paths, suffixed ``.rN`` so they never
match ``leftover_shard_dirs``'s cross-mode absorb scan).  The lease is
long (10 s) relative to a supervisor restart (~1 s) by design: the
supervisor IS the failover authority for mesh shards — the follower
exists for durability and read scale, promotion is the fallback for a
supervisor that is itself gone.
"""

from __future__ import annotations

import json
import multiprocessing
import os
import queue
import signal
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from volcano_tpu import timeseries, vtfleet
from volcano_tpu.locksan import make_lock
from volcano_tpu.scheduler import metrics
from volcano_tpu.store.partition import shard_of, shard_wal_dir
from volcano_tpu.store.procmesh.seqbus import SeqBus


def shard_state_path(state: str, shard: int, replica: int = 0) -> str:
    """The snapshot file one mesh member owns (``<state>.s01``,
    follower ``<state>.s01.r1``) — beside the in-process snapshot, never
    colliding with it."""
    p = f"{state}.s{int(shard):02d}"
    return p if replica == 0 else f"{p}.r{int(replica)}"


def _member_wal_dir(wal_root: str, shard: int, replica: int = 0) -> str:
    """Leader shards own the exact ShardedWAL directory (``<wal>/s01``)
    so in-process and procmesh deployments recover each other's acked
    tails; follower dirs carry an ``.rN`` suffix that the cross-mode
    absorb scan (``leftover_shard_dirs``: ``s\\d\\d`` exactly) ignores."""
    d = shard_wal_dir(wal_root, shard)
    return d if replica == 0 else f"{d}.r{int(replica)}"


def _shard_main(cfg: Dict[str, Any], bus, ready_q) -> None:
    """Child-process entry (module-level: spawn pickles the reference).
    One ``StoreServer(shards=1)`` — leaders allocate on the shared bus,
    followers mirror their leader's stamps via the feed exactly as in
    single-process replication.  Mirrors ``daemons.run_apiserver``'s
    shutdown shape: SIGTERM -> SystemExit on the serving thread, final
    flush with the signal masked (SIGKILL is what the WAL recovers
    from)."""
    import sys

    from volcano_tpu import trace
    from volcano_tpu.store.server import StoreServer

    name = f"shard{cfg['shard']:02d}"
    if cfg["replica"]:
        name += f".r{cfg['replica']}"
    trace.set_component(name)
    signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))
    srv = StoreServer(
        host=cfg["host"],
        port=cfg["port"],
        state_path=cfg["state"],
        save_interval=cfg["save_interval"],
        wal=cfg["wal"],
        shards=1,
        repl=cfg["repl"],
        seq_bus=bus if cfg["replica"] == 0 else None,
        proc_shard=(cfg["shard"], cfg["nshards"]),
    )
    try:
        ready_q.put({"shard": cfg["shard"], "replica": cfg["replica"],
                     "port": srv.port, "pid": os.getpid()})
    except (OSError, ValueError):
        pass  # supervisor gone/queue closed: serve anyway, health probes rule
    try:
        srv.serve_forever()
    finally:
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        srv.stop()


class _Member:
    """One supervised process: shard leader (``replica == 0``) or
    follower.  The config dict is immutable across restarts — that is
    the restart contract (same paths, same port, same role)."""

    __slots__ = ("cfg", "proc", "restarts")

    def __init__(self, cfg: Dict[str, Any]):
        self.cfg = cfg
        self.proc = None
        self.restarts = 0

    @property
    def url(self) -> str:
        return f"http://{self.cfg['host']}:{self.cfg['port']}"


class ShardSupervisor:
    """Spawn/monitor/restart N shard-server processes behind one
    logical store.  ``state``/``wal`` are the SAME roots the in-process
    ``shards=N`` server uses — ``start()`` splits an in-process
    snapshot into per-shard slices on first boot, and each shard's WAL
    directory is the one ShardedWAL already appends, so the two
    deployment modes hand the store back and forth."""

    def __init__(self, nshards: int, host: str = "127.0.0.1",
                 state: Optional[str] = None, wal: Optional[str] = None,
                 save_interval: float = 0.25, replicas: int = 1,
                 repl_ack: str = "sync", lease_duration: float = 10.0,
                 restart: bool = True, ready_timeout: float = 60.0):
        if nshards < 1:
            raise ValueError("procmesh needs >= 1 shard")
        self.nshards = int(nshards)
        self.host = host
        self.state = state or None
        self.wal = wal or None
        self.save_interval = save_interval
        self.replicas = max(1, int(replicas))
        self.repl_ack = repl_ack
        self.lease_duration = lease_duration
        self.restart = restart
        self.ready_timeout = ready_timeout
        if self.replicas > 1 and not (self.state and self.wal):
            raise ValueError("per-shard replication requires state and wal "
                             "roots: the feed ships fsynced WAL records")
        if self.wal and not self.state:
            raise ValueError("wal requires state (the WAL checkpoints into "
                             "the shard snapshots)")
        self._ctx = multiprocessing.get_context("spawn")
        self.bus = SeqBus(self._ctx)
        self._ready_q = self._ctx.Queue()
        #: members in spawn order: shard-major, leader before followers
        self.members: List[_Member] = []
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._mu = make_lock("ShardSupervisor.members")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ShardSupervisor":
        ports = self._alloc_ports(self.nshards * self.replicas)
        self._seed_shard_states()
        for s in range(self.nshards):
            group = [
                f"http://{self.host}:{ports[s * self.replicas + r]}"
                for r in range(self.replicas)
            ]
            for r in range(self.replicas):
                repl = None
                if self.replicas > 1:
                    repl = {
                        "identity": group[r],
                        "peers": list(group),
                        "leader": None if r == 0 else group[0],
                        "ack": self.repl_ack,
                        "lease_duration": self.lease_duration,
                        # one lease object per shard GROUP, shard-
                        # qualified: each group's lease lives in its own
                        # shard store, and the merged /apis/Lease list
                        # must keep them distinct keys or the wire
                        # digest diverges from the shard-root rollup
                        "lease_name": f"vt-store-s{s:02d}",
                    }
                self.members.append(_Member({
                    "shard": s,
                    "replica": r,
                    "nshards": self.nshards,
                    "host": self.host,
                    "port": ports[s * self.replicas + r],
                    "state": (shard_state_path(self.state, s, r)
                              if self.state else None),
                    "wal": (_member_wal_dir(self.wal, s, r)
                            if self.wal else None),
                    "save_interval": self.save_interval,
                    "repl": repl,
                }))
        for m in self.members:
            self._spawn(m)
        self._await_ready(len(self.members))
        self._wait_members_healthy()
        col = vtfleet.COLLECTOR
        if col is not None:
            # armed: cache a BASELINE snapshot of every member before
            # the monitor takes over — a member killed within the first
            # tick must still yield an incident bundle with a real ring
            self._harvest_round(col)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="procmesh-monitor", daemon=True,
        )
        self._monitor.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._mu:
            procs = [m.proc for m in self.members if m.proc is not None]
        for p in procs:
            if p.is_alive():
                p.terminate()
        deadline = time.monotonic() + 10.0
        for p in procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
            if p.is_alive():
                p.kill()
                p.join(timeout=5.0)
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        self._ready_q.close()

    # -- shard map / status --------------------------------------------------

    @property
    def shard_map(self) -> List[str]:
        """Leader URL per shard, mesh order — the routing table clients
        and the router fetch (ports are stable across restarts, so this
        list is valid for the supervisor's whole life)."""
        return [m.url for m in self.members if m.cfg["replica"] == 0]

    def status(self) -> Dict[str, Any]:
        with self._mu:
            members = [{
                "shard": m.cfg["shard"],
                "replica": m.cfg["replica"],
                "role": "leader" if m.cfg["replica"] == 0 else "follower",
                "url": m.url,
                "pid": m.proc.pid if m.proc is not None else None,
                "alive": bool(m.proc is not None and m.proc.is_alive()),
                "restarts": m.restarts,
            } for m in self.members]
        seq, rv = self.bus.snapshot()
        return {
            "shards": self.nshards,
            "replicas": self.replicas,
            "seq": seq,
            "rv": rv,
            "restarts": sum(m["restarts"] for m in members),
            "members": members,
        }

    # -- crash harness -------------------------------------------------------

    def kill_shard(self, shard: int, replica: int = 0) -> int:
        """SIGKILL one member (the chaos/crash-storm hook) and return
        the killed pid.  The monitor respawns it on the same config; the
        acked-loss contract is the WAL's."""
        m = self._member(shard, replica)
        if m.proc is None or not m.proc.is_alive():
            raise RuntimeError(f"shard {shard} replica {replica} not running")
        pid = m.proc.pid
        os.kill(pid, signal.SIGKILL)
        return pid

    def _member(self, shard: int, replica: int = 0) -> _Member:
        for m in self.members:
            if m.cfg["shard"] == shard and m.cfg["replica"] == replica:
                return m
        raise KeyError(f"no member for shard {shard} replica {replica}")

    # -- internals -----------------------------------------------------------

    def _spawn(self, m: _Member) -> None:
        p = self._ctx.Process(
            target=_shard_main,
            args=(m.cfg, self.bus, self._ready_q),
            name=f"vt-shard{m.cfg['shard']:02d}-r{m.cfg['replica']}",
            daemon=True,
        )
        p.start()
        m.proc = p
        # structural lifecycle events: every spawn/respawn lands in the
        # supervisor's time-series ring (vtctl top renders them) and
        # flips the liveness gauge; the metrics registry records
        # unconditionally, timeseries.record is a free no-op disarmed
        timeseries.record(
            "proc", event="respawn" if m.restarts else "spawn",
            shard=m.cfg["shard"], replica=m.cfg["replica"], pid=p.pid)
        metrics.update_proc_up(m.cfg["shard"], True,
                               replica=m.cfg["replica"])

    def _await_ready(self, n: int) -> None:
        deadline = time.monotonic() + self.ready_timeout
        got = 0
        while got < n:
            budget = deadline - time.monotonic()
            if budget <= 0:
                raise RuntimeError(
                    f"procmesh: {got}/{n} shard processes ready after "
                    f"{self.ready_timeout:.0f}s")
            try:
                self._ready_q.get(timeout=min(1.0, budget))
                got += 1
            except queue.Empty:
                continue  # loop re-budgets against the deadline

    def _wait_members_healthy(self) -> None:
        from volcano_tpu.store.client import wait_healthy

        for m in self.members:
            # followers answer /healthz too (reads are local); a member
            # that never comes up fails the whole start
            if not wait_healthy(m.url, timeout=self.ready_timeout):
                raise RuntimeError(f"procmesh: {m.url} never became healthy")

    def _harvest_round(self, col) -> None:
        """One fleet-collector refresh pass over the live members."""
        with self._mu:
            live = [(vtfleet.member_name(m.cfg["shard"],
                                         m.cfg["replica"]), m.url)
                    for m in self.members
                    if m.proc is not None and m.proc.is_alive()]
        for name, url in live:
            col.harvest_member(name, url)

    def _monitor_loop(self) -> None:
        while not self._stop.wait(0.2):
            # drain restart-time ready messages so the queue never fills
            try:
                while True:
                    self._ready_q.get_nowait()
            except queue.Empty:
                pass  # drained
            col = vtfleet.COLLECTOR
            if col is not None:
                # armed-only periodic harvest: cache every live member's
                # forensics surfaces so a member that dies THIS tick
                # still yields an incident bundle with its final rings
                self._harvest_round(col)
            with self._mu:
                dead = [m for m in self.members
                        if m.proc is not None and not m.proc.is_alive()]
            for m in dead:
                if self._stop.is_set() or not self.restart:
                    break
                dead_pid = m.proc.pid
                m.proc.join(timeout=1.0)
                m.restarts += 1
                shard, replica = m.cfg["shard"], m.cfg["replica"]
                timeseries.record("proc", event="exit", shard=shard,
                                  replica=replica, pid=dead_pid,
                                  exitcode=m.proc.exitcode)
                metrics.update_proc_up(shard, False, replica=replica)
                metrics.register_proc_restart(shard, replica=replica)
                if col is not None:
                    # crash forensics BEFORE the respawn reuses the port:
                    # the bundle is the member's last harvested snapshot
                    # (its "final" trace ring/profile — the process is
                    # already gone)
                    col.incident(
                        vtfleet.member_name(shard, replica),
                        {"pid": dead_pid, "shard": shard,
                         "replica": replica, "exitcode": m.proc.exitcode,
                         "restarts": m.restarts, "reason": "proc-exit"})
                # same config, same port, same paths: recovery replays
                # the shard's WAL tail and advance_to() rejoins the line
                self._spawn(m)

    def _alloc_ports(self, n: int) -> List[int]:
        """Reserve n distinct free ports up front.  Sockets are held
        open until ALL are allocated (so the OS cannot hand the same
        port twice), then released just before the children bind —
        the standard pre-bind race, narrow enough for a test harness
        and irrelevant for production (explicit ports)."""
        socks = []
        try:
            for _ in range(n):
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind((self.host, 0))
                socks.append(s)
            return [s.getsockname()[1] for s in socks]
        finally:
            for s in socks:
                s.close()

    def _seed_shard_states(self) -> None:
        """First boot on an in-process snapshot: split ``<state>`` into
        per-shard slices by namespace hash.  Each slice keeps the global
        seq/rv stamps (the line is shared; a shard's local counters may
        sit below it, exactly like an idle shard) and takes the matching
        per-shard ``wal_floor`` when the in-process life stamped a floor
        list.  Never overwrites an existing shard snapshot — those are
        newer than the in-process file by construction."""
        if not self.state or not os.path.exists(self.state):
            return
        targets = [shard_state_path(self.state, s)
                   for s in range(self.nshards)]
        if all(os.path.exists(t) for t in targets):
            return
        with open(self.state) as f:
            data = json.load(f)
        kinds = data.get("kinds", {})
        per_kinds: List[Dict[str, List[Any]]] = [
            {} for _ in range(self.nshards)
        ]
        for kind, items in kinds.items():
            for enc in items:
                meta = enc.get("meta") or {}
                s = shard_of(str(meta.get("namespace") or ""), self.nshards)
                per_kinds[s].setdefault(kind, []).append(enc)
        floor_raw = data.get("wal_floor")
        floors = floor_raw if isinstance(floor_raw, list) else None
        for s, target in enumerate(targets):
            if os.path.exists(target):
                continue
            payload: Dict[str, Any] = {
                "seq": int(data.get("seq", 0)),
                "rv": int(data.get("rv", 0)),
                # distinct lineage uid per shard: two servers must never
                # claim the same store uid to mirrors/checkpoints
                "store_uid": f"{data.get('store_uid', '')}.s{s:02d}",
                "kinds": per_kinds[s],
            }
            if floors is not None and s < len(floors):
                payload["wal_floor"] = int(floors[s])
            if data.get("repl_epoch"):
                payload["repl_epoch"] = int(data["repl_epoch"])
            tmp = f"{target}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, target)
