"""procmesh — per-shard OS processes behind one logical store.

ROADMAP item 1's final form.  The partitioned bus (store/partition.py)
sharded the decision stream by namespace hash inside ONE process; this
package deploys each shard as its own ``StoreServer(shards=1)`` process
while keeping every cross-shard contract the in-process bus already
proved:

* ``seqbus.SeqBus`` — the shared seq/rv line (two counters in shared
  memory) whose lock-coupled allocation gives every shard's watch reply
  a sound completeness watermark;
* ``supervisor.ShardSupervisor`` — spawns/monitors/restarts the shard
  processes on stable ports, splits an in-process snapshot into
  per-shard slices on first boot, and reuses the EXACT ShardedWAL
  directory layout so the two deployment modes hand the store back and
  forth; per-shard replica groups (vtrepl) ride along unchanged;
* ``router.ShardRouter`` — one URL for legacy clients: merged ``/watch``
  (byte-identical to the single-process stream), fan-out lists, routed
  writes, and the vtaudit digest rollup that keeps ``vtctl audit``
  working against a mesh.

Mesh-aware clients skip the router: ``RemoteStore`` reads the shard map
from ``/healthz`` and ships each namespace shard's traffic straight to
its process.
"""

from volcano_tpu.store.procmesh.router import ShardRouter
from volcano_tpu.store.procmesh.seqbus import SeqBus
from volcano_tpu.store.procmesh.supervisor import (
    ShardSupervisor, shard_state_path,
)

__all__ = [
    "SeqBus",
    "ShardRouter",
    "ShardSupervisor",
    "shard_state_path",
]
